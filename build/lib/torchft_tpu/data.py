"""Dataset sharding across elastic replica groups.

Analog of the reference sampler (reference: torchft/data.py:24-77): the global
data-parallel world is ``num_replica_groups * num_replicas`` and this worker
owns global shard ``rank + num_replicas * replica_rank``.  Sharding is *lossy
by design* under membership change — when a replica group dies its shard is
simply not consumed that step; exact-once data accounting is delegated to a
stateful loader checkpointed through the Manager state dict.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np


class DistributedSampler:
    """Index sampler assigning this worker a fixed global shard.

    Args:
        dataset_len: number of examples (or a Sized dataset).
        replica_rank: which replica group this worker belongs to.
        num_replica_groups: total replica groups in the job.
        rank: this worker's rank within the replica group.
        num_replicas: workers per replica group.
        shuffle: reshuffle each epoch with a deterministic seed.
        seed: base seed shared by all workers.
    """

    def __init__(
        self,
        dataset_len: "int | Sequence",
        replica_rank: int,
        num_replica_groups: int,
        rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = False,
        seed: int = 0,
    ) -> None:
        if not isinstance(dataset_len, int):
            dataset_len = len(dataset_len)
        if not (0 <= rank < num_replicas):
            raise ValueError(f"invalid rank {rank}, must be in [0, {num_replicas})")
        if not (0 <= replica_rank < num_replica_groups):
            raise ValueError(
                f"invalid replica_rank {replica_rank}, must be in [0, {num_replica_groups})"
            )
        self.dataset_len = dataset_len
        self.global_rank = rank + num_replicas * replica_rank
        self.global_world_size = num_replicas * num_replica_groups
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        # ceil-divide so every rank yields the same number of indices
        self.num_samples = -(-dataset_len // self.global_world_size)
        self.total_size = self.num_samples * self.global_world_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_len)
        else:
            indices = np.arange(self.dataset_len)
        # pad to total_size by wrapping, then take a strided shard
        if self.total_size > len(indices):
            pad = np.resize(indices, self.total_size - len(indices))
            indices = np.concatenate([indices, pad])
        shard = indices[self.global_rank : self.total_size : self.global_world_size]
        return iter(shard.tolist())


class StatefulDistributedSampler(DistributedSampler):
    """DistributedSampler with data-position checkpointing.

    The reference defers exact data accounting to torchdata's
    StatefulDataLoader (reference data.py docstring); this sampler carries
    the position natively: ``state_dict()/load_state_dict()`` capture
    (epoch, position) so a healed replica resumes its shard where the
    cohort left off. Register through the Manager::

        manager.register_state_dict_fn(
            "sampler", sampler.load_state_dict, sampler.state_dict)

    Accounting contract: ``position`` counts indices *handed to the
    consumer*, advancing at ``next()``. Resume is exact when each batch is
    drawn and trained within the same committed step; a loader that
    prefetches across step boundaries hands out indices before they are
    trained, so a checkpoint would overcount by the in-flight depth —
    either keep prefetch within the step or checkpoint the loader's
    in-flight count alongside.

    At epoch end the position stays at ``num_samples`` (so an end-of-epoch
    checkpoint is distinguishable from a fresh epoch and resumes to an
    empty remainder); ``set_epoch`` starts the next epoch at 0.
    """

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._position = 0

    def state_dict(self) -> dict:
        """Checkpointable progress: {epoch, position-within-epoch}."""
        return {"epoch": self.epoch, "position": self._position}

    def load_state_dict(self, sd: dict) -> None:
        self.epoch = int(sd["epoch"])
        self._position = int(sd["position"])

    def set_epoch(self, epoch: int) -> None:
        super().set_epoch(epoch)
        self._position = 0

    @property
    def remaining(self) -> int:
        """Indices left in the current epoch (``__len__`` stays the stable
        per-epoch constant)."""
        return max(self.num_samples - self._position, 0)

    def __iter__(self):
        shard = list(super().__iter__())
        start = self._position

        def gen():
            for i, idx in enumerate(shard[start:], start=start):
                self._position = i + 1
                yield idx

        return gen()
