"""Fault-tolerant optimizer wrapper (optax).

Analog of the reference OptimizerWrapper (reference: torchft/optim.py:48-55):
the step boundary hooks the FT protocol — ``begin_step`` (the zero_grad
analog) starts the quorum; ``step`` applies the optax update only if
``should_commit`` votes yes.  Functional JAX adaptation: instead of mutating
module parameters, ``step`` returns the (possibly unchanged) new
``(params, opt_state, committed)``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import optax

from torchft_tpu.manager import Manager


class OptimizerWrapper:
    """Wraps an optax GradientTransformation with the Manager protocol.

    Usage::

        opt = OptimizerWrapper(manager, optax.adamw(3e-4))
        opt_state = opt.init(params)
        ...
        opt.begin_step()                       # starts quorum (zero_grad analog)
        grads = grad_fn(params, batch)
        avg = manager.allreduce(grads).wait()
        params, opt_state, committed = opt.step(params, avg, opt_state)
    """

    def __init__(self, manager: Manager, optimizer: optax.GradientTransformation) -> None:
        self._manager = manager
        self._optimizer = optimizer

    def init(self, params: Any) -> Any:
        return self._optimizer.init(params)

    def begin_step(self) -> None:
        """Start the new step's quorum (reference: zero_grad -> start_quorum)."""
        self._manager.start_quorum()

    # torch-API-compatible alias
    zero_grad = begin_step

    def step(
        self, params: Any, grads: Any, opt_state: Any
    ) -> "Tuple[Any, Any, bool]":
        """Apply the update iff the group votes to commit.

        Returns ``(params, opt_state, committed)`` — unchanged on a failed
        commit so the step is retried on consistent state.
        """
        if not self._manager.should_commit():
            return params, opt_state, False
        updates, new_opt_state = self._optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt_state, True
