"""Compute ops: quantized collectives, Pallas kernels, SP attention.

Heavy modules (jax/pallas) import lazily via their submodules:

- ``torchft_tpu.ops.quantization`` — host int8 wire codec
- ``torchft_tpu.ops.pallas_quant`` — fused device quantize/dequant/reduce
- ``torchft_tpu.ops.collectives`` — quantized allreduce / reduce-scatter
- ``torchft_tpu.ops.ring_attention`` — ring (context-parallel) attention
- ``torchft_tpu.ops.ulysses`` — all-to-all sequence parallelism
"""
