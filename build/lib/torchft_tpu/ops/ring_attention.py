"""Ring attention: context-parallel causal attention over a mesh axis.

Long-context scaling for the TPU framework.  The reference has no
context-parallel code (SURVEY §2.3 — verified absent in zhengchenyu/torchft);
this is a TPU-first capability, not a port: sequence is sharded over a mesh
axis ("cp"), K/V chunks rotate around the ring with ``jax.lax.ppermute``
(riding ICI neighbor links), and each device accumulates its output with a
flash-attention-style online softmax (running max + rescaled partial sums) so
nothing materializes the full [T, T] score matrix.

Per ring step each device computes one [Tq_local, Tk_local] tile on the MXU
(bf16 inputs, fp32 accumulation) while the next K/V chunk is in flight —
`lax.scan` keeps the loop compiler-friendly (static trip count = ring size).

Used standalone via :func:`ring_attention` (a `jax.shard_map` wrapper) or
inside a larger shard_mapped step via :func:`ring_attention_local`.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30
_warned_dense: set = set()


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    use_flash: "Optional[bool]" = None,
) -> jax.Array:
    """Per-shard ring attention body. Must run inside shard_map over
    ``axis_name``; q/k/v are local sequence chunks ``[B, T_local, H, D]``
    (already rotary-embedded with *global* positions by the caller).

    GQA: K/V may carry fewer heads (``H % H_kv == 0``); they rotate around
    the ring *unexpanded* (H/H_kv fewer ppermute bytes) and are broadcast
    up to the query heads only inside each tile's einsum.

    Returns the local output chunk ``[B, T_local, H, D]`` in q's dtype.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    hkv = k.shape[2]
    if h % hkv != 0:
        raise ValueError(f"query heads {h} not a multiple of kv heads {hkv}")
    # Long-context fast path: when the local chunks are lane-aligned, run
    # the fused Pallas kernel per (Q x visiting-KV) tile instead of
    # materializing [T_local, T_local] scores (flash x ring composition;
    # identical contract, bwd re-rotates against the global logsumexp).
    # ``use_flash=False`` opts out — required inside partial-auto shard_map
    # contexts (the pipeline), where pallas_call's missing vma annotation
    # is rejected.
    if use_flash is None:
        use_flash = tq % 128 == 0 and tk % 128 == 0
    if use_flash:
        from torchft_tpu.ops.flash_attention import ring_flash_local

        return ring_flash_local(q, k, v, axis_name, causal)
    idx = jax.lax.axis_index(axis_name)
    size = jax.lax.axis_size(axis_name)
    rep = h // hkv
    scale = 1.0 / math.sqrt(d)

    def step(carry, s):
        o, m, l, kc, vc = carry
        kv_idx = (idx - s) % size
        kr, vr = kc, vc
        if rep > 1:
            kr = jnp.repeat(kr, rep, axis=2)
            vr = jnp.repeat(vr, rep, axis=2)
        # [B, H, Tq, Tk] tile on the MXU in the input dtype, fp32
        # accumulate (see dense_attention: bf16 inputs are the fast path;
        # the running softmax statistics stay f32 regardless).
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q, kr, preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal:
            q_pos = idx * tq + jnp.arange(tq)
            k_pos = kv_idx * tk + jnp.arange(tk)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        if causal:
            # A fully-masked tile (kv chunk strictly in the future) would
            # otherwise contribute exp(0)=1 per entry.
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd",
            p.astype(q.dtype),
            vr,
            preferred_element_type=jnp.float32,
        )
        # Rotate K/V one hop around the ring (neighbor ppermute -> ICI).
        perm = [(i, (i + 1) % size) for i in range(size)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, m_new, l, kc, vc), None

    # Derive the accumulators from q so they carry q's full device-varying
    # axis set (shard_map vma tracking): fresh jnp.zeros would be axis-
    # invariant and mismatch the scan carry's output type.
    zq = jnp.zeros_like(q, dtype=jnp.float32).transpose(0, 2, 1, 3)  # [B,H,Tq,D]
    o0 = zq
    m0 = zq[..., 0] + _NEG_INF
    l0 = zq[..., 0]
    (o, _, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(size)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """Plain (single-pass) causal attention over the full sequence,
    ``[B, T, H, D]`` — the cp=1 path; XLA shards it via constraint
    propagation (batch/head parallel). GQA: K/V with fewer heads are
    broadcast up to the query head count.

    Materializes the full ``[B, H, T, T]`` score matrix — O(T^2) HBM.
    Warns once per (B, H, T) at trace time beyond 4k context; use
    ``attn_impl='ring'`` (or 'ulysses') for long sequences."""
    d = q.shape[-1]
    t_full = q.shape[1]
    if t_full > 4096:
        key = (q.shape[0], q.shape[2], t_full)
        if key not in _warned_dense:
            _warned_dense.add(key)
            score_gb = q.shape[0] * q.shape[2] * t_full * t_full * 4 / 1024**3
            logging.getLogger(__name__).warning(
                "dense_attention at T=%d materializes a [%d, %d, %d, %d] f32 "
                "score matrix (~%.1f GiB); use attn_impl='ring' or 'ulysses' "
                "for long context",
                t_full, q.shape[0], q.shape[2], t_full, t_full, score_gb,
            )
    if k.shape[2] != q.shape[2]:
        if q.shape[2] % k.shape[2] != 0:
            raise ValueError(
                f"query heads {q.shape[2]} not a multiple of kv heads {k.shape[2]}"
            )
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # Matmuls run in the INPUT dtype with f32 accumulation
    # (preferred_element_type): bf16 activations hit the MXU's fast path
    # (measured 1.14x whole-step at d1024; hard-casting to f32 ran the
    # FLOP-dominant einsums at the slow f32 rate), while f32 activations
    # (the test configs) stay bitwise-f32 throughout.  Softmax statistics
    # are always f32.
    scores = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        / math.sqrt(d)
    )
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bhqd", p.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def sharded_attention(
    local_fn,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "cp",
    causal: bool = True,
    batch_axes: "Optional[tuple]" = None,
    head_axis: "Optional[str]" = None,
    may_use_pallas: bool = False,
) -> jax.Array:
    """Shared shard_map wrapper for sequence-parallel attention bodies.

    q/k/v: global ``[B, T, H, D]`` with T sharded over ``axis_name``.
    ``batch_axes``/``head_axis`` name the mesh axes B and H are sharded over
    (so shard_map's in_specs match the arrays' actual layout). ``local_fn``
    is a per-shard body with the ring/ulysses signature.
    """
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = jax.shard_map(
        functools.partial(local_fn, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # vma validation stays ON except when the body may lower to
        # pallas_call (flash ring tiles), whose out_shape carries no vma
        # annotation
        check_vma=not may_use_pallas,
    )
    return fn(q, k, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "cp",
    causal: bool = True,
    batch_axes: "Optional[tuple]" = None,
    head_axis: "Optional[str]" = None,
) -> jax.Array:
    """shard_map'd ring attention over ``mesh`` axis ``axis_name``
    (see :func:`sharded_attention` for the layout contract)."""
    size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    t_local = q.shape[1] // size
    return sharded_attention(
        ring_attention_local, q, k, v, mesh, axis_name, causal,
        batch_axes, head_axis,
        may_use_pallas=t_local % 128 == 0,
    )
