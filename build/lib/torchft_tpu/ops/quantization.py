"""Row-scaled 8-bit quantization for bandwidth-reduced DCN collectives.

Analog of the reference's fused quantization kernels
(reference: torchft/quantization.py:44-686): per-row absmax scales, int8
payload, and scales interleaved into one flat comm buffer; dequant-reduce-
requant fuses the reduction.  The reference targets fp8e4nv on SM90 with an
int8 fallback; the DCN payloads here are int8 (numpy has no fp8), matching
the reference's fallback format (:30-41).

Two implementations share the wire format:
- host path (numpy) used by the TCP/DCN collective layer below;
- device path (jax / Pallas TPU kernel, torchft_tpu.ops.pallas_quant) for
  quantizing on-chip before the host copy — see fused_* wrappers there.

Wire layout per array: ``[rows x f32 scale][rows x cols int8]`` flattened.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

INT8_MAX = 127.0


def _as_rows(a: np.ndarray) -> np.ndarray:
    """View as 2-D (rows, cols): leading dim preserved, rest flattened."""
    if a.ndim == 0:
        return a.reshape(1, 1)
    if a.ndim == 1:
        return a.reshape(1, -1)
    return a.reshape(a.shape[0], -1)


def quantize(a: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row absmax int8 quantization -> (scales f32 [rows], payload int8).

    Memory-bandwidth-bound on big arrays (the DCN host path quantizes
    ~GB-scale pseudograd fragments), so the hot loop is pass-minimal:
    multiply by the reciprocal scale (division is the slow ufunc), round
    in place, and skip the clip — absmax scaling bounds every product to
    [-127, 127] by construction (1-ulp excursions round back to 127).
    """
    rows = _as_rows(np.asarray(a, dtype=np.float32))
    absmax = np.abs(rows).max(axis=1)
    # Rows with absmax below 127/f32max would overflow the reciprocal to
    # inf (inf*0 = NaN payload); values that tiny (< ~3.7e-37) carry no
    # quantizable signal, so such rows encode as exact zeros (scale 1.0),
    # same as all-zero rows.
    nonzero = absmax > INT8_MAX / np.finfo(np.float32).max
    scales = np.where(nonzero, absmax / INT8_MAX, 1.0).astype(np.float32)
    inv = np.divide(
        INT8_MAX, absmax, out=np.ones_like(absmax), where=nonzero
    ).astype(np.float32)
    tmp = rows * inv[:, None]
    np.rint(tmp, out=tmp)
    payload = tmp.astype(np.int8)
    return scales, payload


def dequantize(
    scales: np.ndarray, payload: np.ndarray, shape: "Tuple[int, ...]", dtype: np.dtype
) -> np.ndarray:
    # one fused int8 x f32 -> f32 pass; asarray avoids the astype copy
    # when dtype is already float32 (the common DCN case)
    out = np.multiply(payload, scales[:, None], dtype=np.float32)
    return np.asarray(out.reshape(shape), dtype=dtype)


def pack(scales: np.ndarray, payload: np.ndarray) -> np.ndarray:
    """Interleave scales + payload into one uint8 comm buffer
    (reference quantization.py:54-165 packs fp8 payload + f32 scales)."""
    return np.concatenate([scales.view(np.uint8).ravel(), payload.view(np.uint8).ravel()])


def unpack(buf: np.ndarray, rows: int, cols: int) -> Tuple[np.ndarray, np.ndarray]:
    """Split a packed wire buffer back into (scales, payload).

    Returns VIEWS into ``buf`` (zero-copy): every consumer immediately
    widens the payload in its own f32 pass, so a defensive copy here would
    only add a full memory pass at GB fragment scale."""
    scale_bytes = rows * 4
    scales = buf[:scale_bytes].view(np.float32)
    payload = buf[scale_bytes : scale_bytes + rows * cols].view(np.int8).reshape(rows, cols)
    return scales, payload


def reduce_quantized(
    bufs: "List[np.ndarray]",
    rows: int,
    cols: int,
    average_by: int = 0,
    requantize: bool = True,
) -> np.ndarray:
    """Dequantize each packed buffer, accumulate in f32, requantize.

    Analog of the reference's fused dequant-accumulate-requant kernel
    (reference quantization.py:262-430). ``average_by > 0`` divides the
    accumulated sum (AVG fusion). ``requantize=False`` returns the raw f32
    accumulator (for results that stay local rather than going back on the
    wire).
    """
    acc: "np.ndarray | None" = None
    for buf in bufs:
        scales, payload = unpack(buf, rows, cols)
        # fused int8 x f32 -> f32 product in one pass; first buffer becomes
        # the accumulator directly (no zeros pass, no first add)
        prod = np.multiply(payload, scales[:, None], dtype=np.float32)
        if acc is None:
            acc = prod
        else:
            acc += prod
    if acc is None:
        acc = np.zeros((rows, cols), dtype=np.float32)
    if average_by > 0:
        acc /= average_by
    if not requantize:
        return acc
    return pack(*quantize(acc))
