"""Pallas TPU kernels for fused 8-bit quantization.

Device-side analog of the reference's fused Triton kernels
(reference: torchft/quantization.py:54-430): per-row absmax scale
computation fused with int8 quantization, dequantization, and
dequant-accumulate-requant reduction.  Shares the wire format of the host
path (torchft_tpu/ops/quantization.py): int8 payload + one f32 scale per
row, ``scale = absmax/127`` (1.0 for all-zero rows), round-half-even.

The reference targets fp8e4nv on SM90 with an int8 fallback
(reference quantization.py:30-41); TPU VPUs have no fp8 compute path worth
taking for a comm codec, so int8 — the reference's fallback format and the
format the DCN wire expects — is the single payload type here.

Use: quantize gradients on-chip *before* the device→host copy that feeds
the TCP/DCN collective, cutting host-transfer and wire bytes ~4x; dequant
on-chip after.  All wrappers fall back to interpreter mode off-TPU so tests
run on CPU.

Layout notes (see /opt/skills/guides/pallas_guide.md): rows are tiled in
blocks of 32 sublanes (int8 min tile), columns padded to the 128-lane
boundary.  Scales are carried as an (rows, 128) f32 block column-broadcast
inside the kernel and sliced to (rows,) on the host side — keeping every
ref layout-legal without scalar-memory gymnastics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INT8_MAX = 127.0
_ROW_TILE = 32  # int8 min sublane tile
_LANE = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _pad2d(x: jax.Array, rows: int, cols: int) -> jax.Array:
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    if pr or pc:
        x = jnp.pad(x, ((0, pr), (0, pc)))
    return x


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _quantize_kernel(x_ref, scales_ref, payload_ref):
    """Per-row absmax scale + int8 quantize, one fused pass over the block.

    Mirrors reference quantization.py:44-165 (scale compute fused into the
    quantize kernel); zero rows get scale 1.0 so dequant is exact.
    """
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0.0, absmax / INT8_MAX, 1.0)
    scales_ref[:] = jnp.broadcast_to(scale, scales_ref.shape)
    q = jnp.clip(jnp.round(x / scale), -INT8_MAX, INT8_MAX)
    payload_ref[:] = q.astype(jnp.int8)


def _dequantize_kernel(scales_ref, payload_ref, out_ref):
    scale = scales_ref[:, :1]
    out_ref[:] = payload_ref[:].astype(jnp.float32) * scale


def _reduce_kernel(scales_ref, payloads_ref, inv_ref, out_scales_ref, out_payload_ref):
    """Fused dequant → accumulate(f32) → optional average → requantize.

    Analog of reference quantization.py:262-430.  The block carries all
    world-size shards (leading axis); world sizes on the elastic replica
    dim are small, so the whole stack fits VMEM alongside one row tile.
    """
    scales = scales_ref[:, :, :1].astype(jnp.float32)  # (n, rows, 1)
    deq = payloads_ref[:].astype(jnp.float32) * scales  # (n, rows, cols)
    acc = jnp.sum(deq, axis=0) * inv_ref[0]  # (rows, cols)
    absmax = jnp.max(jnp.abs(acc), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0.0, absmax / INT8_MAX, 1.0)
    out_scales_ref[:] = jnp.broadcast_to(scale, out_scales_ref.shape)
    q = jnp.clip(jnp.round(acc / scale), -INT8_MAX, INT8_MAX)
    out_payload_ref[:] = q.astype(jnp.int8)


# ---------------------------------------------------------------------------
# host-callable wrappers
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("interpret",))
def _quantize_2d(x: jax.Array, interpret: bool) -> Tuple[jax.Array, jax.Array]:
    rows, cols = x.shape
    pr = _cdiv(rows, _ROW_TILE) * _ROW_TILE
    pc = _cdiv(cols, _LANE) * _LANE
    xp = _pad2d(x.astype(jnp.float32), pr, pc)
    grid = (pr // _ROW_TILE,)
    scales, payload = pl.pallas_call(
        _quantize_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((pr, _LANE), jnp.float32),
            jax.ShapeDtypeStruct((pr, pc), jnp.int8),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_ROW_TILE, pc), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((_ROW_TILE, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_ROW_TILE, pc), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(xp)
    return scales[:rows, 0], payload[:rows, :cols]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dequantize_2d(scales: jax.Array, payload: jax.Array, interpret: bool) -> jax.Array:
    rows, cols = payload.shape
    pr = _cdiv(rows, _ROW_TILE) * _ROW_TILE
    pc = _cdiv(cols, _LANE) * _LANE
    sp = jnp.pad(scales.astype(jnp.float32), (0, pr - rows))
    sp = jnp.broadcast_to(sp[:, None], (pr, _LANE))
    pp = _pad2d(payload, pr, pc)
    grid = (pr // _ROW_TILE,)
    out = pl.pallas_call(
        _dequantize_kernel,
        out_shape=jax.ShapeDtypeStruct((pr, pc), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_ROW_TILE, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_ROW_TILE, pc), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (_ROW_TILE, pc), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(sp, pp)
    return out[:rows, :cols]


@functools.partial(jax.jit, static_argnames=("average_by", "interpret"))
def _reduce_2d(
    scales: jax.Array,
    payloads: jax.Array,
    average_by: int,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    n, rows, cols = payloads.shape
    pr = _cdiv(rows, _ROW_TILE) * _ROW_TILE
    pc = _cdiv(cols, _LANE) * _LANE
    sp = jnp.pad(scales.astype(jnp.float32), ((0, 0), (0, pr - rows)))
    sp = jnp.broadcast_to(sp[:, :, None], (n, pr, _LANE))
    pp = jnp.pad(payloads, ((0, 0), (0, pr - rows), (0, pc - cols)))
    inv = jnp.array([1.0 / average_by if average_by > 0 else 1.0], jnp.float32)
    grid = (pr // _ROW_TILE,)
    out_scales, out_payload = pl.pallas_call(
        _reduce_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((pr, _LANE), jnp.float32),
            jax.ShapeDtypeStruct((pr, pc), jnp.int8),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (n, _ROW_TILE, _LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (n, _ROW_TILE, pc), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((_ROW_TILE, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_ROW_TILE, pc), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(sp, pp, inv)
    return out_scales[:rows, 0], out_payload[:rows, :cols]


def _as_rows(a) -> jax.Array:
    """View as 2-D (rows, cols) — same convention as the host codec."""
    a = jnp.asarray(a)
    if a.ndim == 0:
        return a.reshape(1, 1)
    if a.ndim == 1:
        return a.reshape(1, -1)
    return a.reshape(a.shape[0], -1)


def fused_quantize_into_int8(a) -> Tuple[jax.Array, jax.Array]:
    """Per-row absmax int8 quantization on device.

    Returns ``(scales f32 [rows], payload int8 [rows, cols])`` — bit-
    compatible with the host codec's ``quantize`` (same scales, same
    round-half-even payload), so a device-quantized buffer can be packed
    straight onto the DCN wire.
    """
    return _quantize_2d(_as_rows(a), interpret=_interpret())


def fused_dequantize_from_int8(scales, payload, shape=None, dtype=jnp.float32):
    """Inverse of :func:`fused_quantize_into_int8`; reshapes to ``shape``."""
    out = _dequantize_2d(jnp.asarray(scales), jnp.asarray(payload), interpret=_interpret())
    if shape is not None:
        out = out.reshape(shape)
    return out.astype(dtype)


def fused_reduce_int8(scales, payloads, average_by: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Fused dequant-accumulate-requant over stacked per-rank shards.

    Args:
        scales: f32 ``(n, rows)`` per-rank row scales.
        payloads: int8 ``(n, rows, cols)`` per-rank payloads.
        average_by: if > 0, divide the accumulated sum (AVG fusion,
            reference collectives.py:336-344).

    Returns requantized ``(scales [rows], payload [rows, cols])`` ready to
    go back on the wire.
    """
    return _reduce_2d(
        jnp.asarray(scales), jnp.asarray(payloads), int(average_by), _interpret()
    )


def quantize_pytree(tree):
    """Quantize every leaf of a pytree on device.

    Returns a pytree with the same structure whose leaves are
    ``(scales, payload)`` tuples from :func:`fused_quantize_into_int8`.
    """
    return jax.tree_util.tree_map(
        fused_quantize_into_int8, tree, is_leaf=lambda x: hasattr(x, "shape")
    )


__all__ = [
    "fused_quantize_into_int8",
    "fused_dequantize_from_int8",
    "fused_reduce_int8",
    "quantize_pytree",
]
