"""Ulysses (DeepSpeed-style) all-to-all sequence parallelism.

The second long-context strategy alongside ring attention (the reference
has neither — SURVEY §2.3; both are TPU-first capabilities, not ports).
Where ring attention rotates K/V chunks around the mesh axis (N-1 ppermute
steps, attention stays sequence-sharded), Ulysses re-shards once per
direction with ``jax.lax.all_to_all``: scatter heads / gather sequence, run
plain full-sequence attention on the local head group, then the inverse
all-to-all.

Trade-off (How-to-Scale-Your-Model framing): Ulysses moves 2 all-to-alls of
activations per attention call and needs ``heads % axis_size == 0``, but
each device then runs a single dense [T, T/head-group] attention — better
MXU utilization for moderate T and cheap on all-to-all-friendly ICI
topologies; ring keeps memory strictly local-T and overlaps compute with
neighbor transfers — better for extreme T. Both compose with the same
mesh/axis contract, so models can switch per config
(models/transformer.py ``attn_impl``).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from torchft_tpu.ops.ring_attention import dense_attention, sharded_attention


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
) -> jax.Array:
    """Per-shard Ulysses body. Must run inside shard_map over ``axis_name``;
    q/k/v are local sequence chunks ``[B, T_local, H, D]`` (rotary-embedded
    with *global* positions by the caller, same contract as ring attention).

    GQA: K/V may carry fewer heads; they cross the all-to-all *unexpanded*
    (H/H_kv fewer bytes) and are broadcast up inside the local attention.

    Requires both head counts divisible by ``axis_size``.
    Returns ``[B, T_local, H, D]``.
    """
    size = jax.lax.axis_size(axis_name)
    h, hkv = q.shape[2], k.shape[2]
    if h % size != 0 or hkv % size != 0:
        raise ValueError(
            f"ulysses attention needs query heads ({h}) and kv heads "
            f"({hkv}) divisible by the sequence-parallel axis size ({size})"
        )

    def seq_gather(x: jax.Array) -> jax.Array:
        # [B, T_local, H, D] -> [B, T_local*size, H/size, D]
        # split heads across the axis, concatenate sequence chunks in axis
        # order (contiguous sequence sharding => global order).
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def seq_scatter(x: jax.Array) -> jax.Array:
        # inverse: [B, T, H/size, D] -> [B, T_local, H, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qf, kf, vf = seq_gather(q), seq_gather(k), seq_gather(v)
    # dense_attention broadcasts GQA kv heads up locally (post-transfer)
    out = dense_attention(qf, kf, vf, causal=causal)
    return seq_scatter(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "cp",
    causal: bool = True,
    batch_axes: "Optional[tuple]" = None,
    head_axis: "Optional[str]" = None,
) -> jax.Array:
    """shard_map'd Ulysses attention over ``mesh`` axis ``axis_name``
    (same contract as :func:`ring_attention`; see
    :func:`torchft_tpu.ops.ring_attention.sharded_attention`)."""
    return sharded_attention(
        ulysses_attention_local, q, k, v, mesh, axis_name, causal,
        batch_axes, head_axis,
    )


__all__ = ["ulysses_attention", "ulysses_attention_local"]
