from torchft_tpu.utils.futures import (
    context_timeout,
    future_timeout,
    future_wait,
)
from torchft_tpu.utils.logging import ReplicaLogger, log_event, recent_events
from torchft_tpu.utils.rwlock import RWLock

__all__ = [
    "RWLock",
    "context_timeout",
    "future_timeout",
    "future_wait",
    "log_event",
    "recent_events",
    "ReplicaLogger",
]
