"""Checkpoint-transport throughput benchmarks.

Mirrors the reference's standalone bench harnesses
(reference: torchft/checkpointing/pg_transport_bench.py:15-95 and
http_transport_bench.py:13-55): build a large synthetic state dict, time
send/recv between two endpoints, report GB/s per phase.

    python -m torchft_tpu.checkpointing.transport_bench --gb 1.0
    python -m torchft_tpu.checkpointing.transport_bench --transport http \
        --gb 1.0 --chunks 8

The reference defaults to 12 GB; default here is 1 GB so the bench fits
CI-sized hosts — pass ``--gb 12`` for the reference-scale run.
"""

from __future__ import annotations

import argparse
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List

import numpy as np


def make_state_dict(total_bytes: int, leaf_mb: int = 64) -> "Dict[str, Any]":
    """Synthetic model-shaped state dict of f32 leaves (~``leaf_mb`` each)."""
    leaf_elems = leaf_mb * 1024 * 1024 // 4
    n_leaves = max(1, total_bytes // (leaf_elems * 4))
    rng = np.random.default_rng(0)
    return {
        f"layer_{i}": rng.standard_normal(leaf_elems).astype(np.float32)
        for i in range(n_leaves)
    }


def bench_http(gb: float, chunks: int) -> "Dict[str, float]":
    from torchft_tpu.checkpointing.http_transport import HTTPTransport

    state = make_state_dict(int(gb * 1024**3))
    nbytes = sum(v.nbytes for v in state.values())

    # warm in-place target: the live-training heal path receives into
    # existing (already-faulted) parameter buffers
    live = {k: np.zeros_like(v) for k, v in state.items()}

    sender = HTTPTransport(timeout=300.0, num_chunks=chunks)
    receiver = HTTPTransport(timeout=300.0, num_chunks=chunks)
    receiver_inplace = HTTPTransport(
        timeout=300.0, num_chunks=chunks, state_dict_fn=lambda: live
    )
    try:
        t0 = time.perf_counter()
        sender.send_checkpoint([1], step=1, state_dict=state, timeout=300.0)
        t_send = time.perf_counter() - t0

        t0 = time.perf_counter()
        got = receiver.recv_checkpoint(
            src_rank=0, metadata=sender.metadata(), step=1, timeout=300.0
        )
        t_recv = time.perf_counter() - t0
        assert set(got) == set(state)

        t0 = time.perf_counter()
        got = receiver_inplace.recv_checkpoint(
            src_rank=0, metadata=sender.metadata(), step=1, timeout=300.0
        )
        t_inplace = time.perf_counter() - t0
        assert set(got) == set(state)
        return {
            "stage_s": t_send,
            "recv_s": t_recv,
            "inplace_s": t_inplace,
            "gbps": nbytes / t_recv / 1024**3,
            "inplace_gbps": nbytes / t_inplace / 1024**3,
        }
    finally:
        sender.shutdown()
        receiver.shutdown()
        receiver_inplace.shutdown()


def bench_pg(gb: float) -> "Dict[str, float]":
    from torchft_tpu.checkpointing.pg_transport import PGTransport
    from torchft_tpu.coordination import StoreServer
    from torchft_tpu.parallel.process_group import ProcessGroupTCP

    state = make_state_dict(int(gb * 1024**3))
    nbytes = sum(v.nbytes for v in state.values())

    store = StoreServer()
    pgs = [ProcessGroupTCP(timeout=300.0) for _ in range(2)]
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [
            ex.submit(pgs[r].configure, f"{store.address()}/bench", f"r{r}", r, 2)
            for r in range(2)
        ]
        [f.result() for f in futs]
    # warm in-place target: the live-training heal path receives straight
    # into existing (already-faulted) parameter buffers via recv(out=...)
    live = {k: np.zeros_like(v) for k, v in state.items()}

    sender = PGTransport(pgs[0], timeout=300.0)
    receiver = PGTransport(pgs[1], timeout=300.0)
    receiver_inplace = PGTransport(
        pgs[1], timeout=300.0, state_dict_fn=lambda: live
    )
    try:
        def run(recv_transport) -> float:
            def send() -> None:
                sender.send_checkpoint(
                    [1], step=1, state_dict=state, timeout=300.0
                )

            def recv() -> "Dict[str, Any]":
                return recv_transport.recv_checkpoint(
                    src_rank=0, metadata=sender.metadata(), step=1, timeout=300.0
                )

            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=2) as ex:
                fs = ex.submit(send)
                fr = ex.submit(recv)
                got = fr.result(timeout=600)
                fs.result(timeout=600)
            assert set(got) == set(state)
            return time.perf_counter() - t0

        t_cold = run(receiver)
        t_inplace = run(receiver_inplace)
        return {
            "total_s": t_cold,
            "inplace_s": t_inplace,
            "gbps": nbytes / t_cold / 1024**3,
            "inplace_gbps": nbytes / t_inplace / 1024**3,
        }
    finally:
        for t in (sender, receiver, receiver_inplace):
            t.shutdown()
        for pg in pgs:
            pg.shutdown()
        store.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--transport", choices=("http", "pg", "both"), default="both")
    p.add_argument("--gb", type=float, default=1.0, help="state dict size in GiB")
    p.add_argument("--chunks", type=int, default=0,
                   help="HTTP: parallel chunk fetches (0 = single stream)")
    args = p.parse_args(argv)

    if args.transport in ("http", "both"):
        r = bench_http(args.gb, args.chunks)
        print(
            f"http  {args.gb:.1f} GiB chunks={args.chunks}: "
            f"stage {r['stage_s']:.2f}s  recv {r['recv_s']:.2f}s "
            f"({r['gbps']:.2f} GiB/s)  in-place recv {r['inplace_s']:.2f}s "
            f"({r['inplace_gbps']:.2f} GiB/s)"
        )
    if args.transport in ("pg", "both"):
        r = bench_pg(args.gb)
        print(
            f"pg    {args.gb:.1f} GiB: send+recv {r['total_s']:.2f}s "
            f"({r['gbps']:.2f} GiB/s)  in-place {r['inplace_s']:.2f}s "
            f"({r['inplace_gbps']:.2f} GiB/s)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
