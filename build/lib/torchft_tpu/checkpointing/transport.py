"""Checkpoint transport interface.

Parity with the reference ABC (reference: torchft/checkpointing/transport.py:14-68):
a transport moves a live state dict (pytree) from a healthy replica to a
recovering one, keyed by step.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Generic, List, TypeVar

T = TypeVar("T")


class CheckpointTransport(ABC, Generic[T]):
    @abstractmethod
    def metadata(self) -> str:
        """Transport-specific connection info shipped via the quorum
        (e.g. the HTTP endpoint peers fetch from)."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: "List[int]", step: int, state_dict: T, timeout: float
    ) -> None:
        """Make ``state_dict`` available to (or push it to) ``dst_ranks``."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> T:
        """Fetch the step's state dict from the source replica."""

    def disallow_checkpoint(self) -> None:
        """Stop serving the staged checkpoint (the state is about to mutate)."""

    def shutdown(self, wait: bool = True) -> None:
        """Release resources."""
