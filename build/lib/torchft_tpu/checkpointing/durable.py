"""Durable (on-disk) checkpoints for cold-start resume.

Live healing (HTTP/PG transports) covers the *partial* failure case — some
replicas die, peers hold the state.  Durable checkpoints cover the total
one: every replica died (preemption, maintenance), so on restart there is
no healthy peer to heal from and the job must resume from disk.  The
reference demonstrates this in its trainer: periodic ``torch.save`` of
``{model, optim}`` alongside ``manager.state_dict()``
(reference: train_ddp.py:201-208); here the same composite
``{"user": ..., "torchft": manager.state_dict()}`` pytree goes through the
transports' streaming serializer (checkpointing/serialization.py) so large
arrays are written without pickling copies.

Writes are atomic (tmp file + ``os.replace``) so a kill mid-save can never
corrupt the latest checkpoint, and old checkpoints are pruned to
``keep_last``.
"""

from __future__ import annotations

import os
import re
from typing import Any, List, Optional, Tuple

from torchft_tpu.checkpointing.serialization import (
    deserialize_from,
    reassemble,
    serialize_to,
)

_CKPT_RE = re.compile(r"^ckpt_step(\d+)\.tft$")


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_step{step}.tft")


def save_checkpoint(
    directory: str, step: int, state_dict: Any, keep_last: int = 2
) -> str:
    """Atomically write ``state_dict`` for ``step``; prune to ``keep_last``.

    Returns the checkpoint path.  The composite Manager layout
    (``{"user": ..., "torchft": {"step": ..., ...}}``) is conventional but
    not required — any pytree serializes.
    """
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        serialize_to(state_dict, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)

    if keep_last > 0:
        for old_step, old_path in list_checkpoints(directory)[:-keep_last]:
            if old_step != step:
                try:
                    os.remove(old_path)
                except OSError:
                    pass
    return path


def load_checkpoint(path: str) -> Any:
    with open(path, "rb") as f:
        return reassemble(*deserialize_from(f))


def list_checkpoints(directory: str) -> "List[Tuple[int, str]]":
    """All checkpoints in ``directory`` as (step, path), step-ascending."""
    if not os.path.isdir(directory):
        return []
    found = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(found)


def latest_checkpoint(directory: str) -> "Optional[str]":
    """Path of the highest-step checkpoint, or None."""
    ckpts = list_checkpoints(directory)
    return ckpts[-1][1] if ckpts else None
