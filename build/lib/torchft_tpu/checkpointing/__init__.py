from torchft_tpu.checkpointing.durable import (
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
    save_checkpoint,
)
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.pg_transport import PGTransport
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = [
    "CheckpointTransport",
    "HTTPTransport",
    "PGTransport",
    "latest_checkpoint",
    "list_checkpoints",
    "load_checkpoint",
    "save_checkpoint",
]
