"""Model families: flagship llama-style transformer + the reference's
example-scale CNN/MLP (reference train_ddp.py:84-102, train_diloco.py:76-120)."""

from torchft_tpu.models import cnn, mlp, transformer
from torchft_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    make_grad_step,
    make_train_step,
    param_specs,
    shard_params,
)

__all__ = [
    "cnn",
    "mlp",
    "transformer",
    "TransformerConfig",
    "init_params",
    "param_specs",
    "shard_params",
    "make_train_step",
    "make_grad_step",
]
