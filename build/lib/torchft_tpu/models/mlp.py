"""Small MLP — the reference's DiLoCo example model family
(reference: train_diloco.py:76-120 trains an MLP split into fragments).

Pure-functional JAX; the param dict's top-level keys double as DiLoCo
fragment boundaries (each layer is a fragment candidate, mirroring how the
reference splits with torch.distributed.pipelining SplitPoints)."""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_params(
    rng: jax.Array, sizes: "Sequence[int]" = (784, 128, 128, 10)
) -> Params:
    params: Params = {}
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, (n_in, n_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"layer_{i}"] = {
            "w": jax.random.normal(keys[i], (n_in, n_out), jnp.float32)
            / jnp.sqrt(n_in),
            "b": jnp.zeros((n_out,), jnp.float32),
        }
    return params


def forward(params: Params, x: jax.Array) -> jax.Array:
    n = len(params)
    for i in range(n):
        p = params[f"layer_{i}"]
        x = x @ p["w"] + p["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def fragment_keys(params: Params, n_fragments: int) -> "List[List[str]]":
    """Partition top-level param keys into n contiguous fragments (DiLoCo)."""
    keys = sorted(params.keys(), key=lambda k: int(k.rsplit("_", 1)[1]))
    base, rem = divmod(len(keys), n_fragments)
    out, start = [], 0
    for i in range(n_fragments):
        n = base + (1 if i < rem else 0)
        out.append(keys[start : start + n])
        start += n
    return out
