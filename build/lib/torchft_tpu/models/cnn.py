"""Small convnet — the reference's DDP example model family
(reference: train_ddp.py:84-102, a CIFAR10 CNN).

JAX-native: NHWC layout (TPU-preferred), `lax.conv_general_dilated` convs
so XLA tiles them onto the MXU."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_params(rng: jax.Array, num_classes: int = 10, channels: int = 3) -> Params:
    k = jax.random.split(rng, 4)

    def conv(key, kh, kw, cin, cout):
        fan_in = kh * kw * cin
        return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) / jnp.sqrt(
            fan_in
        )

    return {
        "conv1": {"w": conv(k[0], 3, 3, channels, 32), "b": jnp.zeros((32,))},
        "conv2": {"w": conv(k[1], 3, 3, 32, 64), "b": jnp.zeros((64,))},
        "fc1": {
            "w": jax.random.normal(k[2], (64 * 8 * 8, 128), jnp.float32) / 64.0,
            "b": jnp.zeros((128,)),
        },
        "fc2": {
            "w": jax.random.normal(k[3], (128, num_classes), jnp.float32) / 16.0,
            "b": jnp.zeros((num_classes,)),
        },
    }


def _conv2d(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + b


def _max_pool(x: jax.Array) -> jax.Array:
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def forward(params: Params, x: jax.Array) -> jax.Array:
    """x [B, 32, 32, C] NHWC -> logits [B, num_classes]."""
    x = jax.nn.relu(_conv2d(x, params["conv1"]["w"], params["conv1"]["b"]))
    x = _max_pool(x)
    x = jax.nn.relu(_conv2d(x, params["conv2"]["w"], params["conv2"]["b"]))
    x = _max_pool(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
    return x @ params["fc2"]["w"] + params["fc2"]["b"]
