"""Mixture-of-Experts FFN with expert parallelism (``ep`` mesh axis).

A TPU-first capability beyond the reference (which has no expert
parallelism — SURVEY §2.3): GShard-style capacity-based top-k routing
expressed entirely as dense one-hot einsums, so the whole layer is static-
shaped, jit-friendly, and MXU-resident. Experts are sharded over the ``ep``
mesh axis via sharding constraints on the ``[E, C, d]`` dispatch tensor —
XLA inserts the token all-to-alls; no hand-written collectives.

Routing: top-k (default 2) experts per token, probabilities renormalized
over the chosen k; per-expert capacity ``C = ceil(capacity_factor * N * k /
E)``; tokens past capacity are dropped (their combine weight is zero, so
the residual connection passes them through unchanged — standard GShard
semantics). The load-balance auxiliary loss (Switch/GShard ``E * Σ_e
fraction_tokens_e * mean_prob_e``) is returned for the trainer to add.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    ep_axis: str = "ep"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def init_moe_params(rng: jax.Array, cfg: MoEConfig, n_layers: int = 0) -> Params:
    """Expert + router weights; with ``n_layers`` > 0 a leading stacked
    layer dim is added (for `lax.scan` blocks)."""
    e, f, ne = cfg.d_model, cfg.d_ff, cfg.n_experts
    pd = cfg.param_dtype
    lead = (n_layers,) if n_layers else ()
    keys = jax.random.split(rng, 4)

    def dense(key, *shape):
        fan_in = shape[-2]
        return (jax.random.normal(key, shape, pd) / np.sqrt(fan_in)).astype(pd)

    return {
        "router": dense(keys[0], *lead, e, ne),
        "w_gate": dense(keys[1], *lead, ne, e, f),
        "w_up": dense(keys[2], *lead, ne, e, f),
        "w_down": dense(keys[3], *lead, ne, f, e),
    }


def moe_param_specs(cfg: MoEConfig, stacked: bool = False) -> Params:
    """PartitionSpecs: experts sharded over ep, inner dims over fsdp/tp."""
    lead = (None,) if stacked else ()
    ep, fs, tp = cfg.ep_axis, cfg.fsdp_axis, cfg.tp_axis
    return {
        "router": P(*lead, None, None),
        "w_gate": P(*lead, ep, fs, tp),
        "w_up": P(*lead, ep, fs, tp),
        "w_down": P(*lead, ep, tp, fs),
    }


def _capacity(n_tokens: int, cfg: MoEConfig) -> int:
    return max(
        1, math.ceil(cfg.capacity_factor * n_tokens * cfg.top_k / cfg.n_experts)
    )


def moe_ffn(
    x: jax.Array,
    params: Params,
    cfg: MoEConfig,
    mesh: "Optional[Mesh]" = None,
) -> "Tuple[jax.Array, jax.Array]":
    """MoE feed-forward: ``x [B, T, d] -> (y [B, T, d], aux_loss scalar)``.

    With a mesh, the ``[E, C, d]`` expert buffers get ``P(ep, ...)``
    sharding constraints so XLA dispatches tokens to expert shards over the
    ep axis (all-to-all on ICI).  ``mesh="manual"`` applies the constraint
    with a bare PartitionSpec — the form required inside a partial-manual
    shard_map (e.g. the pipeline), where ep stays automatic but a
    NamedSharding over the full mesh is rejected for mentioning manual
    axes.
    """
    b, t, d = x.shape
    n = b * t
    ne, k = cfg.n_experts, cfg.top_k
    cap = _capacity(n, cfg)
    act = cfg.dtype

    flat = x.reshape(n, d)
    logits = (
        flat.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    )  # [N, E] — routing in f32 always
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k assignment (distinct experts per token)
    _, top_idx = jax.lax.top_k(logits, k)  # [N, k]
    expert_masks = [
        jax.nn.one_hot(top_idx[:, kk], ne, dtype=jnp.float32) for kk in range(k)
    ]

    # renormalize gates over the chosen k
    gates = jnp.stack(
        [(probs * m).sum(axis=-1) for m in expert_masks], axis=0
    )  # [k, N]
    gates = gates / jnp.maximum(gates.sum(axis=0, keepdims=True), 1e-9)

    # position of each (token, choice) within its expert: earlier choices
    # get priority, then token order (GShard scheme). Counts in int32 —
    # f32 cumsum would collide capacity slots past 2^24 assignments.
    prev_per_expert = jnp.zeros((ne,), jnp.int32)
    dispatch = jnp.zeros((n, ne, cap), jnp.float32)
    combine = jnp.zeros((n, ne, cap), jnp.float32)
    for kk in range(k):
        mask = expert_masks[kk]  # [N, E]
        imask = mask.astype(jnp.int32)
        pos = jnp.cumsum(imask, axis=0) - 1 + prev_per_expert[None, :]
        prev_per_expert = prev_per_expert + imask.sum(axis=0)
        within = (pos < cap) & (imask > 0)
        pos_oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32)
        sel = jnp.where(within[..., None], pos_oh, 0.0)  # [N, E, C]
        dispatch = dispatch + sel
        combine = combine + sel * gates[kk][:, None, None]

    # dispatch tokens into per-expert buffers on the MXU
    expert_in = jnp.einsum(
        "nec,nd->ecd", dispatch, flat.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(act)
    if mesh is not None:
        spec = (
            P(cfg.ep_axis, None, None)
            if isinstance(mesh, str)
            else NamedSharding(mesh, P(cfg.ep_axis, None, None))
        )
        expert_in = jax.lax.with_sharding_constraint(expert_in, spec)

    wg = params["w_gate"].astype(act)
    wu = params["w_up"].astype(act)
    wd = params["w_down"].astype(act)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, wg)) * jnp.einsum(
        "ecd,edf->ecf", expert_in, wu
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, wd)
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(expert_out, spec)

    y = jnp.einsum(
        "nec,ecd->nd", combine, expert_out.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )

    # load-balance auxiliary loss (Switch eq. 4): E * sum_e f_e * p_e over
    # the FIRST choice (standard), where f_e = fraction of tokens routed
    fraction = expert_masks[0].mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = ne * jnp.sum(fraction * mean_prob)

    return y.reshape(b, t, d).astype(x.dtype), aux


def moe_ffn_reference(
    x: jax.Array, params: Params, cfg: MoEConfig
) -> jax.Array:
    """Brute-force per-token reference (no capacity drops): for tests."""
    b, t, d = x.shape
    flat = x.reshape(b * t, d).astype(jnp.float32)
    logits = flat @ params["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    _, top_idx = jax.lax.top_k(logits, cfg.top_k)
    out = jnp.zeros_like(flat)
    gates = jnp.take_along_axis(probs, top_idx, axis=-1)
    gates = gates / gates.sum(axis=-1, keepdims=True)

    def one_expert(e):
        wg = params["w_gate"][e].astype(jnp.float32)
        wu = params["w_up"][e].astype(jnp.float32)
        wd = params["w_down"][e].astype(jnp.float32)
        h = jax.nn.silu(flat @ wg) * (flat @ wu)
        return h @ wd

    all_out = jnp.stack([one_expert(e) for e in range(cfg.n_experts)])  # [E, N, d]
    for kk in range(cfg.top_k):
        idx = top_idx[:, kk]
        out = out + gates[:, kk:kk + 1] * jnp.take_along_axis(
            all_out, idx[None, :, None], axis=0
        )[0]
    return out.reshape(b, t, d).astype(x.dtype)


__all__ = [
    "MoEConfig",
    "init_moe_params",
    "moe_param_specs",
    "moe_ffn",
    "moe_ffn_reference",
]
