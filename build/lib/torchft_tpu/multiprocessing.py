"""Monitored subprocess pipes for crash-isolated workers.

Analog of the reference's ``_MonitoredPipe``
(reference: torchft/multiprocessing.py:10-31): a Connection wrapper whose
``recv`` polls with a deadline, re-raises exceptions that were sent through
the pipe, and turns a closed pipe into an ``EOFError`` — so a dead worker
subprocess surfaces as a clean, catchable failure in the parent instead of
a hang.
"""

from __future__ import annotations

import multiprocessing.connection as mp_conn
import time
from typing import Any, Optional


class _MonitoredPipe:
    """Poll-based pipe reader with timeout + exception passthrough."""

    def __init__(self, pipe: "mp_conn.Connection") -> None:
        self._pipe = pipe

    def send(self, obj: Any) -> None:
        self._pipe.send(obj)

    def recv(self, timeout: "Optional[float]" = None) -> Any:
        """Receive one object; raises it if it's an Exception.

        Raises TimeoutError if nothing arrives within ``timeout`` seconds,
        EOFError if the other end is closed.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError(f"pipe recv timed out after {timeout}s")
            if self._pipe.poll(min(remaining, 0.1) if remaining is not None else 0.1):
                obj = self._pipe.recv()  # raises EOFError on closed pipe
                if isinstance(obj, Exception):
                    raise obj
                return obj

    def close(self) -> None:
        self._pipe.close()

    def closed(self) -> bool:
        return self._pipe.closed
