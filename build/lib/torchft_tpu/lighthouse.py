"""Standalone Lighthouse CLI (reference: src/bin/lighthouse.rs:11-24 and the
``lighthouse_main`` entry in src/lib.rs:329-344).

Run one per job; point every replica group's Manager at it:

    python -m torchft_tpu.lighthouse --bind :29510 --min-replicas 2

Serves the quorum RPC protocol and the HTML dashboard (with per-replica
kill buttons and ``/status.json``) on the same port.
"""

from __future__ import annotations

import argparse
import signal
import threading

from torchft_tpu.coordination import LighthouseServer


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--bind", default=":29510", help="host:port (port 0 = ephemeral)")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--join-timeout-ms", type=int, default=60000,
                   help="straggler wait before forming a smaller quorum "
                        "(reference CLI default 60s)")
    p.add_argument("--quorum-tick-ms", type=int, default=100)
    p.add_argument("--heartbeat-timeout-ms", type=int, default=5000)
    args = p.parse_args(argv)

    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
    )
    print(f"lighthouse serving at {server.address()} "
          f"(dashboard: http://{server.address()}/)", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        server.shutdown()


if __name__ == "__main__":
    main()
