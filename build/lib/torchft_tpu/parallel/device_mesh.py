"""FT-aware device mesh composition (the HSDP story).

Analog of the reference's ManagedDeviceMesh (reference:
torchft/device_mesh.py:51-340) — but designed the JAX way.  The reference
must *lie* to torch's DeviceMesh (registering a fake world-size-1 backend)
because torch parallelism APIs demand every dim be a real process group.  In
JAX, inner parallelism (FSDP/TP/SP over ICI within a slice) is a
``jax.sharding.Mesh`` + pjit shardings, and the elastic replica dimension
lives *above* jit entirely: the FT allreduce runs on host gradients between
jitted steps.  So the composition is explicit rather than spoofed:

- ``ManagedDeviceMesh.mesh`` — the static inner mesh handed to pjit; its
  membership never changes (a slice is fault-free by assumption; if a chip
  dies, the whole replica group dies and heals as a unit).
- the replicate dim is virtual: ``num_participants`` / ``replica_rank`` are
  live quorum values used for loss scaling and data sharding.

Zero-fill + divide-by-participants keeps compiled shapes static, so
membership changes never trigger a re-jit (SURVEY §7 / reference
manager.py:416-417).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from torchft_tpu.manager import Manager


class ManagedDeviceMesh:
    """An inner JAX mesh plus the elastic FT replicate dimension.

    Args:
        manager: FT manager owning the replica dimension.
        mesh: inner ``jax.sharding.Mesh`` (ICI dims: fsdp/tp/sp/...).
        replicate_dim_name: name reported for the virtual FT dim.
    """

    def __init__(
        self,
        manager: Manager,
        mesh: "jax.sharding.Mesh",
        replicate_dim_name: str = "dp_replicate",
    ) -> None:
        self._manager = manager
        self.mesh = mesh
        self.replicate_dim_name = replicate_dim_name

    # -- virtual replicate dim (live quorum values) ------------------------

    def num_participants(self) -> int:
        return self._manager.num_participants()

    def replica_rank(self) -> "Optional[int]":
        return self._manager.participating_rank()

    def is_participating(self) -> bool:
        return self._manager.is_participating()

    # -- composed topology -------------------------------------------------

    @property
    def axis_names(self) -> "Tuple[str, ...]":
        return (self.replicate_dim_name,) + tuple(self.mesh.axis_names)

    def shape(self) -> "Dict[str, int]":
        """Axis sizes; the replicate dim reports the live participant count
        (>=1 during 0-participant init, mirroring reference :169-184)."""
        sizes = {self.replicate_dim_name: max(self.num_participants(), 1)}
        sizes.update(dict(zip(self.mesh.axis_names, self.mesh.devices.shape)))
        return sizes

    def global_batch_slice(self, global_batch_size: int) -> "Tuple[int, int]":
        """This replica's contiguous [start, end) share of the global batch,
        given the live quorum (DistributedSampler analog at batch level).

        Returns the empty slice (0, 0) while not participating (healing /
        no quorum yet) — defaulting to rank 0's slice would silently train
        on another replica's data."""
        rank = self.replica_rank()
        if rank is None or not self.is_participating():
            return 0, 0
        n = max(self.num_participants(), 1)
        per, rem = divmod(global_batch_size, n)
        # first `rem` ranks take one extra example so every example in the
        # global batch is assigned under any elastic membership
        start = rank * per + min(rank, rem)
        end = start + per + (1 if rank < rem else 0)
        return start, end

    def __repr__(self) -> str:
        return (
            f"ManagedDeviceMesh({self.replicate_dim_name}="
            f"{max(self.num_participants(), 1)} x inner {self.mesh!r})"
        )


def ft_init_device_mesh(
    manager: Manager,
    mesh_shape: "Dict[str, int]",
    devices: "Optional[Sequence[Any]]" = None,
    replicate_dim_name: str = "dp_replicate",
) -> ManagedDeviceMesh:
    """Build the inner mesh over this replica group's devices and wrap it
    with the FT dim (reference ft_init_device_mesh, device_mesh.py:307-340).

    ``mesh_shape`` maps inner axis names to sizes, e.g.
    ``{"fsdp": 4, "tp": 2}``; the product must equal the local device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    total = int(np.prod(list(mesh_shape.values()), dtype=np.int64))
    if total != len(devices):
        raise ValueError(
            f"mesh_shape {mesh_shape} needs {total} devices, have {len(devices)}"
        )
    dev_array = np.array(devices).reshape(tuple(mesh_shape.values()))
    mesh = jax.sharding.Mesh(dev_array, tuple(mesh_shape.keys()))
    return ManagedDeviceMesh(manager, mesh, replicate_dim_name)
