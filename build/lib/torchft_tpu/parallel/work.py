"""Async work handles for collective operations.

Analog of the reference's Work objects and `_DummyWork`
(reference: torchft/work.py:9-20 and manager.py:1015-1298 _ManagedWork).
A Work wraps a ``concurrent.futures.Future`` carrying the op's result
(numpy arrays for host-mediated collectives).  ``then`` chains callbacks
lazily, mirroring the reference's callback-chain semantics without CUDA
streams — on TPU, device-side async is owned by XLA, and these handles
sequence the *host-side* DCN collectives.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, Callable, Optional, TypeVar

from torchft_tpu.utils.futures import future_timeout

T = TypeVar("T")


class Work:
    """Handle to an in-flight collective; resolves to the op's value."""

    def __init__(self, future: "Future[Any]") -> None:
        self._future = future

    def wait(self, timeout: "Optional[float]" = None) -> Any:
        """Block until complete; raises the op's error if it failed."""
        return self._future.result(timeout=timeout)

    def done(self) -> bool:
        return self._future.done()

    def exception(self, timeout: "Optional[float]" = None) -> "Optional[BaseException]":
        return self._future.exception(timeout=timeout)

    def get_future(self) -> "Future[Any]":
        return self._future

    def then(self, fn: "Callable[[Any], Any]") -> "Work":
        """Chain: returns a Work resolving to ``fn(result)``.

        Errors propagate: if this work failed, the chained work fails with
        the same exception without invoking ``fn``.
        """
        out: Future = Future()

        def _done(f: "Future[Any]") -> None:
            exc = f.exception()
            if exc is not None:
                out.set_exception(exc)
                return
            try:
                out.set_result(fn(f.result()))
            except Exception as e:  # noqa: BLE001 - propagate into the chain
                out.set_exception(e)

        self._future.add_done_callback(_done)
        return Work(out)

    def with_timeout(self, timeout: float) -> "Work":
        return Work(future_timeout(self._future, timeout))


def completed_work(value: Any = None) -> Work:
    """A Work that is already complete (reference _DummyWork analog)."""
    fut: Future = Future()
    fut.set_result(value)
    return Work(fut)


def failed_work(exc: BaseException) -> Work:
    fut: Future = Future()
    fut.set_exception(exc)
    return Work(fut)
