"""Pipeline parallelism: a GPipe schedule over a ``pp`` mesh axis.

A TPU-first capability beyond the reference (which has no pipeline
schedule — SURVEY §2.3: torch pipelining appears there only as a
model-splitting tool for DiLoCo fragments). Layer-stacked parameters
``[L, ...]`` are sharded over the ``pp`` axis (each stage holds ``L/S``
consecutive layers); inside ``shard_map`` the classic GPipe tick loop runs
as a ``lax.scan``: at tick ``t`` stage ``s`` processes microbatch
``t - s``, then activations hop one stage forward via neighbor
``ppermute`` (riding ICI). Reverse-mode AD through the scan + ppermute
gives the backward schedule for free.

Shapes are fully static: every stage computes every tick (bubble ticks are
masked with ``where``), so the whole schedule jits once. Bubble overhead is
the standard ``(S-1)/(M+S-1)`` — pick ``microbatches >= 4*stages`` to
amortize.

Composes with the other axes: the per-stage ``fn`` may itself use tp/cp
collectives (its shard_map axis names remain visible), and dp/fsdp shard
the microbatch dim through ``in_specs``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Params = Any


def _stage_apply(
    fn: "Callable[[jax.Array, Params], jax.Array]",
    x: jax.Array,
    stage_params: Params,
) -> jax.Array:
    """Run this stage's local layer stack ``[L/S, ...]`` over x."""

    def body(h, layer_params):
        return fn(h, layer_params), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def pipeline_apply_local(
    params: Params,
    microbatches: Any,
    fn: "Callable[[Any, Params], Any]",
    axis_name: str = "pp",
) -> Any:
    """Per-shard GPipe body; must run inside shard_map over ``axis_name``.

    Args:
        params: this stage's layer stack, pytree with leading ``[L/S]`` dim.
        microbatches: activation pytree (an array is the common case),
            every leaf ``[M, mb, ...]`` — full microbatch set (replicated
            across stages; only stage 0 feeds it into the pipe).
            Multi-leaf activations let side streams ride the pipe (e.g.
            the MoE load-balance aux loss accumulating across stages).
        fn: one decoder-layer step ``fn(x, layer_params) -> x`` over the
            activation pytree.

    Returns ``[M, mb, ...]``-leaved outputs, identical on every stage (the
    last stage's results are broadcast back via psum).
    """
    tmap = jax.tree_util.tree_map
    stage = jax.lax.axis_index(axis_name)
    size = jax.lax.axis_size(axis_name)
    m = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    n_ticks = m + size - 1
    perm_fwd = [(i, i + 1) for i in range(size - 1)]

    def tick(carry, t):
        buf, outputs = carry
        mb_idx = t - stage
        active = (mb_idx >= 0) & (mb_idx < m)
        idx = jnp.clip(mb_idx, 0, m - 1)
        # stage 0 pulls the next microbatch; later stages consume the
        # activation that hopped in last tick
        feed = tmap(
            lambda mbs: jax.lax.dynamic_index_in_dim(
                mbs, idx, axis=0, keepdims=False
            ),
            microbatches,
        )
        x_in = tmap(lambda f, b: jnp.where(stage == 0, f, b), feed, buf)
        y = _stage_apply(fn, x_in, params)
        # bubble ticks produce garbage; zero it so the output scatter and
        # the ppermute hand clean values downstream
        y = tmap(lambda v: jnp.where(active, v, jnp.zeros_like(v)), y)
        is_last = stage == size - 1
        outputs = tmap(
            lambda outs, v: jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(
                    active & is_last,
                    v,
                    jax.lax.dynamic_index_in_dim(
                        outs, idx, axis=0, keepdims=False
                    ),
                ),
                idx,
                axis=0,
            ),
            outputs,
            y,
        )
        buf = tmap(lambda v: jax.lax.ppermute(v, axis_name, perm_fwd), y)
        return (buf, outputs), None

    # pvary: the carry becomes device-varying after one tick (it depends on
    # the stage index), so the initial carry must carry the same varying-
    # axis type or scan rejects the carry signature (shard_map vma rule)
    _pcast = getattr(jax.lax, "pcast", None)
    if _pcast is not None:
        vary = lambda v: _pcast(v, axis_name, to="varying")  # noqa: E731
    else:  # older jax
        vary = lambda v: jax.lax.pvary(v, (axis_name,))  # noqa: E731
    buf0 = tmap(lambda mbs: vary(jnp.zeros_like(mbs[0])), microbatches)
    out0 = tmap(lambda mbs: vary(jnp.zeros_like(mbs)), microbatches)
    (_, outputs), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(n_ticks))
    # only the last stage holds real outputs; broadcast to all stages
    return tmap(
        lambda outs: jax.lax.psum(
            jnp.where(stage == size - 1, outs, jnp.zeros_like(outs)), axis_name
        ),
        outputs,
    )


def pipeline_apply(
    params: Params,
    x: jax.Array,
    fn: "Callable[[jax.Array, Params], jax.Array]",
    mesh: Mesh,
    axis_name: str = "pp",
    microbatches: int = 4,
    batch_axes: "Optional[tuple]" = None,
    seq_axis: "Optional[str]" = None,
    seq_dim: int = 1,
) -> jax.Array:
    """GPipe-apply a stacked-layer model over the ``pp`` mesh axis.

    The shard_map is *partial-manual* (``axis_names={pp[, seq_axis]}``):
    only the pipeline axis (and, when given, the sequence-parallel axis the
    stage fn handles itself, e.g. ring attention over cp) is manual; every
    other mesh axis stays automatic, so dp/fsdp batch sharding and fsdp/tp
    weight sharding flow through from the inputs' shardings with XLA
    placing the collectives — stage weights are NOT replicated.

    Args:
        params: pytree with leading layer dim ``[L]``; ``L`` must divide by
            the pp axis size (each stage takes ``L/S`` consecutive layers).
        x: ``[B, ...]`` activations; ``B`` must divide by ``microbatches``.
            May be a PYTREE of ``[B, ...]`` leaves (side streams ride the
            pipe — e.g. a per-example MoE aux-loss accumulator); the
            sequence sharding (``seq_axis``) applies to leaves with a
            ``seq_dim`` to shard (ndim > seq_dim).
        fn: one layer step ``fn(x_mb, layer_params) -> x_mb`` over the
            activation (pytree). With ``seq_axis`` the fn runs in manual
            context over that axis too (it may call e.g.
            ring_attention_local or ulysses_attention_local over it) and
            receives the local sequence chunk.
        mesh: mesh containing ``axis_name``.
        microbatches: GPipe microbatch count M (bubble = (S-1)/(M+S-1)).
        batch_axes: unused (kept for call-site stability); batch sharding
            over dp/fsdp/ep is automatic in partial-manual mode.
        seq_axis: optional mesh axis the sequence dim is sharded over
            (manual: the stage fn owns its collectives).
        seq_dim: which dim of ``x`` is the sequence (default 1, [B, T, E]).

    Returns outputs with x's structure and sharding.
    """
    del batch_axes  # automatic in partial-manual mode
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis_name!r} axis: {mesh.axis_names}")
    if seq_axis is not None and seq_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {seq_axis!r} axis: {mesh.axis_names}")
    stages = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    n_layers = jax.tree_util.tree_leaves(params)[0].shape[0]
    if n_layers % stages != 0:
        raise ValueError(
            f"layer count {n_layers} not divisible by pp axis size {stages}"
        )
    x_leaves, x_treedef = jax.tree_util.tree_flatten(x)
    b = x_leaves[0].shape[0]
    if b % microbatches != 0:
        raise ValueError(f"batch {b} not divisible by microbatches {microbatches}")
    mb = b // microbatches
    x_mb = jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((microbatches, mb) + leaf.shape[1:]), x
    )

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), params
    )

    def leaf_spec(leaf: jax.Array) -> P:
        entries: "list" = [None] * (leaf.ndim + 1)
        if seq_axis is not None and leaf.ndim > seq_dim:
            entries[seq_dim + 1] = seq_axis  # +1 for the microbatch dim
        return P(*entries)

    data_specs = jax.tree_util.tree_map(leaf_spec, x)

    manual = {axis_name} if seq_axis is None else {axis_name, seq_axis}
    out = jax.shard_map(
        functools.partial(pipeline_apply_local, fn=fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, data_specs),
        out_specs=data_specs,
        axis_names=manual,
    )(params, x_mb)
    return jax.tree_util.tree_map(
        lambda o, leaf: o.reshape(leaf.shape), out, x
    )


__all__ = ["pipeline_apply", "pipeline_apply_local"]
