"""Fault-tolerant data parallelism for JAX training loops.

Analog of the reference FT-DDP (reference: torchft/ddp.py:32-105).  The
reference hooks torch's gradient buckets; in JAX gradients are an explicit
pytree, so DDP here is a gradient-averaging step: zero-contribution
participation and live-count division come from ``Manager.allreduce``
(reference trick, manager.py:416-417), which keeps compiled shapes static
across membership changes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax

from torchft_tpu.manager import Manager
from torchft_tpu.parallel.work import Work


class DistributedDataParallel:
    """FT gradient averaging over the elastic replica dimension.

    Usage::

        ddp = DistributedDataParallel(manager)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        avg_grads = ddp.allreduce_gradients(grads).wait()
    """

    def __init__(self, manager: Manager, should_quantize: bool = False) -> None:
        self._manager = manager
        self._should_quantize = should_quantize

    def allreduce_gradients(self, grads: Any) -> Work:
        """Average a gradient pytree over the live quorum (single fused op —
        bandwidth-optimal for the ring; the reference's bucket hook exists to
        overlap with backward, which JAX expresses via async dispatch)."""
        return self._manager.allreduce(grads, should_quantize=self._should_quantize)

    def wrap_grad_fn(
        self, grad_fn: "Callable[..., Tuple[Any, Any]]"
    ) -> "Callable[..., Tuple[Any, Any]]":
        """Wrap a ``value_and_grad``-style fn so its gradients come back
        pre-averaged (the comm-hook analog, reference ddp.py:67-79)."""

        def wrapped(*args: Any, **kwargs: Any) -> "Tuple[Any, Any]":
            value, grads = grad_fn(*args, **kwargs)
            return value, self.allreduce_gradients(grads).wait()

        return wrapped


class PureDistributedDataParallel:
    """Naive per-leaf allreduce (reference ddp.py:82-105): simpler to reason
    about, one collective per parameter — for tests and small models."""

    def __init__(self, manager: Manager) -> None:
        self._manager = manager

    def allreduce_gradients(self, grads: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        works = [self._manager.allreduce(leaf) for leaf in leaves]
        return jax.tree_util.tree_unflatten(treedef, [w.wait() for w in works])
