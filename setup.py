"""Build hook: compile the native coordination core into the wheel.

``pip wheel .`` / ``pip install .`` (non-editable) run the ``native/``
Makefile and ship the resulting ``libtorchft_tpu_native.so`` inside the
``torchft_tpu`` package (found at import time by ``torchft_tpu._native``'s
search order).  Editable installs (``pip install -e .``) skip this — the
repo-layout ``native/`` directory is used directly, building on first
import if needed.

Reference analog: the Rust core's build.rs + maturin wiring
(/root/reference/pyproject.toml, /root/reference/build.rs); C++ here.
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildNativeThenPy(build_py):
    def run(self):
        root = os.path.dirname(os.path.abspath(__file__))
        native_dir = os.path.join(root, "native")
        lib = os.path.join(native_dir, "libtorchft_tpu_native.so")
        staged = os.path.join(root, "torchft_tpu", "libtorchft_tpu_native.so")
        if os.path.isdir(native_dir):
            subprocess.run(
                ["make", "-C", native_dir, "-j", str(os.cpu_count() or 2)],
                check=True,
            )
            # stage the .so inside the package so package-data picks it up
            shutil.copy2(lib, staged)
        elif not os.path.exists(staged):
            # never produce a green build with no native core in it: an
            # sdist missing native/ (MANIFEST.in grafts it) would otherwise
            # ship a package that fails at first import
            raise RuntimeError(
                "native/ source tree not found and no prebuilt "
                "libtorchft_tpu_native.so staged — refusing to build a "
                "wheel without the native core"
            )
        super().run()


setup(cmdclass={"build_py": BuildNativeThenPy})
