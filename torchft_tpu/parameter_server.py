"""Fault-tolerant parameter server built on reconfigurable ProcessGroups.

TPU-native rebuild of the reference prototype
(reference: torchft/parameter_server.py:31-195): the server runs a tiny
HTTP endpoint; ``GET /new_session`` mints a uuid session, replies with a
per-session rendezvous store prefix, then *hijacks the handler thread* to
configure a fresh 2-rank ProcessGroup (server rank 0, client rank 1) and
hand it to the abstract ``forward`` — one thread per live session, no
Lighthouse required.

Differences by design: rendezvous uses the C++ StoreServer
(torchft_tpu.coordination) instead of torch TCPStore, and the exchanged
payloads are numpy/pytree host buffers moved by ProcessGroupTCP — on TPU
the parameters live in jax Arrays and cross host<->device at the session
boundary.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
import uuid
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from torchft_tpu.coordination import StoreServer
from torchft_tpu.parallel.process_group import ProcessGroup, _routable_local_ip
from torchft_tpu.utils.retry import RetryPolicy

logger = logging.getLogger(__name__)

# Session-mint retry: the server may still be binding (rolling restart)
# or briefly saturated — poll connection-level failures and retryable
# 503s with jittered backoff inside the caller's deadline.  A 400 (bad
# path) or any other HTTP error is permanent and fails immediately.
_SESSION_POLICY = RetryPolicy(
    name="parameter_server.new_session",
    base_delay=0.05,
    multiplier=2.0,
    max_delay=1.0,
    retry_if=lambda e: (
        e.code == 503
        if isinstance(e, urllib.error.HTTPError)
        else isinstance(e, (urllib.error.URLError, ConnectionError, OSError))
    ),
)


class ParameterServer(ABC):
    """Threaded parameter server over the FT collective layer.

    Subclasses implement :meth:`new_process_group` (an unconfigured PG,
    e.g. ``ProcessGroupTCP``) and :meth:`forward` (the per-session serving
    loop). Reference: torchft/parameter_server.py:31-128.
    """

    def __init__(self, port: int = 0, store_port: int = 0) -> None:
        self._store = StoreServer(bind=f":{store_port}")

        ps = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug("ps http: " + fmt, *args)

            def do_GET(self) -> None:
                if self.path != "/new_session":
                    self.send_response(400)
                    self.send_header("Content-type", "text/plain")
                    self.end_headers()
                    self.wfile.write(b"invalid path\n")
                    return

                session_id = str(uuid.uuid4())
                store_addr = f"{ps._store.address()}/session/{session_id}"
                logger.info("creating new session %s", session_id)

                body = json.dumps(
                    {"session_id": session_id, "store_addr": store_addr}
                ).encode()
                self.send_response(200)
                self.send_header("Content-type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                # Content-Length lets the client complete the request while
                # this thread stays hijacked as the session's serving thread.
                self.wfile.flush()
                self.close_connection = True

                try:
                    ps._handle_session(session_id, store_addr)
                except Exception:
                    logger.exception("session %s failed", session_id)

        self._server = ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            name="tft_param_server",
            daemon=True,
        )
        self._thread.start()
        logger.info("started ParameterServer on %s", self.address())

    def address(self) -> str:
        """HTTP address to create a new session: ``http://host:port/new_session``."""
        port = self._server.socket.getsockname()[1]
        # hostnames aren't guaranteed resolvable across hosts/containers;
        # advertise the interface that routes to our own store
        host = _routable_local_ip(self._store.address())
        return f"http://{host}:{port}/new_session"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._store.shutdown()

    # -- session plumbing --------------------------------------------------

    def _handle_session(self, session_id: str, store_addr: str) -> None:
        pg = self.new_process_group()
        # server is always rank 0 (reference parameter_server.py:170-175)
        pg.configure(store_addr, replica_id="0", rank=0, world_size=2)
        try:
            self.forward(session_id, pg)
        finally:
            pg.shutdown()

    @classmethod
    def new_session(cls, address: str, timeout: float = 30.0) -> ProcessGroup:
        """Client side: mint a session and return a configured PG (rank 1).

        The mint request runs under the unified retry layer
        (``_SESSION_POLICY``): connection failures and retryable 503s
        are polled with jittered backoff until ``timeout``; permanent
        HTTP errors fail immediately."""

        def attempt(budget: "Optional[float]") -> dict:
            t = max(budget if budget is not None else 0.001, 0.001)
            with urllib.request.urlopen(address, timeout=t) as f:
                return json.load(f)

        data = _SESSION_POLICY.run(
            attempt, timeout=timeout, op="parameter_server.new_session"
        )

        logger.info(
            "connecting to session %s at %s", data["session_id"], data["store_addr"]
        )
        pg = cls.new_process_group()
        # client is always rank 1 (reference parameter_server.py:148-168)
        pg.configure(data["store_addr"], replica_id="0", rank=1, world_size=2)
        return pg

    # -- to implement ------------------------------------------------------

    @classmethod
    @abstractmethod
    def new_process_group(cls) -> ProcessGroup:
        """A new *unconfigured* ProcessGroup for one session's pair."""

    @abstractmethod
    def forward(self, session_id: str, pg: ProcessGroup) -> None:
        """Per-session serving loop, called on a dedicated thread.

        Server rank is 0, client rank is 1; loop over ops (e.g. recv grads,
        broadcast params) until the client disconnects — a failed collective
        raises, the PG is freed, and the client must open a new session.
        """
