"""Elastic replica-group launcher (reference torchx component analog).

The reference ships a TorchX component that turns one training script into
N torchrun roles, one per replica group, each with the env triple
``REPLICA_GROUP_ID`` / ``NUM_REPLICA_GROUPS`` / ``TORCHFT_LIGHTHOUSE`` and a
``--max_restarts`` supervision budget (reference: torchft/torchx.py:11-83).
TPU deployments don't run torchrun or TorchX, so this module provides the
same three capabilities natively:

- :func:`replica_app_spec` — a scheduler-agnostic spec (plain dicts) that a
  SLURM/k8s/GKE adapter can translate (the TorchX ``specs.AppDef`` analog);
- :class:`ReplicaGroupLauncher` — a local supervisor that spawns one
  process per replica group, injects the env triple, and restarts crashed
  groups up to ``max_restarts`` times (the torchrun ``--max_restarts``
  analog; on TPU a restarted group live-heals via quorum instead of
  re-rendezvousing the whole world);
- a CLI: ``python -m torchft_tpu.launcher --replicas 2 -- python
  examples/train_ddp.py`` (starts an in-process Lighthouse when
  ``TORCHFT_LIGHTHOUSE`` isn't set).

One replica group == one TPU slice == one process here; intra-slice
parallelism is pjit/ICI inside the trainer, so there is no
``workers_per_replica``-style nproc fan-out — that knob becomes the number
of hosts in the slice's JAX process group, owned by the deployment layer.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from torchft_tpu.utils.env import env_str

logger = logging.getLogger(__name__)


def replica_app_spec(
    *script_args: str,
    replicas: int = 2,
    max_restarts: int = 10,
    script: str = "examples/train_ddp.py",
    env: "Optional[Dict[str, str]]" = None,
    lighthouse: "Optional[str]" = None,
) -> "Dict[str, Any]":
    """Build a scheduler-agnostic app spec: one role per replica group.

    Mirrors the reference component's shape (reference torchx.py:11-83)
    without the TorchX dependency: each role carries the entrypoint command
    and the replica-group env triple; a deployment adapter (SLURM sbatch,
    k8s Job, ...) consumes ``roles[i]["args"]`` + ``roles[i]["env"]``.
    """
    if replicas <= 0:
        raise ValueError("replicas must be > 0")
    base_env = dict(env or {})
    base_env.setdefault("LOGLEVEL", "INFO")
    if lighthouse is not None:
        # explicit argument wins over anything in a forwarded caller env
        base_env["TORCHFT_LIGHTHOUSE"] = lighthouse
    else:
        base_env.setdefault(
            "TORCHFT_LIGHTHOUSE",
            env_str("TORCHFT_LIGHTHOUSE", "localhost:29510"),
        )

    roles = []
    for replica_id in range(replicas):
        roles.append(
            {
                "name": f"replica_{replica_id}",
                "entrypoint": sys.executable,
                "args": [script, *script_args],
                "max_restarts": max_restarts,
                # per-role triple last: caller env (e.g. a forwarded
                # os.environ that itself contains REPLICA_GROUP_ID) must
                # never override the role identity
                "env": {
                    **base_env,
                    "REPLICA_GROUP_ID": str(replica_id),
                    "NUM_REPLICA_GROUPS": str(replicas),
                },
            }
        )
    return {"name": "torchft_tpu", "roles": roles}


@dataclass
class _ReplicaProc:
    replica_id: int
    cmd: "List[str]"
    env: "Dict[str, str]"
    max_restarts: int
    proc: "Optional[subprocess.Popen]" = None
    restarts: int = 0
    returncode: "Optional[int]" = None  # terminal result
    history: "List[int]" = field(default_factory=list)

    def start(self) -> None:
        logger.info(
            "starting replica_group %d (attempt %d): %s",
            self.replica_id,
            self.restarts + 1,
            " ".join(self.cmd),
        )
        self.proc = subprocess.Popen(self.cmd, env=self.env)


class ReplicaGroupLauncher:
    """Spawn + supervise one process per replica group.

    A crashed group is restarted with the same env until its
    ``max_restarts`` budget is exhausted; the quorum protocol absorbs the
    membership change, so surviving groups keep training throughout
    (reference semantics: torchrun --max_restarts per role,
    torchx.py:53-58). Exit code 0 is terminal success.
    """

    def __init__(
        self,
        cmd: "Sequence[str]",
        replicas: int,
        max_restarts: int = 10,
        env: "Optional[Dict[str, str]]" = None,
        lighthouse_addr: "Optional[str]" = None,
        restart_backoff: float = 1.0,
    ) -> None:
        if replicas <= 0:
            raise ValueError("replicas must be > 0")
        self._lighthouse = None
        if lighthouse_addr is None:
            lighthouse_addr = env_str("TORCHFT_LIGHTHOUSE") or None
        if lighthouse_addr is None:
            # local mode: host a Lighthouse in this supervisor process
            from torchft_tpu.coordination import LighthouseServer

            self._lighthouse = LighthouseServer(min_replicas=1)
            lighthouse_addr = self._lighthouse.address()
            logger.info("started local lighthouse at %s", lighthouse_addr)
        self.lighthouse_addr = lighthouse_addr
        self._restart_backoff = restart_backoff

        base_env = {**os.environ, **(env or {})}
        base_env["TORCHFT_LIGHTHOUSE"] = lighthouse_addr
        base_env["NUM_REPLICA_GROUPS"] = str(replicas)

        self._replicas = [
            _ReplicaProc(
                replica_id=r,
                cmd=list(cmd),
                env={**base_env, "REPLICA_GROUP_ID": str(r)},
                max_restarts=max_restarts,
            )
            for r in range(replicas)
        ]

    def run(self, timeout: "Optional[float]" = None, poll_interval: float = 0.2) -> "Dict[int, int]":
        """Run all groups to completion; returns {replica_id: exit_code}.

        Raises TimeoutError if ``timeout`` elapses first (all groups are
        terminated).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            # inside the try: a Popen failure mid-loop must still tear down
            # the replicas (and local Lighthouse) already started
            for rp in self._replicas:
                rp.start()
            while True:
                live = 0
                for rp in self._replicas:
                    if rp.returncode is not None:
                        continue
                    code = rp.proc.poll()
                    if code is None:
                        live += 1
                        continue
                    rp.history.append(code)
                    if code == 0:
                        rp.returncode = 0
                    elif rp.restarts < rp.max_restarts:
                        rp.restarts += 1
                        logger.warning(
                            "replica_group %d exited with %d; restart %d/%d",
                            rp.replica_id, code, rp.restarts, rp.max_restarts,
                        )
                        time.sleep(self._restart_backoff)
                        rp.start()
                        live += 1
                    else:
                        logger.error(
                            "replica_group %d failed permanently (exit %d, "
                            "%d restarts used)", rp.replica_id, code, rp.restarts,
                        )
                        rp.returncode = code
                if live == 0:
                    break
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"launcher timed out after {timeout}s")
                time.sleep(poll_interval)
        finally:
            self.shutdown()
        return {rp.replica_id: rp.returncode for rp in self._replicas}

    def kill_replica(self, replica_id: int, sig: int = signal.SIGKILL) -> None:
        """Chaos hook: deliver ``sig`` to one group (punisher analog)."""
        rp = self._replicas[replica_id]
        if rp.proc is not None and rp.proc.poll() is None:
            rp.proc.send_signal(sig)

    def shutdown(self) -> None:
        for rp in self._replicas:
            if rp.proc is not None and rp.proc.poll() is None:
                rp.proc.terminate()
        for rp in self._replicas:
            if rp.proc is not None and rp.proc.poll() is None:
                try:
                    rp.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    rp.proc.kill()
        if self._lighthouse is not None:
            self._lighthouse.shutdown()
            self._lighthouse = None


def main(argv: "Optional[Sequence[str]]" = None) -> int:
    p = argparse.ArgumentParser(
        description="Launch N fault-tolerant replica groups of a training "
        "command (everything after `--`)."
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--max-restarts", type=int, default=10)
    p.add_argument("--lighthouse", default=None,
                   help="host:port of an external Lighthouse (default: host one locally)")
    p.add_argument("--timeout", type=float, default=None)
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- command to run per replica group")
    args = p.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given; usage: ... -- python train.py [args]")

    logging.basicConfig(level=logging.INFO, format="%(asctime)s launcher: %(message)s")
    launcher = ReplicaGroupLauncher(
        cmd,
        replicas=args.replicas,
        max_restarts=args.max_restarts,
        lighthouse_addr=args.lighthouse,
    )
    codes = launcher.run(timeout=args.timeout)
    bad = {r: c for r, c in codes.items() if c != 0}
    if bad:
        logger.error("failed replica groups: %s", bad)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
