"""torchft-diagnose: cross-replica post-mortem from flight dumps + events.

``python -m torchft_tpu.diagnose dump1.jsonl dump2.jsonl [--events ev.jsonl]``
merges N replicas' flight-recorder dumps (``TORCHFT_FLIGHT_FILE``,
utils/flightrecorder.py) and structured-event logs
(``TORCHFT_EVENTS_FILE``, utils/logging.py) into **one cross-replica
timeline keyed by (step, quorum_id)**, then flags the likely culprit of a
degraded run:

1. **injected faults** — a chaos-killed replica carries a fault-tagged
   flight record (``utils/faults.py`` stamps every injection);
2. **silent death** — the replica whose records stop earliest while its
   peers kept going (the classic "which replica stalled the quorum"
   question both PCCL-style reports treat as first-class);
3. **last to enter the failed phase** — among replicas that DID reach the
   step where the first error fired, the one missing (or last to enter)
   that phase;
4. **retry storms** — bursts of ``retry`` records flagged per operation.

``--timeline <file-or-URL>`` additionally folds in the lighthouse's
rolling cluster step-timeline (``GET /timeline.json`` — aggregated from
the heartbeat-piggybacked per-replica step digests) so one scrape
answers "what was the whole fleet doing at step N"; its worst-K
straggler snapshot names a culprit (signal ``timeline_straggler``) even
when no flight dumps were collected at all.

``--links <file-or-URL>`` folds in the lighthouse's fleet link-state
matrix (``GET /links.json`` — aggregated from the heartbeat-piggybacked
per-host link digests, utils/linkstats.py) and adds a ``slow_link``
culprit signal: a host pair whose sustained goodput is a strong outlier
below the fleet median names the wire itself as the culprit — the one
degradation mode no per-replica evidence can see (every replica on the
slow link looks equally unlucky from inside).  Combined with ``--trace``
it also splits the critical-path ledger's ``wire`` category into
**expected** (what the fleet-median link would have spent moving the
same traffic) vs **excess** (the slow link's surcharge), so "wire ate
the step" becomes "the wire was 4x slower than the fleet's, costing
120ms/step".

``--fragment <frag_id>`` (e.g. ``weights/0``) reconstructs one
fragment's whole journey — publish, relay hops, serving clients, heal
destinations, durable store — from the ``fragment.hold`` /
``fragment.hop`` provenance records in the given dumps.  The provenance
registry (checkpointing/provenance.py) dumps its hop ring to
``TORCHFT_FLIGHT_FILE + ".prov"`` alongside every flight dump (same
JSONL format, so ``.prov`` files are passed as ordinary positional
dumps).  The first hop whose digest verdict is ``mismatch``/``torn`` is
where bad bytes entered the plane; its source is named as the
``poisoned_hop`` culprit — attribution from serialized dumps alone, no
live fleet required.

``--trace <TORCHFT_TRACE_FILE>`` reads the distributed-tracing span sink
(utils/tracing.py) and reconstructs the **cross-replica critical path**
per step: trace ids are deterministic per step, every replica's
``quorum_round`` root plus its phase / native ``rpc.*`` / heal /
quantized-pipeline children land in one trace, and the ledger attributes
the slowest replica's wall time to ``compute`` / ``codec`` / ``wire`` /
``protocol`` / ``straggler-wait`` — naming the dominant contributor per
step and per replica, and (signal ``trace_error``) the replica whose
span failed, from the trace file alone.  All three inputs join on
``step``/``quorum_id``, so dumps + timeline + trace compose into one
report.

Output is a human timeline + verdict (default) or ``--json`` for machines.
``--selftest`` generates a synthetic two-replica dump pair in a temp dir
and checks culprit attribution end to end — wired into the test suite so
the CLI can never silently rot (tests/test_diagnose.py).

Exit codes: 0 = analysis produced (or selftest passed), 1 = selftest
failed / no input parseable, 2 = bad usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "load_records",
    "load_timeline",
    "load_links",
    "load_spans",
    "analyze",
    "analyze_timeline",
    "analyze_links",
    "analyze_fragment",
    "analyze_trace",
    "apply_wire_split",
    "ledger_categories",
    "dominant_contributor",
    "render_text",
    "render_timeline_text",
    "render_links_text",
    "render_fragment_text",
    "render_trace_text",
    "selftest",
    "main",
]

# record statuses that mean "something went wrong here"
_ERROR_STATUSES = ("error", "abort")
# event kinds that mean the same in the TORCHFT_EVENTS_FILE stream
_ERROR_KINDS = ("error", "abort")
# at least this many retry records for one op counts as a storm
RETRY_STORM_THRESHOLD = 3
# a straggler score this far past typical (~1.0) in the lighthouse
# timeline snapshot is a culprit signal of its own
TIMELINE_STRAGGLER_SCORE = 4.0
# a WAN link whose goodput is this many times below the fleet median is
# a slow_link culprit (with enough samples to call it sustained)
SLOW_LINK_RATIO = 4.0
# estimator samples required before a link can be named a culprit — a
# couple of unlucky transfers are noise, not a slow wire
SLOW_LINK_MIN_SAMPLES = 8

#: protocol-phase name -> critical-path ledger cost category.  The same
#: mapping bench.py uses for its per-leg dominant-contributor field, so
#: the bench tail and the trace ledger speak one vocabulary.
PHASE_CATEGORY = {
    "quorum_wait": "straggler-wait",
    "quorum_rpc": "protocol",
    "pg_configure": "protocol",
    "commit": "protocol",
    "host_sync": "compute",
    "ring": "wire",
    "heal_send": "wire",
    "heal_recv": "wire",
    # striped-heal receive split (ISSUE 15): the manifest fetch is a
    # protocol round trip, the digest diff and fragment decode are codec
    # work, the striped fragment fetches are wire
    "heal_manifest": "protocol",
    "heal_diff": "codec",
    "heal_wire": "wire",
    "heal_decode": "codec",
    # online parallelism switching (parallel/layout.py): the reshard
    # slice-diff transfers are wire cost; the commit round is protocol
    "reshard": "wire",
    "layout_commit": "protocol",
}

#: the ledger's full category vocabulary, in render order
LEDGER_CATEGORIES = ("compute", "codec", "wire", "protocol", "straggler-wait")


def ledger_categories(phase_times: "Dict[str, Any]") -> "Dict[str, float]":
    """Fold a phase->duration mapping (``Manager.phase_times`` deltas, or
    a timeline bucket's ``phase_ms``) into ledger categories.  Unknown
    phase names count as ``protocol`` (they are protocol bookkeeping by
    construction — every traced phase is in ``manager.PROTOCOL_PHASES``)."""
    out: "Dict[str, float]" = {}
    for name, dur in phase_times.items():
        try:
            v = float(dur)
        except (TypeError, ValueError):
            continue
        cat = PHASE_CATEGORY.get(name, "protocol")
        out[cat] = out.get(cat, 0.0) + v
    return out


def dominant_contributor(phase_times: "Dict[str, Any]") -> "Optional[str]":
    """The ledger category that ate the most time, or None on empty/zero
    input — the one-word answer bench legs and the per-step ledger give."""
    cats = ledger_categories(phase_times)
    if not cats or max(cats.values()) <= 0.0:
        return None
    return max(cats.items(), key=lambda kv: kv[1])[0]


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def _normalize_flight(rec: "Dict[str, Any]") -> "Dict[str, Any]":
    """One flight record -> timeline entry."""
    return {
        "source": "flight",
        "t_ns": int(rec.get("end_ns") or rec.get("start_ns") or 0),
        "start_ns": int(rec.get("start_ns") or 0),
        "replica_id": str(rec.get("replica_id", "") or ""),
        "op": str(rec.get("op", "?")),
        "status": str(rec.get("status", "ok")),
        "step": rec.get("step"),
        "quorum_id": rec.get("quorum_id"),
        "fields": {
            k: v
            for k, v in rec.items()
            if k
            not in ("flight", "op", "status", "start_ns", "end_ns", "replica_id",
                    "step", "quorum_id")
        },
    }


def _normalize_event(ev: "Dict[str, Any]") -> "Dict[str, Any]":
    """One structured event (utils/logging.py JSONL) -> timeline entry."""
    return {
        "source": "event",
        "t_ns": int(float(ev.get("ts", 0.0)) * 1e9),
        "start_ns": int(float(ev.get("ts", 0.0)) * 1e9),
        "replica_id": str(ev.get("replica_id", "") or ""),
        "op": str(ev.get("kind", "?")),
        "status": "error" if ev.get("kind") in _ERROR_KINDS else "ok",
        "step": ev.get("step"),
        "quorum_id": ev.get("quorum_id"),
        "fields": {
            k: v
            for k, v in ev.items()
            if k not in ("ts", "kind", "replica_id", "step", "quorum_id")
        },
    }


def load_records(
    paths: "List[str]", event_paths: "Optional[List[str]]" = None
) -> "Tuple[List[Dict[str, Any]], List[str]]":
    """Parse dump + event JSONL files into deduplicated timeline entries.

    A flight file accumulates one full ring snapshot per dump trigger, so
    the same record can appear many times across (and within) files —
    dedupe on (replica_id, op, start_ns, status).  Returns (entries sorted
    by time, warnings)."""
    entries: "List[Dict[str, Any]]" = []
    warnings: "List[str]" = []
    seen: set = set()

    def add(entry: "Dict[str, Any]") -> None:
        key = (
            entry["replica_id"], entry["op"], entry["start_ns"],
            entry["status"], entry["source"],
        )
        if key in seen:
            return
        seen.add(key)
        entries.append(entry)

    def parse_file(path: str, events_only: bool) -> None:
        try:
            fh = open(path, "r", encoding="utf-8")
        except OSError as e:
            warnings.append(f"{path}: unreadable ({e})")
            return
        bad = 0
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    bad += 1
                    continue
                if not isinstance(obj, dict):
                    bad += 1
                    continue
                if obj.get("flight") == "meta":
                    continue  # dump headers are bookkeeping, not evidence
                if obj.get("flight") == "rec" and not events_only:
                    add(_normalize_flight(obj))
                elif "kind" in obj:
                    add(_normalize_event(obj))
                else:
                    bad += 1
        if bad:
            warnings.append(f"{path}: skipped {bad} unparseable line(s)")

    for p in paths:
        parse_file(p, events_only=False)
    for p in event_paths or []:
        parse_file(p, events_only=True)
    entries.sort(key=lambda e: e["t_ns"])
    return entries, warnings


def load_timeline(src: str) -> "Dict[str, Any]":
    """Load a lighthouse ``/timeline.json`` document from a file path or
    an ``http(s)://`` URL (``host:port`` shorthand fetches
    ``http://host:port/timeline.json``; a ``h1:p,h2:p`` comma list rides
    the coordination-plane-HA failover walk to whichever peer currently
    leads).  Raises on unreadable/invalid input — a requested timeline
    that cannot be read is an error, not a silently thinner report."""
    if "," in src and ":" in src and not os.path.exists(src):
        # replicated-lighthouse endpoint list: the RPC client walks dead
        # peers and follows NOT_LEADER redirects (coordination.py)
        from torchft_tpu.coordination import LighthouseClient

        client = LighthouseClient(src)
        try:
            doc = client.timeline(timeout=10.0)
        finally:
            client.close()
        if not isinstance(doc, dict) or "steps" not in doc:
            raise ValueError(f"{src}: not a /timeline.json document")
        return doc
    if src.startswith(("http://", "https://")) or (
        "/" not in src and ":" in src and not os.path.exists(src)
    ):
        import urllib.request

        url = src if src.startswith("http") else f"http://{src}"
        if not url.rstrip("/").endswith("/timeline.json"):
            url = url.rstrip("/") + "/timeline.json"
        with urllib.request.urlopen(url, timeout=10) as resp:
            doc = json.loads(resp.read().decode())
    else:
        with open(src, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    if not isinstance(doc, dict) or "steps" not in doc:
        raise ValueError(f"{src}: not a /timeline.json document")
    return doc


def load_links(src: str) -> "Dict[str, Any]":
    """Load a lighthouse ``/links.json`` document from a file path, an
    ``http(s)://`` URL, a ``host:port`` shorthand, or a replicated-
    lighthouse ``h1:p,h2:p`` comma list (which rides the HA failover walk
    via the ``links`` RPC).  Raises on unreadable/invalid input, same
    contract as :func:`load_timeline`."""
    if "," in src and ":" in src and not os.path.exists(src):
        from torchft_tpu.coordination import LighthouseClient

        client = LighthouseClient(src)
        try:
            doc = client.links(timeout=10.0)
        finally:
            client.close()
        if not isinstance(doc, dict) or "rows" not in doc:
            raise ValueError(f"{src}: not a /links.json document")
        return doc
    if src.startswith(("http://", "https://")) or (
        "/" not in src and ":" in src and not os.path.exists(src)
    ):
        import urllib.request

        url = src if src.startswith("http") else f"http://{src}"
        if not url.rstrip("/").endswith("/links.json"):
            url = url.rstrip("/") + "/links.json"
        with urllib.request.urlopen(url, timeout=10) as resp:
            doc = json.loads(resp.read().decode())
    else:
        with open(src, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    if not isinstance(doc, dict) or "rows" not in doc:
        raise ValueError(f"{src}: not a /links.json document")
    return doc


def load_spans(path: str) -> "Tuple[List[Dict[str, Any]], List[str]]":
    """Parse a ``TORCHFT_TRACE_FILE`` JSONL span sink.  Returns (spans,
    warnings); a span is any object with ``trace_id``/``span_id``/``name``
    (the exact schema ``Tracer.export_span`` writes)."""
    spans: "List[Dict[str, Any]]" = []
    warnings: "List[str]" = []
    try:
        fh = open(path, "r", encoding="utf-8")
    except OSError as e:
        return [], [f"{path}: unreadable ({e})"]
    bad = 0
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if (
                isinstance(obj, dict)
                and "trace_id" in obj
                and "span_id" in obj
                and "name" in obj
            ):
                spans.append(obj)
            else:
                bad += 1
    if bad:
        warnings.append(f"{path}: skipped {bad} non-span line(s)")
    return spans, warnings


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------


def analyze(entries: "List[Dict[str, Any]]") -> "Dict[str, Any]":
    """Cross-replica culprit attribution over a merged timeline."""
    # Backfill steps per replica: PG-level records (collectives, aborts)
    # carry no step — the worker thread doesn't know it — but the same
    # replica's quorum phases do, so inherit the latest preceding one.
    # This is what lets "who entered the failed phase at step N" work.
    last_step: "Dict[str, int]" = {}
    for e in entries:  # time-sorted by load_records
        rid = e["replica_id"]
        if isinstance(e.get("step"), int):
            last_step[rid] = e["step"]
        elif rid in last_step:
            e["step"] = last_step[rid]
            e["step_inferred"] = True

    replicas: "Dict[str, Dict[str, Any]]" = {}
    for e in entries:
        rid = e["replica_id"]
        if not rid:
            continue
        if e["op"] == "fault" or e["status"] == "fault":
            # Fault records are stamped with the BARE replica id (no
            # ":uuid" incarnation suffix) — folding them into the
            # liveness table would mint a phantom replica whose records
            # "stop" at the injection and shadow the real incarnation.
            # The injected_fault branch handles them prefix-aware.
            continue
        info = replicas.setdefault(
            rid, {"first_ns": e["t_ns"], "last_ns": e["t_ns"], "max_step": -1,
                  "records": 0, "errors": 0}
        )
        info["records"] += 1
        info["last_ns"] = max(info["last_ns"], e["t_ns"])
        info["first_ns"] = min(info["first_ns"], e["t_ns"])
        if isinstance(e.get("step"), int):
            info["max_step"] = max(info["max_step"], e["step"])
        if e["status"] in _ERROR_STATUSES:
            info["errors"] += 1

    faults = [
        e for e in entries
        if e["op"] == "fault" or e["status"] == "fault"
        or (e["source"] == "event" and e["op"] == "fault")
    ]
    errors = [e for e in entries if e["status"] in _ERROR_STATUSES]

    # retry storms: many retries of one op is a failure signature of its own
    retry_counts: "Dict[Tuple[str, str], int]" = defaultdict(int)
    for e in entries:
        if e["op"] == "retry":
            retry_counts[(e["replica_id"], str(e["fields"].get("retry_op", "?")))] += 1
    storms = [
        {"replica_id": rid, "op": op, "retries": n}
        for (rid, op), n in sorted(retry_counts.items())
        if n >= RETRY_STORM_THRESHOLD
    ]

    # The failure point: the FIRST hard error in the merged timeline —
    # later errors are usually cascade.  Deliberate aborts (status
    # "abort": teardown, watchdogs, a dying replica closing its own PG)
    # only qualify when no hard error exists.
    failure: "Optional[Dict[str, Any]]" = None
    if errors:
        hard = [e for e in errors if e["status"] == "error"]
        first = (hard or errors)[0]
        step = first.get("step")
        quorum_id = first.get("quorum_id")
        if step is None:
            # PG-level records carry no step (the worker thread doesn't
            # know it); backfill from the reporter's nearest earlier
            # record that does — e.g. its quorum phases for that round.
            for e in reversed(entries):
                if (
                    e["t_ns"] <= first["t_ns"]
                    and e["replica_id"] == first["replica_id"]
                    and isinstance(e.get("step"), int)
                ):
                    step = e["step"]
                    if quorum_id is None:
                        quorum_id = e.get("quorum_id")
                    break
        failure = {
            "phase": first["op"],
            "step": step,
            "quorum_id": quorum_id,
            "t_ns": first["t_ns"],
            "reported_by": first["replica_id"],
            "detail": first["fields"].get("reason")
            or first["fields"].get("error")
            or first["fields"].get("message", ""),
        }

    culprit: "Optional[Dict[str, Any]]" = None
    # 1) injected fault wins — but only when the chaos layer stamped a
    #    REPLICA and that replica actually stopped.  A fault the system
    #    recovered from (a retried heal, an absorbed connection drop) or
    #    one without replica context (transports supply step only) is
    #    context, not the culprit — blaming it would mask a later real
    #    death.
    kill_faults = [
        f for f in faults
        if f["replica_id"]
        and str(
            f["fields"].get("action", f["fields"].get("fault", ""))
        ).find("delay") < 0
    ]
    if kill_faults and replicas:
        # Prefix-aware: the faults layer stamps the BARE replica id while
        # protocol records carry the ":uuid" incarnation suffix — compare
        # per logical replica, and report the full incarnation id.
        def _base(rid: str) -> str:
            return rid.split(":", 1)[0]

        last_by_base: "Dict[str, Tuple[int, str]]" = {}
        for rid, info in replicas.items():
            b = _base(rid)
            if b not in last_by_base or info["last_ns"] > last_by_base[b][0]:
                last_by_base[b] = (info["last_ns"], rid)
        global_last = max(info["last_ns"] for info in replicas.values())
        for f in reversed(kill_faults):
            fb = _base(f["replica_id"])
            my_last, full_id = last_by_base.get(fb, (0, f["replica_id"]))
            dead = (global_last - my_last) / 1e9 > 0.05
            if dead or len(last_by_base) == 1:
                culprit = {
                    "replica_id": full_id,
                    "reason": (
                        f"injected fault "
                        f"{f['fields'].get('fault') or f['fields'].get('site', '?')}"
                        f" at step {f.get('step')}"
                    ),
                    "signal": "injected_fault",
                }
                break
    # 1b) rejected live plan: a ``plan.verify`` record with a reject
    #     verdict (TORCHFT_PLAN_VERIFY) names the exact invariant a
    #     synthesized topology plan violated at its commit point — far
    #     more specific than any death/straggler inference, so it
    #     outranks everything except an injected fault.
    if culprit is None:
        for e in reversed(entries):
            if e["op"] != "plan.verify":
                continue
            if e["fields"].get("verdict") != "reject":
                continue
            culprit = {
                "replica_id": e["replica_id"] or "(unknown)",
                "reason": (
                    f"rejected live {e['fields'].get('plane', '?')} plan "
                    f"(epoch {e.get('step')}): invariant "
                    f"{e['fields'].get('invariant', '?')} violated — "
                    f"{e['fields'].get('detail', '')}"
                ),
                "signal": "bad_plan",
            }
            break
    # 2) silent death: a replica whose records stop earliest while peers
    #    kept producing evidence afterwards.  Only with a failure
    #    signature on the table — staggered shutdown of a HEALTHY run
    #    also leaves unequal last-record times, and a post-mortem tool
    #    that names culprits on clean runs trains operators to ignore it.
    if (
        culprit is None
        and len(replicas) >= 2
        and (failure is not None or kill_faults)
    ):
        by_last = sorted(replicas.items(), key=lambda kv: kv[1]["last_ns"])
        (dead_id, dead), (_, next_one) = by_last[0], by_last[1]
        gap_s = (next_one["last_ns"] - dead["last_ns"]) / 1e9
        if gap_s > 0.05:
            culprit = {
                "replica_id": dead_id,
                "reason": (
                    f"records stop at step {dead['max_step']} "
                    f"({gap_s:.2f}s before the next replica's last record)"
                    + (
                        f"; peers failed in phase {failure['phase']} after"
                        if failure is not None
                        else ""
                    )
                ),
                "signal": "silent_death",
            }
    # 3) last to enter the failed phase: among replicas with records at
    #    the failure step, the one that never entered (or entered last).
    if culprit is None and failure is not None and failure.get("step") is not None:
        step = failure["step"]
        entered: "Dict[str, int]" = {}
        for e in entries:
            if e.get("step") == step and e["op"] == failure["phase"] and e["replica_id"]:
                entered.setdefault(e["replica_id"], e["start_ns"])
        # Prefix-aware: fault records use the bare replica id while
        # protocol records use the ":uuid" incarnation id — a logical
        # replica whose incarnation entered is not missing.
        entered_bases = {rid.split(":", 1)[0] for rid in entered}
        missing = [
            rid
            for rid in replicas
            if rid not in entered
            and rid.split(":", 1)[0] not in entered_bases
        ]
        # earliest-stopped first (most suspicious); among same-base ids
        # report the full incarnation id
        missing.sort(key=lambda r: replicas[r]["last_ns"])
        if missing:
            base0 = missing[0].split(":", 1)[0]
            candidates = [
                r for r in missing if r.split(":", 1)[0] == base0
            ]
            culprit = {
                "replica_id": max(candidates, key=len),
                "reason": (
                    f"never entered failed phase {failure['phase']} "
                    f"at step {step}"
                ),
                "signal": "missing_phase",
            }
        elif len(entered) >= 2:
            # Only meaningful with peers to compare against: with a single
            # entrant (e.g. only the survivor's dump was collected) this
            # would confidently blame the replica that REPORTED the
            # failure.
            last_rid = max(entered, key=lambda r: entered[r])
            culprit = {
                "replica_id": last_rid,
                "reason": (
                    f"last replica to enter failed phase "
                    f"{failure['phase']} at step {step}"
                ),
                "signal": "last_entry",
            }
    # 3b) one-sided evidence: only the reporter's records exist (the peer
    #     was SIGKILLed/OOM-killed and never dumped) but its failure names
    #     a peer rank — point at that peer rather than staying silent or
    #     blaming the survivor.
    if culprit is None and failure is not None and len(replicas) == 1:
        fail_fields = next(
            (
                e["fields"]
                for e in entries
                if e["t_ns"] == failure["t_ns"]
                and e["status"] in _ERROR_STATUSES
            ),
            {},
        )
        peer = fail_fields.get("recv_peer", fail_fields.get("send_peer"))
        if peer is not None:
            culprit = {
                "replica_id": f"replica rank {peer} (no records collected)",
                "reason": (
                    f"{failure['reported_by']} failed in "
                    f"{failure['phase']} talking to rank {peer}; that peer "
                    f"left no flight records (killed without a dump?)"
                ),
                "signal": "peer_without_evidence",
            }
    # 4) retry storms as a last resort.
    if culprit is None and storms:
        worst = max(storms, key=lambda s: s["retries"])
        culprit = {
            "replica_id": worst["replica_id"] or "(unknown)",
            "reason": f"retry storm: {worst['retries']}x {worst['op']}",
            "signal": "retry_storm",
        }

    return {
        "replicas": replicas,
        "failure": failure,
        "culprit": culprit,
        "faults": [
            {
                "replica_id": f["replica_id"],
                "step": f.get("step"),
                "fault": f["fields"].get("fault")
                or f"{f['fields'].get('site', '?')}:{f['fields'].get('action', '?')}",
                "t_ns": f["t_ns"],
            }
            for f in faults
        ],
        "retry_storms": storms,
        "entries": len(entries),
    }


def analyze_timeline(timeline: "Dict[str, Any]") -> "Dict[str, Any]":
    """Culprit attribution from the lighthouse's own fleet view: the
    worst straggler snapshot riding ``/timeline.json``.

    A replica is named when it is **stale** (still tracked, heartbeat
    expired — dead or wedged hard) or its straggler score is past
    ``TIMELINE_STRAGGLER_SCORE`` (progress age many multiples of the
    fleet-typical cadence).  This is evidence the flight-recorder path
    cannot see: it requires no dump from any replica."""
    worst = timeline.get("stragglers_worst") or []
    culprit: "Optional[Dict[str, Any]]" = None
    for row in worst:
        score = float(row.get("straggler_score") or 0.0)
        stale = bool(row.get("stale"))
        if stale or score >= TIMELINE_STRAGGLER_SCORE:
            reason = (
                f"lighthouse timeline: heartbeat stale at step "
                f"{row.get('step')} (lag {row.get('step_lag')})"
                if stale
                else (
                    f"lighthouse timeline: straggler score {score:.1f} "
                    f"(>= {TIMELINE_STRAGGLER_SCORE:.0f}x typical progress "
                    f"age) at step {row.get('step')}, "
                    f"lag {row.get('step_lag')}"
                )
            )
            culprit = {
                "replica_id": str(row.get("replica_id", "?")),
                "reason": reason,
                "signal": "timeline_straggler",
            }
            break  # worst-first order: the first hit is the worst
    steps = timeline.get("steps") or []
    return {
        "culprit": culprit,
        "steps": len(steps),
        "stragglers_worst": worst,
        "last_step": steps[-1].get("step") if steps else None,
    }


def _median(vals: "List[float]") -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def analyze_links(links: "Dict[str, Any]") -> "Dict[str, Any]":
    """The ``slow_link`` culprit signal from the fleet link matrix.

    Only **WAN rows** (``local=false``) compete — the intra-host fabric
    runs at memory speed and would drag the median up until every real
    wire looks like a culprit.  A link is named when its estimated
    goodput is ``SLOW_LINK_RATIO``x below the fleet-median WAN goodput
    with at least ``SLOW_LINK_MIN_SAMPLES`` samples behind the estimate
    (sustained, not one unlucky transfer).  The culprit is the host
    PAIR, not a replica: every replica crossing that wire is equally
    slow from inside, which is exactly why no flight dump can see it."""
    rows = [r for r in (links.get("rows") or []) if isinstance(r, dict)]
    wan = [
        r
        for r in rows
        if not r.get("local")
        and float(r.get("goodput_bps") or 0.0) > 0.0
    ]
    med = _median([float(r["goodput_bps"]) for r in wan])
    culprit: "Optional[Dict[str, Any]]" = None
    slow: "List[Dict[str, Any]]" = []
    for r in sorted(wan, key=lambda r: float(r["goodput_bps"])):
        g = float(r["goodput_bps"])
        if (
            med > 0.0
            and g * SLOW_LINK_RATIO < med
            and int(r.get("samples") or 0) >= SLOW_LINK_MIN_SAMPLES
        ):
            slow.append(r)
    if slow:
        r = slow[0]  # sorted ascending: the slowest sustained outlier
        culprit = {
            "replica_id": f"link {r.get('src')}->{r.get('peer')}",
            "reason": (
                f"link-state matrix: {r.get('plane')} goodput "
                f"{float(r['goodput_bps']) / 1e6:.1f} MB/s is "
                f"{med / max(float(r['goodput_bps']), 1e-9):.1f}x below "
                f"the fleet-median WAN link ({med / 1e6:.1f} MB/s, "
                f"{r.get('samples')} samples)"
            ),
            "signal": "slow_link",
        }
    return {
        "culprit": culprit,
        "rows_total": links.get("rows_total", len(rows)),
        "rows_wan": len(wan),
        "hosts": links.get("hosts"),
        "version": links.get("version"),
        "median_wan_goodput_bps": med,
        "slow_links": [
            {
                "src": r.get("src"),
                "peer": r.get("peer"),
                "plane": r.get("plane"),
                "goodput_bps": float(r.get("goodput_bps") or 0.0),
                "rtt_p99_ms": float(r.get("rtt_p99_ms") or 0.0),
                "samples": int(r.get("samples") or 0),
            }
            for r in slow
        ],
    }


def analyze_fragment(
    entries: "List[Dict[str, Any]]", frag: str
) -> "Dict[str, Any]":
    """One fragment's journey + the ``poisoned_hop`` culprit signal.

    Replays every ``fragment.hold`` / ``fragment.hop`` provenance record
    for ``frag`` (frag_id ``"<payload>/<index>"``, e.g. ``weights/0``)
    out of the already-merged dump timeline — the ``.prov`` companions
    the provenance registry dumps alongside ``TORCHFT_FLIGHT_FILE`` use
    the same JSONL format, so they load through :func:`load_records`
    unchanged.  The journey is publish -> relay hops -> client / heal
    destination / durable store, ordered by start time across every
    process that dumped.  The FIRST hop whose digest verdict is
    ``mismatch`` or ``torn`` is where bad bytes entered the plane: its
    SOURCE is the culprit (every receiver downstream of it sees the same
    mismatch and is a victim, not a cause) — attribution needs no live
    fleet, only the serialized dumps."""
    journey = [
        e
        for e in entries
        if e.get("op") in ("fragment.hold", "fragment.hop")
        and str((e.get("fields") or {}).get("frag", "")) == frag
    ]
    journey.sort(key=lambda e: e.get("start_ns") or e.get("t_ns") or 0)
    hops = [e for e in journey if e["op"] == "fragment.hop"]
    holds = [e for e in journey if e["op"] == "fragment.hold"]
    poisoned: "Optional[Dict[str, Any]]" = None
    for e in hops:
        if str(e["fields"].get("verdict", "ok")) in ("mismatch", "torn"):
            poisoned = e
            break
    culprit: "Optional[Dict[str, Any]]" = None
    if poisoned is not None:
        f = poisoned["fields"]
        source = str(f.get("source", "?"))
        holder = str(f.get("holder", "?"))
        verdict = str(f.get("verdict", "?"))
        culprit = {
            "replica_id": source,
            "reason": (
                f"fragment {frag} v{f.get('version')} arrived '{verdict}' "
                f"at {holder} over the {f.get('plane')} plane — {source} "
                f"is the first hop where the digest broke ({len(hops)} "
                f"hop(s) audited)"
            ),
            "frag": frag,
            "version": f.get("version"),
            "plane": f.get("plane"),
            "verdict": verdict,
            "holder": holder,
            "signal": "poisoned_hop",
        }
    return {
        "frag": frag,
        "holds": len(holds),
        "hops": len(hops),
        "journey": journey,
        "poisoned_hop": dict(poisoned["fields"]) if poisoned else None,
        "culprit": culprit,
    }


def apply_wire_split(
    trace_report: "Dict[str, Any]", links_report: "Dict[str, Any]"
) -> None:
    """Annotate the critical-path ledger with the expected-vs-excess wire
    split, in place.

    The ledger knows how long the wire was busy (``wire`` seconds); the
    link matrix knows how fast the wire actually ran vs the fleet.  For
    each step's critical replica: the same traffic on a fleet-median
    link would have taken ``wire_s * (slow / median)`` — that is the
    **expected** share; the rest is **excess**, the slow link's
    surcharge.  With no sustained slow link the split is degenerate
    (everything expected) and nothing is annotated — the split exists to
    quantify a named culprit, not to invent one."""
    slow = links_report.get("slow_links") or []
    med = float(links_report.get("median_wan_goodput_bps") or 0.0)
    if not slow or med <= 0.0:
        return
    g = float(slow[0]["goodput_bps"])
    if g <= 0.0 or g >= med:
        return
    frac_expected = g / med
    for step in trace_report.get("steps") or []:
        info = step["replicas"].get(step["critical_replica"]) or {}
        wire_s = float((info.get("categories") or {}).get("wire") or 0.0)
        if wire_s <= 0.0:
            continue
        step["wire_expected_s"] = round(wire_s * frac_expected, 6)
        step["wire_excess_s"] = round(wire_s * (1.0 - frac_expected), 6)
        step["wire_slow_link"] = f"{slow[0]['src']}->{slow[0]['peer']}"


def _span_dur_s(span: "Dict[str, Any]") -> float:
    try:
        return max(
            (int(span.get("end_ns") or 0) - int(span.get("start_ns") or 0))
            / 1e9,
            0.0,
        )
    except (TypeError, ValueError):
        return 0.0


def analyze_trace(spans: "List[Dict[str, Any]]") -> "Dict[str, Any]":
    """The per-step critical-path ledger from a span-sink file.

    One trace == one training step (ids are deterministic per step), with
    one ``quorum_round`` root per replica and every other span a child of
    some replica's root (phase spans, native ``rpc.*`` server spans, heal
    spans, the quantized-pipeline spans).  Per replica the ledger sums:

    - the **phase spans** (the Manager's own non-overlapping accounting)
      through :data:`PHASE_CATEGORY`;
    - ``quant.pipeline``'s ``codec_s``/``wire_s`` attributes, which
      REPLACE the ``ring`` phase when present (ring wraps the pipeline —
      counting both would double-bill the wire);
    - the lighthouse's ``rpc.quorum`` server span, which REFINES
      straggler-wait (it measures exactly the block-until-quorum-forms
      wait; the ``quorum_wait`` phase then only contributes any excess).

    Mirror spans (``heal.send``/``heal.recv``, per-chunk ``quant.chunk``,
    manager/store ``rpc.*``) join endpoints causally but are excluded
    from the sums — their cost is already inside a phase.  The step's
    critical path is the slowest replica's root; its dominant category is
    the step's answer to "what ate this step".  Any ``ok=false`` span
    names a culprit (signal ``trace_error``) with no other input needed.
    """
    by_trace: "Dict[str, List[Dict[str, Any]]]" = defaultdict(list)
    for s in spans:
        by_trace[str(s.get("trace_id"))].append(s)

    steps: "List[Dict[str, Any]]" = []
    culprit: "Optional[Dict[str, Any]]" = None
    for trace_id, sp in by_trace.items():
        roots = [s for s in sp if s.get("name") == "quorum_round"]
        if not roots:
            continue
        step = (roots[0].get("attributes") or {}).get("step")
        quorum_id = (roots[0].get("attributes") or {}).get("quorum_id")
        root_ids = {s.get("span_id"): s for s in roots}
        children: "Dict[str, List[Dict[str, Any]]]" = defaultdict(list)
        for s in sp:
            parent = s.get("parent_span_id")
            if parent in root_ids and s.get("name") != "quorum_round":
                children[parent].append(s)

        replicas: "Dict[str, Dict[str, Any]]" = {}
        for root in roots:
            attrs = root.get("attributes") or {}
            rid = str(attrs.get("replica_id", "?"))
            info = replicas.setdefault(
                rid,
                {
                    "wall_s": 0.0,
                    "categories": {},
                    "ok": True,
                    "spans": 0,
                    "failed_span": None,
                },
            )
            info["wall_s"] += _span_dur_s(root)
            if not root.get("ok", True):
                info["ok"] = False
                info["failed_span"] = info["failed_span"] or "quorum_round"
            cats: "Dict[str, float]" = info["categories"]
            phase_sums: "Dict[str, float]" = {}
            quant_seen = False
            lighthouse_wait = 0.0
            kids = children.get(root.get("span_id"), [])
            info["spans"] += 1 + len(kids)
            for c in kids:
                name = str(c.get("name"))
                cattrs = c.get("attributes") or {}
                if not c.get("ok", True):
                    info["ok"] = False
                    info["failed_span"] = info["failed_span"] or name
                if name in PHASE_CATEGORY:
                    phase_sums[name] = phase_sums.get(name, 0.0) + _span_dur_s(c)
                elif name == "quant.pipeline":
                    quant_seen = True
                    cats["codec"] = cats.get("codec", 0.0) + float(
                        cattrs.get("codec_s") or 0.0
                    )
                    cats["wire"] = cats.get("wire", 0.0) + float(
                        cattrs.get("wire_s") or 0.0
                    )
                elif name == "rpc.quorum" and cattrs.get("server") == "lighthouse":
                    lighthouse_wait += _span_dur_s(c)
                # mirror spans (heal.*, quant.chunk, other rpc.*): causal
                # join only — their cost is inside a phase already
            if quant_seen:
                phase_sums.pop("ring", None)
            if lighthouse_wait > 0.0:
                # the measured block-until-quorum wait replaces the phase;
                # quorum_wait only contributes any excess beyond it
                excess = max(phase_sums.get("quorum_wait", 0.0) - lighthouse_wait, 0.0)
                phase_sums["quorum_wait"] = excess
                cats["straggler-wait"] = (
                    cats.get("straggler-wait", 0.0) + lighthouse_wait
                )
            for cat, v in ledger_categories(phase_sums).items():
                cats[cat] = cats.get(cat, 0.0) + v

        for rid, info in replicas.items():
            # argmax over the already-categorized sums (NOT through
            # dominant_contributor, which maps phase names to categories)
            info["dominant"] = (
                max(info["categories"].items(), key=lambda kv: kv[1])[0]
                if info["categories"]
                and max(info["categories"].values()) > 0.0
                else None
            )
            info["categories"] = {
                k: round(v, 6) for k, v in sorted(info["categories"].items())
            }
            info["wall_s"] = round(info["wall_s"], 6)

        slowest = max(replicas.items(), key=lambda kv: kv[1]["wall_s"])
        # the slowest replica IS the step's critical path; its dominant
        # category answers "what ate this step" (same >0 guard as the
        # per-replica dominant — all-zero sums name nothing)
        dominant = (
            max(slowest[1]["categories"].items(), key=lambda kv: kv[1])[0]
            if slowest[1]["categories"]
            and max(slowest[1]["categories"].values()) > 0.0
            else None
        )
        starts = [int(s.get("start_ns") or 0) for s in roots]
        ends = [int(s.get("end_ns") or 0) for s in roots]
        steps.append(
            {
                "step": step,
                "quorum_id": quorum_id,
                "trace_id": trace_id,
                "wall_s": round((max(ends) - min(starts)) / 1e9, 6),
                "replicas": replicas,
                "critical_replica": slowest[0],
                "dominant": dominant,
            }
        )
    steps.sort(key=lambda s: (s["step"] is None, s["step"]))
    for s in steps:
        failed = [
            (rid, info)
            for rid, info in s["replicas"].items()
            if not info["ok"]
        ]
        if failed and culprit is None:
            # earliest failing step wins (later failures are cascade)
            rid, info = failed[0]
            culprit = {
                "replica_id": rid,
                "reason": (
                    f"trace: span {info['failed_span']!r} failed (ok=false) "
                    f"at step {s['step']}"
                ),
                "signal": "trace_error",
            }
    dominants = [s["dominant"] for s in steps if s["dominant"]]
    overall = (
        max(set(dominants), key=dominants.count) if dominants else None
    )
    return {
        "steps": steps,
        "spans": len(spans),
        "traces": len(by_trace),
        "dominant_overall": overall,
        "culprit": culprit,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_t(t_ns: int, t0_ns: int) -> str:
    return f"+{(t_ns - t0_ns) / 1e9:9.3f}s"


def render_text(
    entries: "List[Dict[str, Any]]",
    report: "Dict[str, Any]",
    warnings: "List[str]",
    max_rows: int = 200,
) -> str:
    out: "List[str]" = []
    culprit = report["culprit"]
    out.append("torchft-diagnose")
    out.append("=" * 60)
    if culprit:
        out.append(
            f"LIKELY CULPRIT: {culprit['replica_id']}  "
            f"[{culprit['signal']}]"
        )
        out.append(f"  {culprit['reason']}")
    else:
        out.append("LIKELY CULPRIT: none identified (no failure signature)")
    failure = report["failure"]
    if failure:
        out.append(
            f"FAILED PHASE: {failure['phase']} at step={failure['step']} "
            f"quorum_id={failure['quorum_id']} "
            f"(first reported by {failure['reported_by'] or '?'})"
        )
        if failure["detail"]:
            out.append(f"  detail: {failure['detail']}")
    for storm in report["retry_storms"]:
        out.append(
            f"RETRY STORM: {storm['retries']}x {storm['op']} "
            f"on {storm['replica_id'] or '?'}"
        )
    out.append("")
    out.append("replicas:")
    for rid, info in sorted(report["replicas"].items()):
        out.append(
            f"  {rid:32s} max_step={info['max_step']:<5d} "
            f"records={info['records']:<5d} errors={info['errors']}"
        )
    if warnings:
        out.append("")
        for w in warnings:
            out.append(f"warning: {w}")
    out.append("")
    out.append(f"timeline ({min(len(entries), max_rows)} of {len(entries)} entries):")
    t0 = entries[0]["t_ns"] if entries else 0
    shown = entries if len(entries) <= max_rows else entries[-max_rows:]
    for e in shown:
        step = e.get("step")
        q = e.get("quorum_id")
        ctx = f"step={step}" if step is not None else ""
        if q is not None:
            ctx += f" q={q}"
        marker = "!" if e["status"] in _ERROR_STATUSES else (
            "~" if e["status"] == "fault" else " ")
        out.append(
            f" {marker} {_fmt_t(e['t_ns'], t0)} {e['replica_id'][:28]:28s} "
            f"{e['op']:24s} {e['status']:8s} {ctx}"
        )
    return "\n".join(out)


def render_timeline_text(
    timeline: "Dict[str, Any]", max_rows: int = 30
) -> str:
    """The cluster step-timeline as a text section: one row per step
    bucket (replicas seen, wall span, codec/wire busy, slowest phase)
    plus the worst-straggler snapshot."""
    out: "List[str]" = []
    steps = timeline.get("steps") or []
    out.append(
        f"cluster timeline ({min(len(steps), max_rows)} of {len(steps)} "
        f"step buckets, ring {timeline.get('ring')}):"
    )
    for b in steps[-max_rows:]:
        phases = b.get("phases") or {}
        slowest = max(
            phases.items(), key=lambda kv: kv[1].get("mean_ms", 0.0), default=None
        )
        slow_txt = (
            f" slowest {slowest[0]} {slowest[1].get('mean_ms', 0.0):.1f}ms "
            f"(max {slowest[1].get('max_ms', 0.0):.1f})"
            if slowest
            else ""
        )
        busy = ""
        if b.get("codec_busy_s") or b.get("wire_busy_s"):
            busy = (
                f" codec {b.get('codec_busy_s', 0.0):.2f}s"
                f" wire {b.get('wire_busy_s', 0.0):.2f}s"
            )
        out.append(
            f"  step {b.get('step'):<6} replicas={b.get('replicas'):<4} "
            f"span={b.get('span_ms', 0)}ms{busy}{slow_txt}"
        )
    worst = timeline.get("stragglers_worst") or []
    if worst:
        out.append("worst stragglers (lighthouse snapshot):")
        for row in worst:
            out.append(
                f"  {str(row.get('replica_id', '?')):32s} "
                f"score={float(row.get('straggler_score') or 0.0):6.1f} "
                f"lag={row.get('step_lag')} "
                f"{'STALE' if row.get('stale') else 'fresh'} "
                f"op={row.get('inflight_op') or '-'}"
            )
    return "\n".join(out)


def render_links_text(
    links: "Dict[str, Any]",
    links_report: "Dict[str, Any]",
    max_rows: int = 15,
) -> str:
    """The fleet link matrix as a text section: worst WAN links first
    (goodput ascending), the fleet median for scale, and any sustained
    slow-link outliers called out."""
    out: "List[str]" = []
    rows = [
        r
        for r in (links.get("rows") or [])
        if isinstance(r, dict) and not r.get("local")
    ]
    rows.sort(key=lambda r: float(r.get("goodput_bps") or 0.0))
    med = float(links_report.get("median_wan_goodput_bps") or 0.0)
    out.append(
        f"fleet link matrix ({min(len(rows), max_rows)} of {len(rows)} WAN "
        f"links, {links_report.get('hosts')} hosts, "
        f"median {med / 1e6:.1f} MB/s):"
    )
    for r in rows[:max_rows]:
        g = float(r.get("goodput_bps") or 0.0)
        ratio = f" ({med / g:.1f}x below median)" if med > 0 < g < med else ""
        out.append(
            f"  {str(r.get('src', '?'))[:20]:20s} -> "
            f"{str(r.get('peer', '?'))[:20]:20s} {str(r.get('plane')):10s} "
            f"{g / 1e6:8.1f} MB/s  rtt p99 "
            f"{float(r.get('rtt_p99_ms') or 0.0):7.1f}ms  "
            f"samples={r.get('samples')}{ratio}"
        )
    for s in links_report.get("slow_links") or []:
        out.append(
            f"  SLOW LINK: {s['src']}->{s['peer']} ({s['plane']}) "
            f"{s['goodput_bps'] / 1e6:.1f} MB/s sustained over "
            f"{s['samples']} samples"
        )
    return "\n".join(out)


def render_fragment_text(
    frag_report: "Dict[str, Any]", max_rows: int = 60
) -> str:
    """One fragment's journey as a text section: every hold and hop in
    time order (holder, role, plane, digest verdict), the poisoned hop
    called out when a mismatch/torn verdict entered the plane."""
    out: "List[str]" = []
    journey = frag_report.get("journey") or []
    out.append(
        f"fragment journey {frag_report['frag']} "
        f"({frag_report.get('holds')} hold(s), "
        f"{frag_report.get('hops')} hop(s)):"
    )
    if not journey:
        out.append(
            "  no provenance records for this fragment — pass the .prov "
            "companion dumps written alongside TORCHFT_FLIGHT_FILE"
        )
        return "\n".join(out)
    t0 = min(e.get("start_ns") or e.get("t_ns") or 0 for e in journey)
    for e in journey[:max_rows]:
        f = e.get("fields") or {}
        t = _fmt_t(e.get("start_ns") or e.get("t_ns") or 0, t0)
        if e["op"] == "fragment.hold":
            out.append(
                f"  {t}  HELD v{f.get('version')!s:<4} by "
                f"{str(f.get('holder', '?'))[:28]:28s} "
                f"[{f.get('role', 'holder')}] "
                f"digest={f.get('digest8') or '-'}"
            )
        else:
            verdict = str(f.get("verdict", "ok"))
            out.append(
                f"  {t}  HOP  v{f.get('version')!s:<4} "
                f"{str(f.get('source', '?'))[:28]:28s} -> "
                f"{str(f.get('holder', '?'))[:28]:28s} "
                f"({f.get('plane')}) {verdict.upper()} "
                f"{f.get('bytes', 0)}B fb={f.get('first_byte_ms', 0)}ms"
            )
    poisoned = frag_report.get("poisoned_hop")
    if poisoned:
        out.append(
            f"  POISONED HOP: {poisoned.get('source')} -> "
            f"{poisoned.get('holder')} ({poisoned.get('plane')}) verdict="
            f"{poisoned.get('verdict')} at v{poisoned.get('version')} — "
            f"first hop where the digest broke"
        )
    return "\n".join(out)


def render_trace_text(trace_report: "Dict[str, Any]", max_rows: int = 30) -> str:
    """The per-step critical-path ledger as a text section: one row per
    step (wall, critical replica, dominant category, category split) plus
    per-replica dominants."""
    out: "List[str]" = []
    steps = trace_report.get("steps") or []
    out.append(
        f"critical-path ledger ({min(len(steps), max_rows)} of {len(steps)} "
        f"steps, {trace_report.get('spans')} spans):"
    )
    if trace_report.get("dominant_overall"):
        out.append(
            f"  dominant contributor overall: "
            f"{trace_report['dominant_overall']}"
        )
    for s in steps[-max_rows:]:
        cats = s["replicas"][s["critical_replica"]]["categories"]
        split = " ".join(
            f"{c}={cats.get(c, 0.0) * 1e3:.1f}ms"
            for c in LEDGER_CATEGORIES
            if cats.get(c)
        )
        out.append(
            f"  step {s['step']!s:<6} wall={s['wall_s'] * 1e3:8.1f}ms "
            f"critical={s['critical_replica'][:28]:28s} "
            f"dominant={s['dominant'] or '-':<14} {split}"
        )
        if "wire_excess_s" in s:
            out.append(
                f"      wire split vs fleet-median link: expected "
                f"{s['wire_expected_s'] * 1e3:.1f}ms + excess "
                f"{s['wire_excess_s'] * 1e3:.1f}ms "
                f"(slow link {s['wire_slow_link']})"
            )
        for rid, info in sorted(s["replicas"].items()):
            marker = " " if info["ok"] else "!"
            out.append(
                f"   {marker}  {rid[:30]:30s} wall={info['wall_s'] * 1e3:8.1f}ms "
                f"dominant={info['dominant'] or '-'}"
                + (
                    f" FAILED in {info['failed_span']}"
                    if not info["ok"]
                    else ""
                )
            )
    culprit = trace_report.get("culprit")
    if culprit:
        out.append(
            f"  trace culprit: {culprit['replica_id']} — {culprit['reason']}"
        )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------


def _synthetic_dumps(tmpdir: str) -> "Tuple[str, str]":
    """Two replicas: replica_b silently dies at step 3; replica_a's
    allreduce then fails.  Written in the exact flight-dump format."""
    t0 = time.time_ns()
    s = 1_000_000_000  # 1s in ns

    def rec(**kw: Any) -> "Dict[str, Any]":
        return {"flight": "rec", **kw}

    a_records: "List[Dict[str, Any]]" = []
    b_records: "List[Dict[str, Any]]" = []
    for step in range(4):
        for rid, records in (("replica_a:u1", a_records), ("replica_b:u2", b_records)):
            if rid.startswith("replica_b") and step >= 3:
                continue  # b died before step 3's collective
            base = t0 + step * s + (0 if rid.startswith("replica_a") else 10_000_000)
            records.append(
                rec(op="quorum_rpc", status="ok", start_ns=base,
                    end_ns=base + 5_000_000, replica_id=rid, step=step,
                    quorum_id=1, kind="phase")
            )
            records.append(
                rec(op="allreduce", status="ok", start_ns=base + 6_000_000,
                    end_ns=base + 9_000_000, replica_id=rid, step=step,
                    quorum_id=1, kind="collective", rank=0, world=2)
            )
    # b entered step 3's quorum then vanished
    b_base = t0 + 3 * s
    b_records.append(
        rec(op="quorum_rpc", status="ok", start_ns=b_base,
            end_ns=b_base + 5_000_000, replica_id="replica_b:u2", step=3,
            quorum_id=1, kind="phase")
    )
    # a's step-3 collective fails ~10s later (peer gone, deadline expired)
    a_fail = t0 + 13 * s
    a_records.append(
        rec(op="allreduce", status="error", start_ns=t0 + 3 * s,
            end_ns=a_fail, replica_id="replica_a:u1", step=3, quorum_id=1,
            kind="collective", rank=0, world=2,
            reason="collective failed: ConnectionError('peer closed connection')")
    )

    def write(name: str, records: "List[Dict[str, Any]]") -> str:
        path = os.path.join(tmpdir, name)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({
                "flight": "meta", "reason": "selftest", "trigger": "manual",
                "ts": t0 / 1e9, "pid": 0, "records": len(records),
            }) + "\n")
            for r in records:
                fh.write(json.dumps(r) + "\n")
        return path

    return write("replica_a.jsonl", a_records), write("replica_b.jsonl", b_records)


def _synthetic_prov_dump(tmpdir: str) -> str:
    """One ``.prov`` companion dump: fragment weights/0 publishes clean,
    relay_mid serves poisoned bytes (the client's digest check fires),
    and a downstream client sees the same mismatch — the exact trail the
    provenance registry dumps."""
    t0 = time.time_ns()
    ms = 1_000_000  # 1ms in ns
    records = [
        {"flight": "rec", "op": "fragment.hold", "status": "ok",
         "start_ns": t0, "end_ns": t0, "frag": "weights/0", "version": 7,
         "digest8": "aaaaaaaa", "version_ms": 1000, "holder": "pub:1",
         "role": "publisher"},
        {"flight": "rec", "op": "fragment.hop", "status": "ok",
         "start_ns": t0 + ms, "end_ns": t0 + 2 * ms, "frag": "weights/0",
         "version": 7, "source": "http://pub:1", "plane": "serving",
         "verdict": "ok", "bytes": 4096, "first_byte_ms": 0.4,
         "holder": "relay_mid:2"},
        {"flight": "rec", "op": "fragment.hop", "status": "error",
         "start_ns": t0 + 3 * ms, "end_ns": t0 + 4 * ms,
         "frag": "weights/0", "version": 7, "source": "http://relay_mid:2",
         "plane": "serving", "verdict": "mismatch", "bytes": 4096,
         "first_byte_ms": 0.6, "holder": "client:3"},
        {"flight": "rec", "op": "fragment.hop", "status": "error",
         "start_ns": t0 + 5 * ms, "end_ns": t0 + 6 * ms,
         "frag": "weights/0", "version": 7, "source": "http://client:3",
         "plane": "serving", "verdict": "mismatch", "bytes": 4096,
         "first_byte_ms": 0.5, "holder": "leaf:4"},
    ]
    path = os.path.join(tmpdir, "flight.jsonl.prov")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({
            "flight": "meta", "reason": "selftest", "trigger": "manual",
            "ts": t0 / 1e9, "pid": 0, "records": len(records),
        }) + "\n")
        for r in records:
            fh.write(json.dumps(r) + "\n")
    return path


def selftest(verbose: bool = True) -> bool:
    """Synthetic two-replica dump pair through the full pipeline; the
    culprit must be the silently-dead replica_b and the failed phase the
    surviving replica's collective.  A synthetic provenance dump then
    checks ``--fragment`` attribution: the FIRST mismatching hop's
    source (the mid-tree relay) must be the ``poisoned_hop`` culprit,
    not the downstream victims."""
    with tempfile.TemporaryDirectory() as tmpdir:
        dump_a, dump_b = _synthetic_dumps(tmpdir)
        entries, warnings = load_records([dump_a, dump_b])
        report = analyze(entries)
        prov_entries, prov_warnings = load_records(
            [_synthetic_prov_dump(tmpdir)]
        )
        frag_report = analyze_fragment(prov_entries, "weights/0")
    ok = True

    def check(cond: bool, what: str) -> None:
        nonlocal ok
        if not cond:
            ok = False
            print(f"selftest FAIL: {what}", file=sys.stderr)

    check(len(entries) > 0, "no entries parsed")
    check(not warnings, f"unexpected warnings: {warnings}")
    check(report["culprit"] is not None, "no culprit identified")
    if report["culprit"]:
        check(
            report["culprit"]["replica_id"].startswith("replica_b"),
            f"culprit {report['culprit']} is not replica_b",
        )
    check(
        report["failure"] is not None
        and report["failure"]["phase"] == "allreduce"
        and report["failure"]["step"] == 3,
        f"failure {report['failure']} is not allreduce@3",
    )
    check(not prov_warnings, f"prov warnings: {prov_warnings}")
    check(
        frag_report["hops"] == 3 and frag_report["holds"] == 1,
        f"fragment journey miscounted: {frag_report['hops']} hops, "
        f"{frag_report['holds']} holds",
    )
    check(
        frag_report["culprit"] is not None
        and frag_report["culprit"]["signal"] == "poisoned_hop"
        and frag_report["culprit"]["replica_id"] == "http://relay_mid:2",
        f"poisoned_hop culprit wrong: {frag_report['culprit']}",
    )
    check(
        bool(render_fragment_text(frag_report)),
        "fragment renderer produced nothing",
    )
    if ok and verbose:
        print(
            "selftest OK: culprit=replica_b, failed phase=allreduce@3, "
            "poisoned_hop=relay_mid"
        )
    return ok


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: "Optional[List[str]]" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="torchft-diagnose",
        description=(
            "Merge torchft flight dumps (TORCHFT_FLIGHT_FILE) and event "
            "logs (TORCHFT_EVENTS_FILE) into a cross-replica timeline and "
            "flag the likely culprit."
        ),
    )
    parser.add_argument("dumps", nargs="*", help="flight dump JSONL file(s)")
    parser.add_argument(
        "--events", action="append", default=[],
        help="TORCHFT_EVENTS_FILE JSONL log(s) to merge (repeatable)",
    )
    parser.add_argument(
        "--timeline", default=None, metavar="FILE_OR_URL",
        help="lighthouse /timeline.json (file, URL, or host:port) to fold "
        "into the report — names a straggler culprit even without dumps",
    )
    parser.add_argument(
        "--links", default=None, metavar="FILE_OR_URL",
        help="lighthouse /links.json (file, URL, or host:port) to fold "
        "into the report — names a sustained slow host-pair link "
        "(signal slow_link) and, with --trace, splits the ledger's wire "
        "cost into expected vs excess against the fleet-median link",
    )
    parser.add_argument(
        "--fragment", default=None, metavar="FRAG_ID",
        help="reconstruct this fragment's journey (frag_id like "
        "weights/0) from fragment.hold/fragment.hop provenance records "
        "in the given dumps (pass the TORCHFT_FLIGHT_FILE.prov "
        "companions as positional dumps) and name the hop where a "
        "digest mismatch first entered (signal poisoned_hop)",
    )
    parser.add_argument(
        "--trace", default=None, metavar="TRACE_FILE",
        help="distributed-tracing span sink (TORCHFT_TRACE_FILE JSONL): "
        "reconstructs the per-step cross-replica critical-path ledger "
        "(compute/codec/wire/protocol/straggler-wait) and names failing "
        "replicas from ok=false spans",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable JSON report"
    )
    parser.add_argument(
        "--max-rows", type=int, default=200,
        help="timeline rows shown in text output (default 200)",
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="synthetic two-replica attribution check (CI hook)",
    )
    args = parser.parse_args(argv)

    if args.selftest:
        return 0 if selftest() else 1
    if (
        not args.dumps
        and not args.events
        and not args.timeline
        and not args.trace
        and not args.links
    ):
        parser.print_usage(sys.stderr)
        print("torchft-diagnose: no input files", file=sys.stderr)
        return 2

    cluster_timeline: "Optional[Dict[str, Any]]" = None
    timeline_report: "Optional[Dict[str, Any]]" = None
    if args.timeline:
        try:
            cluster_timeline = load_timeline(args.timeline)
            timeline_report = analyze_timeline(cluster_timeline)
        except Exception as e:  # noqa: BLE001 - report, don't die mid-postmortem
            print(f"warning: --timeline {args.timeline}: {e}", file=sys.stderr)

    links_doc: "Optional[Dict[str, Any]]" = None
    links_report: "Optional[Dict[str, Any]]" = None
    if args.links:
        try:
            links_doc = load_links(args.links)
            links_report = analyze_links(links_doc)
        except Exception as e:  # noqa: BLE001 - report, don't die mid-postmortem
            print(f"warning: --links {args.links}: {e}", file=sys.stderr)

    trace_report: "Optional[Dict[str, Any]]" = None
    trace_warnings: "List[str]" = []
    if args.trace:
        spans, trace_warnings = load_spans(args.trace)
        if spans:
            trace_report = analyze_trace(spans)
        elif not trace_warnings:
            trace_warnings = [f"{args.trace}: no spans"]

    entries, warnings = load_records(list(args.dumps), list(args.events))
    warnings.extend(trace_warnings)
    if (
        not entries
        and timeline_report is None
        and trace_report is None
        and links_report is None
    ):
        for w in warnings:
            print(f"warning: {w}", file=sys.stderr)
        print("torchft-diagnose: no parseable records", file=sys.stderr)
        return 1
    report = analyze(entries)
    frag_report: "Optional[Dict[str, Any]]" = None
    if args.fragment:
        frag_report = analyze_fragment(entries, args.fragment)
    if trace_report is not None and links_report is not None:
        apply_wire_split(trace_report, links_report)
    # Culprit precedence: a poisoned fragment hop answers the question
    # --fragment explicitly asked, so it overrides everything when found;
    # otherwise flight-record signals see INSIDE a replica and win when
    # present; the trace ledger's ok=false spans are next (they also see
    # inside, but dumps carry the fault tags); the lighthouse timeline
    # sees the fleet from outside; the link matrix is last — a slow wire
    # is a degradation, not a failure, so any failure signature outranks
    # it.  All inputs join into one report.
    if frag_report is not None and frag_report["culprit"] is not None:
        report["culprit"] = frag_report["culprit"]
    if report["culprit"] is None and trace_report is not None:
        report["culprit"] = trace_report["culprit"]
    if report["culprit"] is None and timeline_report is not None:
        report["culprit"] = timeline_report["culprit"]
    if report["culprit"] is None and links_report is not None:
        report["culprit"] = links_report["culprit"]
    if timeline_report is not None:
        report["cluster_timeline"] = timeline_report
    if links_report is not None:
        report["link_matrix"] = links_report
    if frag_report is not None:
        report["fragment_journey"] = frag_report
    if trace_report is not None:
        report["trace_ledger"] = trace_report
    if args.json:
        payload = dict(report)
        payload["warnings"] = warnings
        payload["timeline"] = entries
        print(json.dumps(payload, indent=2, default=str))
    else:
        print(render_text(entries, report, warnings, max_rows=args.max_rows))
        if cluster_timeline is not None:
            print(render_timeline_text(cluster_timeline))
        if links_doc is not None and links_report is not None:
            print(render_links_text(links_doc, links_report))
        if frag_report is not None:
            print(render_fragment_text(frag_report))
        if trace_report is not None:
            print(render_trace_text(trace_report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
