"""Flash attention for TPU: fused tiled causal attention in Pallas.

The framework's hot-op kernel (the reference's hot ops are its Triton
quantization kernels, torchft/quantization.py:44-430; attention itself it
leaves to torch — on TPU the [T, T] score materialization is the dominant
HBM cost of the transformer, so this is where a Pallas kernel pays).

Standard FlashAttention-2 scheme, fwd + bwd:

- forward: one pass over K/V blocks per Q block with the online-softmax
  running (m, l) statistics in VMEM scratch; writes O and the per-row
  logsumexp L. Never materializes [T, T].
- backward: recomputes p = exp(q·kᵀ·scale − L) per tile from the saved L
  (no stored probabilities), accumulating dK/dV over Q blocks in one
  kernel and dQ over K/V blocks in another.
- causal block skipping: fully-masked tiles are skipped via ``pl.when``
  (half the FLOPs at long T), diagonal tiles masked elementwise.
- dtypes: matmuls run in the input dtype (bf16 on TPU) with f32
  accumulation; softmax statistics and accumulators are f32 scratch.

Layouts follow the guide (/opt/skills/guides/pallas_guide.md): blocks are
(sublane × lane)-aligned, row statistics ride a 128-lane minor dim.  Off
TPU every kernel runs in interpreter mode so the CPU test suite covers
the same code path.

Wired into the model as ``TransformerConfig(attn_impl="flash")``
(torchft_tpu/models/transformer.py); requires T % 128 == 0.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANE = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_size(t: int, d: int) -> int:
    """Largest tile that divides ``t`` — bigger tiles amortize the
    per-block softmax bookkeeping.  1024 engages only at head_dim <= 256
    (measured +3% whole-step at the d256 flagship; beyond d256 the
    q/k/v/acc tiles alone would crowd VMEM)."""
    sizes = (1024, 512, 256, 128) if d <= 256 else (512, 256, 128)
    for blk in sizes:
        if t % blk == 0:
            return blk
    raise ValueError(f"flash attention requires seq len % 128 == 0, got {t}")


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(
    offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s,
    *, scale, causal, blk_q, blk_k
):
    """offs_ref: SMEM int32 [2] = (q_offset, k_offset) GLOBAL positions of
    this call's first query/key row — the ring composition runs the kernel
    on local chunks whose causal relation depends on the shard offsets."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # causal: this tile is live unless every key position exceeds every
    # query position in the block
    needed = jnp.logical_or(
        not causal, k_off + j * blk_k <= q_off + i * blk_q + blk_q - 1
    )

    @pl.when(needed)
    def _():
        q = q_ref[0]
        s = jax.lax.dot_general(
            q,
            k_ref[0],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [blk_q, blk_k]
        if causal:
            rq = q_off + i * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            rk = k_off + j * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(rq >= rk, s, _NEG_INF)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        # A query row with zero live keys so far has m_new == _NEG_INF, so
        # s - m_new == 0 for every MASKED entry and p would be 1 — O would
        # become a garbage mean of V.  Zero p for such rows instead: l
        # stays 0, O resolves to 0 and lse to ~-inf, so callers passing
        # offsets (ring chunks where q precedes every k) get an exact
        # zero-weight chunk rather than relying on the combiner's
        # exp-underflow to hide it.
        p = jnp.where(m_new > _NEG_INF / 2, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_s[:] = jnp.broadcast_to(
            l_s[:, :1] * corr + p.sum(axis=1, keepdims=True), l_s.shape
        )
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(q.dtype),
            v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _():
        l = jnp.maximum(l_s[:, :1], 1e-30)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        # [blk, 1] column -> [1, blk] lane vector (Mosaic relayout)
        lse_ref[0] = (m_s[:, :1] + jnp.log(l)).reshape(1, -1)


def _fwd(
    q3: jax.Array,
    k3: jax.Array,
    v3: jax.Array,
    scale: float,
    causal: bool,
    offsets: "Optional[jax.Array]" = None,
) -> "Tuple[jax.Array, jax.Array]":
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    blk_q = _block_size(tq, d)
    blk_k = _block_size(tk, d)
    if offsets is None:
        offsets = jnp.zeros((2,), jnp.int32)
    grid = (bh, tq // blk_q, tk // blk_k)
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            # row stats as [bh, 1, t]: a (1, 1, blk) block keeps the
            # sublane dim equal to the array's (TPU block-shape rule) and
            # the per-row scalars on lanes — 128x less HBM than
            # broadcasting to a [bh, t, 128] stat plane
            pl.BlockSpec((1, 1, blk_q), lambda b, i, j: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, tq, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((blk_q, d), jnp.float32),
            pltpu.VMEM((blk_q, _LANE), jnp.float32),
            pltpu.VMEM((blk_q, _LANE), jnp.float32),
        ],
        interpret=_interpret(),
    )(offsets.astype(jnp.int32), q3, k3, v3)
    return o, lse[:, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _recompute_p(q, k, lse_row, scale, causal, q_pos0, k_pos0):
    """exp(q·kᵀ·scale − L) with the causal mask — shared by both bwd
    kernels.  lse_row: [1, blk_q] f32 lane vector (reshaped to a column
    here; Mosaic relayout).  q_pos0/k_pos0: GLOBAL position of the first
    row of each block."""
    lse_col = lse_row.reshape(-1, 1)  # lane vector -> column
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    p = jnp.exp(s - lse_col)
    if causal:
        rq = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
        rk = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
        p = jnp.where(rq >= rk, p, 0.0)
    return p


def _bwd_kv_kernel(
    offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, blk_q, blk_k,
):
    j = pl.program_id(1)  # K/V block (outer)
    i = pl.program_id(2)  # Q block (inner, accumulated)
    ni = pl.num_programs(2)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    needed = jnp.logical_or(
        not causal, q_off + i * blk_q + blk_q - 1 >= k_off + j * blk_k
    )

    @pl.when(needed)
    def _():
        q = q_ref[0]
        do = do_ref[0]
        p = _recompute_p(
            q, k_ref[0], lse_ref[0], scale, causal,
            q_off + i * blk_q, k_off + j * blk_k,
        )
        pt = p.astype(q.dtype)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == ni - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_q_kernel(
    offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_acc, *, scale, causal, blk_q, blk_k,
):
    i = pl.program_id(1)  # Q block (outer)
    j = pl.program_id(2)  # K/V block (inner, accumulated)
    nj = pl.num_programs(2)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    needed = jnp.logical_or(
        not causal, k_off + j * blk_k <= q_off + i * blk_q + blk_q - 1
    )

    @pl.when(needed)
    def _():
        q = q_ref[0]
        p = _recompute_p(
            q, k_ref[0], lse_ref[0], scale, causal,
            q_off + i * blk_q, k_off + j * blk_k,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd(
    q3, k3, v3, o3, lse, do3, scale: float, causal: bool,
    offsets: "Optional[jax.Array]" = None,
    delta: "Optional[jax.Array]" = None,
) -> "Tuple[jax.Array, jax.Array, jax.Array]":
    bh, tq, d = q3.shape
    tk = k3.shape[1]
    blk = _block_size(tq, d)
    blk_kk = _block_size(tk, d)
    n = tq // blk
    nk = tk // blk_kk
    if offsets is None:
        offsets = jnp.zeros((2,), jnp.int32)
    offsets = offsets.astype(jnp.int32)
    if delta is None:
        # delta_i = rowsum(dO * O): tiny elementwise pass, plain XLA
        delta = jnp.sum(
            do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1
        )
    delta = delta[:, None, :]  # [bh, 1, t]
    lse3 = lse[:, None, :]

    # kv kernel grid = (b, j, i): index maps receive (b, kv_block, q_block)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_kv_kernel, scale=scale, causal=causal, blk_q=blk,
            blk_k=blk_kk,
        ),
        grid=(bh, nk, n),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, blk, d), lambda b, jj, ii: (b, ii, 0)),     # q
            pl.BlockSpec((1, blk_kk, d), lambda b, jj, ii: (b, jj, 0)),  # k
            pl.BlockSpec((1, blk_kk, d), lambda b, jj, ii: (b, jj, 0)),  # v
            pl.BlockSpec((1, blk, d), lambda b, jj, ii: (b, ii, 0)),     # do
            pl.BlockSpec((1, 1, blk), lambda b, jj, ii: (b, 0, ii)),  # lse
            pl.BlockSpec((1, 1, blk), lambda b, jj, ii: (b, 0, ii)),  # delta
        ],
        out_specs=(
            pl.BlockSpec((1, blk_kk, d), lambda b, jj, ii: (b, jj, 0)),
            pl.BlockSpec((1, blk_kk, d), lambda b, jj, ii: (b, jj, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, tk, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), q3.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((blk_kk, d), jnp.float32),
            pltpu.VMEM((blk_kk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(offsets, q3, k3, v3, do3, lse3, delta)

    # q kernel grid = (b, i, j): index maps receive (b, q_block, kv_block)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_q_kernel, scale=scale, causal=causal, blk_q=blk,
            blk_k=blk_kk,
        ),
        grid=(bh, n, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, blk, d), lambda b, ii, jj: (b, ii, 0)),     # q
            pl.BlockSpec((1, blk_kk, d), lambda b, ii, jj: (b, jj, 0)),  # k
            pl.BlockSpec((1, blk_kk, d), lambda b, ii, jj: (b, jj, 0)),  # v
            pl.BlockSpec((1, blk, d), lambda b, ii, jj: (b, ii, 0)),     # do
            pl.BlockSpec((1, 1, blk), lambda b, ii, jj: (b, 0, ii)),  # lse
            pl.BlockSpec((1, 1, blk), lambda b, ii, jj: (b, 0, ii)),  # delta
        ],
        out_specs=pl.BlockSpec((1, blk, d), lambda b, ii, jj: (b, ii, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((blk, d), jnp.float32)],
        interpret=_interpret(),
    )(offsets, q3, k3, v3, do3, lse3, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# differentiable wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash3(q3, k3, v3, scale, causal):
    return _fwd(q3, k3, v3, scale, causal)[0]


def _flash3_fwd(q3, k3, v3, scale, causal):
    o, lse = _fwd(q3, k3, v3, scale, causal)
    return o, (q3, k3, v3, o, lse)


def _flash3_bwd(scale, causal, res, do3):
    q3, k3, v3, o3, lse = res
    return _bwd(q3, k3, v3, o3, lse, do3, scale, causal)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """Tiled fused causal attention, ``[B, T, H, D] -> [B, T, H, D]``.

    Drop-in for :func:`~torchft_tpu.ops.ring_attention.dense_attention`
    with O(T) memory instead of the O(T^2) score matrix.  GQA K/V with
    fewer heads are broadcast up (the kernel is per-head).  Requires
    ``T % 128 == 0``; other shapes should use ``dense_attention``.
    """
    b, t, h, d = q.shape
    if h % k.shape[2] != 0:
        raise ValueError(
            f"query heads {h} not a multiple of kv heads {k.shape[2]}"
        )
    k, v = _expand_gqa(k, v, h)
    scale = 1.0 / math.sqrt(d)
    out3 = _flash3(_to3(q), _to3(k), _to3(v), scale, causal)
    return _from3(out3, b, h)


__all__ = ["flash_attention"]


# ---------------------------------------------------------------------------
# ring composition: flash tiles inside sequence-parallel ring attention
# ---------------------------------------------------------------------------


def _to3(x: jax.Array) -> jax.Array:
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from3(x3: jax.Array, b: int, h: int) -> jax.Array:
    bh, t, d = x3.shape
    return x3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _expand_gqa(k: jax.Array, v: jax.Array, h: int):
    rep = h // k.shape[2]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_flash_local(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str, causal: bool = True
) -> jax.Array:
    """Per-shard ring attention with FLASH tiles: the K/V chunks rotate
    around the ``axis_name`` ring exactly like
    :func:`~torchft_tpu.ops.ring_attention.ring_attention_local`, but each
    (local-Q x visiting-KV) tile runs the fused Pallas kernel with global
    position offsets instead of materializing [T_local, T_local] scores —
    the single-chip flash memory/speed profile composed with cp sharding.

    Same contract as ring_attention_local: must run inside shard_map over
    ``axis_name``; q/k/v are local chunks [B, T_local, H, D] rotary-
    embedded with GLOBAL positions; GQA K/V rotate unexpanded.  Requires
    T_local % 128 == 0.  The backward pass re-rotates K/V and runs the
    flash bwd kernels per tile against the globally-combined logsumexp
    (the standard ring-attention backward), so [T, T] is never built in
    either direction.
    """
    o, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return o


def _ring_flash_fwd_impl(q, k, v, axis_name, causal):
    idx = jax.lax.axis_index(axis_name)
    size = jax.lax.axis_size(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    q3 = _to3(q)

    def step(carry, s):
        o3, lse, kc, vc = carry
        kv_idx = (idx - s) % size
        ke, ve = _expand_gqa(kc, vc, h)
        offs = jnp.stack([idx * tq, kv_idx * tk]).astype(jnp.int32)
        o_s, lse_s = _fwd(q3, _to3(ke), _to3(ve), scale, causal, offs)
        # blockwise softmax combination over chunks (f32)
        m = jnp.maximum(lse, lse_s)
        w1 = jnp.exp(lse - m)
        w2 = jnp.exp(lse_s - m)
        denom = jnp.maximum(w1 + w2, 1e-30)
        o3 = (
            o3.astype(jnp.float32) * (w1 / denom)[..., None]
            + o_s.astype(jnp.float32) * (w2 / denom)[..., None]
        )
        lse = m + jnp.log(denom)
        perm = [(r, (r + 1) % size) for r in range(size)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o3, lse, kc, vc), None

    # zeros derived from q carry its device-varying axis set (vma rule)
    o0 = jnp.zeros_like(q3, dtype=jnp.float32)
    lse0 = jnp.zeros((b * h, tq), jnp.float32) + (
        jnp.zeros_like(q3[:, :, 0]) + _NEG_INF
    )
    (o3, lse, _, _), _ = jax.lax.scan(
        step, (o0, lse0, k, v), jnp.arange(size)
    )
    return _from3(o3.astype(q.dtype), b, h), lse


def _ring_flash_fwd(q, k, v, axis_name, causal):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, causal, res, do):
    q, k, v, o, lse = res
    idx = jax.lax.axis_index(axis_name)
    size = jax.lax.axis_size(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    hkv = k.shape[2]
    rep = h // hkv
    scale = 1.0 / math.sqrt(d)
    q3, o3, do3 = _to3(q), _to3(o), _to3(do)
    # loop-invariant: rowsum(dO * O), computed once for all ring steps
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)

    def step(carry, s):
        dq3, kc, vc, dkc, dvc = carry
        kv_idx = (idx - s) % size
        ke, ve = _expand_gqa(kc, vc, h)
        offs = jnp.stack([idx * tq, kv_idx * tk]).astype(jnp.int32)
        dq_s, dk_s, dv_s = _bwd(
            q3, _to3(ke), _to3(ve), o3, lse, do3, scale, causal, offs,
            delta=delta,
        )
        dq3 = dq3 + dq_s.astype(jnp.float32)
        # fold expanded-head grads back onto the unexpanded K/V heads
        dk4 = _from3(dk_s, b, h).reshape(b, tk, hkv, rep, d).sum(3)
        dv4 = _from3(dv_s, b, h).reshape(b, tk, hkv, rep, d).sum(3)
        dkc = dkc + dk4.astype(jnp.float32)
        dvc = dvc + dv4.astype(jnp.float32)
        # K/V and their grad accumulators rotate together: after the full
        # cycle each chunk (and its accumulated grad) is home again
        perm = [(r, (r + 1) % size) for r in range(size)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
        return (dq3, kc, vc, dkc, dvc), None

    dq0 = jnp.zeros_like(q3, dtype=jnp.float32)
    dk0 = jnp.zeros_like(k, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v, dtype=jnp.float32)
    (dq3, _, _, dk_acc, dv_acc), _ = jax.lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(size)
    )
    return (
        _from3(dq3, b, h).astype(q.dtype),
        dk_acc.astype(k.dtype),
        dv_acc.astype(v.dtype),
    )


ring_flash_local.defvjp(_ring_flash_fwd, _ring_flash_bwd)

__all__.append("ring_flash_local")
