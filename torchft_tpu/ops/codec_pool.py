"""Worker pool that drives the GIL-releasing codec per row-block.

The quantized-collective codec (ops/quantization.py row-range surface,
native/quant.cc) is a pure memory-bandwidth kernel whose rows are
independent — per-row absmax, per-row scale.  A single Python thread can
therefore only ever use one core of it; this module fans a chunk's rows
across a small process-wide :class:`~concurrent.futures.ThreadPoolExecutor`
(``TORCHFT_QUANT_THREADS`` workers, default ``min(cores, 8)``), and the
native kernels release the GIL for the duration of each block, so the
codec scales across cores for BOTH wire formats (int8 and the fp8 RNE
encode / LUT decode leg).

Handoff is lock-free from the caller's perspective: tasks flow through
the executor's internal queue; completion is signalled through the
returned futures (no bespoke condition variables for the lock-discipline
pass to frown at).  Each collective carries a :class:`CodecTrace` that
tasks stamp with busy intervals — merged at the end into the true
codec-busy wall, the ``C`` of the overlap-efficiency gauge
``torchft_quant_overlap_efficiency`` (docs/observability.md).

The pool is sized once, at first use (``TORCHFT_QUANT_THREADS`` is read
then); it is shared by every collective and replica rank hosted in the
process, which keeps total codec concurrency at the machine's core
budget instead of multiplying per rank.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, List, Optional, Tuple

from torchft_tpu.utils.env import env_int

# Below this many rows a block is not worth a task handoff (~20 us of
# executor overhead vs ~10 us/64-row-block of codec at 2048 cols).
MIN_BLOCK_ROWS = 64

_executors: "dict[str, ThreadPoolExecutor]" = {}
_executor_lock = threading.Lock()


def pool_threads() -> int:
    """Configured codec worker count (``TORCHFT_QUANT_THREADS``)."""
    return env_int(
        "TORCHFT_QUANT_THREADS", min(os.cpu_count() or 1, 8), minimum=1
    )


def get_executor(lane: str = "tx") -> ThreadPoolExecutor:
    """Process-wide codec pool for one LANE, sized at first use.

    Two lanes exist so the receive side of the pipeline is never starved
    by the send side: ``tx`` runs capture work (quantize peer slices /
    own-slice copies — ALL chunks of a collective are enqueued at call
    time to honor the snapshot contract), ``rx`` runs reduce/requant and
    dequant blocks dispatched as wire ops complete.  On one FIFO pool,
    chunk 0's reduce would queue behind every later chunk's quantize and
    the wire would stall at two outstanding alltoalls in the codec-bound
    regime; separate lanes keep the advertised quantize(i+1) ∥ wire(i) ∥
    reduce(i-1) interleave live.  Both lanes share the machine through
    the OS scheduler (the kernels are GIL-free and memory-bound, so the
    brief 2x oversubscription degrades gracefully).
    """
    ex = _executors.get(lane)
    if ex is None:
        with _executor_lock:
            ex = _executors.get(lane)
            if ex is None:
                ex = ThreadPoolExecutor(
                    max_workers=pool_threads(),
                    thread_name_prefix=f"tft_codec_{lane}",
                )
                _executors[lane] = ex
    return ex


class CodecTrace:
    """Per-collective scratchpad for pipeline accounting and abort.

    ``intervals`` collects (start, end) perf-counter pairs from codec
    tasks (list.append is atomic under the GIL — no lock on the hot
    path); :meth:`busy_seconds` merges them into wall-clock during which
    at least one codec task was executing.  ``abort()`` makes remaining
    queued tasks no-ops so a failed collective drains its workers instead
    of burning cores on a result nobody will read.
    """

    def __init__(self) -> None:
        self.intervals: "List[Tuple[float, float]]" = []
        self.wire_intervals: "List[Tuple[float, float]]" = []
        self._aborted = threading.Event()

    def abort(self) -> None:
        self._aborted.set()

    @property
    def aborted(self) -> bool:
        return self._aborted.is_set()

    def add_wire(self, t0: float, t1: float) -> None:
        self.wire_intervals.append((t0, t1))

    @staticmethod
    def _merged(intervals: "List[Tuple[float, float]]") -> float:
        return merged_seconds(intervals)

    def busy_seconds(self) -> float:
        """Merged codec-busy wall across all tasks of this collective."""
        return self._merged(self.intervals)

    def wire_seconds(self) -> float:
        """Merged wire-busy wall (collective-op execution intervals)."""
        return self._merged(self.wire_intervals)


def merged_seconds(intervals: "List[Tuple[float, float]]") -> float:
    """Total seconds covered by the UNION of (start, end) intervals —
    concurrent busy windows must not double-count.  Shared by the codec
    trace (busy/wire walls) and the serving relay's cut-through
    occupancy gauge."""
    total = 0.0
    end = float("-inf")
    for t0, t1 in sorted(intervals):
        if t0 > end:
            total += t1 - t0
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total


def block_bounds(n_rows: int, min_rows: int = MIN_BLOCK_ROWS) -> "List[Tuple[int, int]]":
    """Split ``n_rows`` into up to ``pool_threads()`` contiguous blocks of
    at least ``min_rows`` rows (one block when too small to split)."""
    if n_rows <= 0:
        return []
    n_blocks = max(1, min(pool_threads(), n_rows // max(min_rows, 1) or 1))
    base, rem = divmod(n_rows, n_blocks)
    bounds = []
    start = 0
    for b in range(n_blocks):
        n = base + (1 if b < rem else 0)
        bounds.append((start, start + n))
        start += n
    return bounds


def run_blocks(
    n_rows: int,
    fn: "Callable[[int, int], None]",
    trace: "Optional[CodecTrace]" = None,
    min_rows: int = MIN_BLOCK_ROWS,
    lane: str = "tx",
) -> "List[Future]":
    """Fan ``fn(r0, r1)`` over row blocks on the codec pool.

    Returns the block futures (callers wait or chain completion).  Tasks
    observe ``trace.aborted`` (skip) and stamp busy intervals.  A block
    that raises carries its exception on the future — callers must
    surface it (the pipeline aborts on the first failed block).
    ``lane``: ``"tx"`` for capture work, ``"rx"`` for the
    wire-completion-driven reduce/dequant stages (see
    :func:`get_executor`).
    """
    executor = get_executor(lane)

    def task(r0: int, r1: int) -> None:
        if trace is not None and trace.aborted:
            return
        t0 = time.perf_counter()
        fn(r0, r1)
        if trace is not None:
            trace.intervals.append((t0, time.perf_counter()))

    return [
        executor.submit(task, r0, r1) for r0, r1 in block_bounds(n_rows, min_rows)
    ]
