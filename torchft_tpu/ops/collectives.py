"""Quantized collectives: 8-bit allreduce / reduce-scatter over the FT PG,
run as a chunked software pipeline that hides the codec behind the wire.

Analog of the reference's quantized collectives
(reference: torchft/collectives.py:159-415): quantize per-rank row-slices,
``alltoall`` the slices, locally dequant-reduce-requant the owned slice,
``allgather`` the reduced slices, dequantize.  Cuts DCN bytes ~4x for f32
gradients (int8 payload + f32 row scales) at the cost of quantization error
— the DiLoCo outer-gradient path is tolerant to this by design.

**Pipeline shape** (r5 found the monolithic form codec-bound: int8 sync
spent 83% of its wall in a single-threaded host codec while the NIC sat
idle).  The flat row-matrix is split into K chunks of
``TORCHFT_QUANT_CHUNK_ROWS`` rows (auto-sized to ~4 MiB of payload per
peer when unset), and the stages overlap the way DynamiQ / Prime PCCL
pipeline compressed collectives (PAPERS.md):

- quantize(chunk i+1)  ∥  alltoall(chunk i)  ∥  reduce-requant(chunk i-1)
  ∥  allgather/dequant of earlier chunks;
- the codec itself is row-blocked across a small worker pool
  (``TORCHFT_QUANT_THREADS``, ops/codec_pool.py) driving the GIL-releasing
  native kernels (native/quant.cc row-range entry points), so both wire
  formats scale across cores;
- wire buffers, accumulators and reduced pieces cycle through
  ``utils/bufpool.POOL`` — after the first collective of a given shape,
  steady-state allocation is zero.

Every rank submits the SAME fixed interleave of PG ops
(``a2a_0, a2a_1, ag_0, a2a_2, ag_1, …``) from a dedicated driver thread,
so the single-worker PG executes identical op sequences on every socket
(the collective-ordering contract); per-chunk stage readiness only gates
*when* the next submission happens, never its order.  That contract —
like every PG collective's — assumes ONE collective in flight per
process group at a time: a second concurrent quantized collective on the
same PG would interleave its driver's submissions timing-dependently and
desync the op streams across ranks.  The shipped callers respect this
(DiLoCo serializes fragment syncs; ``Manager.allreduce`` is issued from
the step protocol).  Chunking is by rows
and quantization is per-row, so chunked output is bit-identical to the
monolithic codec (K=1) on finite inputs — asserted for both wire formats
in tests/test_quantized_collectives.py.

Two bit-compatible quantizers feed the same wire format:

- **device path** (default for jax arrays on a TPU backend): the Pallas
  fused absmax-quantize kernel (torchft_tpu/ops/pallas_quant.py) runs in
  one launch *before* any host copy; the pipeline then copies each chunk's
  int8 payload + f32 row scales device→host as a capture task, so the
  PCIe hops overlap earlier chunks' sends;
- **host path** (native/numpy codec, torchft_tpu/ops/quantization.py) for
  host arrays or non-TPU backends.  A rank's OWN row-slice skips the
  codec entirely: it is captured straight into the chunk's f32
  accumulator at call time (zero codec time + zero quantization error on
  own data, and one fewer memory pass than the old snapshot-then-copy).

Observability: ``torchft_quant_codec_seconds`` /
``torchft_quant_wire_seconds`` histograms per stage,
``torchft_quant_overlap_efficiency`` gauge per collective, one flight
record per chunk per hop, and chaos injects mid-pipeline: the existing
``pg.allreduce`` site is consulted before every chunk's alltoall (no
step context — unconstrained rules fire), plus ``pg.allreduce.chunk``
with ``step`` = chunk index for deterministic per-hop targeting.

SUM and AVG only, floating-point inputs only (parity: reference
collectives.py:336-344).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from concurrent.futures import wait as futures_wait
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from torchft_tpu.ops import codec_pool as _cpool
from torchft_tpu.ops import quantization as q
from torchft_tpu.ops import topology as _topo
from torchft_tpu.parallel.process_group import (
    ProcessGroup,
    REDUCE_AVG,
    REDUCE_SUM,
)
from torchft_tpu.parallel.work import Work, completed_work
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import lockcheck as _lockcheck
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.bufpool import POOL as _POOL
from torchft_tpu.utils.env import env_int

# Auto chunk sizing: one chunk's per-peer int8/fp8 payload, when
# TORCHFT_QUANT_CHUNK_ROWS is unset.  ~4 MiB keeps per-message overhead
# (<0.1%) negligible while giving a flagship-scale fragment (~14k slice
# rows at 2048 cols) a pipeline depth of ~7.
_AUTO_CHUNK_PAYLOAD_BYTES = 4 << 20
# Runaway guard: a pathological TORCHFT_QUANT_CHUNK_ROWS=1 on a huge
# fragment must not turn one collective into 50k wire messages.
_MAX_CHUNKS = 1024


def _resolve_chunk_rows(slice_rows: int, cols: int) -> int:
    """Rows per pipeline chunk.  ``TORCHFT_QUANT_CHUNK_ROWS`` when set
    (>0), else auto from the wire-buffer size target.  Clamped to
    [ceil(slice_rows/_MAX_CHUNKS), slice_rows].  Like
    ``TORCHFT_QUANT_WIRE``, the knob must agree across ranks — divergent
    chunking desyncs the op streams and fails loudly mid-collective.
    The auto target is deliberately NOT scaled to the WAN
    bandwidth-delay product: growing chunks to hide per-message RTT
    also serializes the codec behind the wire (the overlap r5 built the
    pipeline for), and the latency bill is the hierarchical plan's to
    cut — by sending fewer inter-host messages, not bigger ones
    (docs/benchmarks.md §3d)."""
    rows = env_int("TORCHFT_QUANT_CHUNK_ROWS", 0, minimum=0)
    if rows <= 0:
        rows = max(_AUTO_CHUNK_PAYLOAD_BYTES // max(cols, 1), 1)
    rows = max(rows, -(-slice_rows // _MAX_CHUNKS))
    return max(1, min(rows, slice_rows))


def _chunk_bounds(n_rows: int, chunk_rows: int) -> "List[Tuple[int, int]]":
    return [
        (a, min(a + chunk_rows, n_rows)) for a in range(0, n_rows, chunk_rows)
    ]


def _check_world(received: "List[np.ndarray]", world: int, op: str) -> None:
    if len(received) != world:
        raise RuntimeError(
            f"{op} returned {len(received)} buffers for world {world} "
            "(degraded result from an error-swallowing PG?)"
        )


def _recycle_wire_bufs(
    send_bufs: "List[np.ndarray]",
    received: "List[np.ndarray]",
    my_rank: int,
    exclude: "Optional[np.ndarray]" = None,
) -> None:
    """Return dead wire buffers to the pool after a reduce consumed them.

    Send side: a packed buffer is drained to the sockets once the
    alltoall resolves — but a degraded (error-swallowing) PG can resolve
    with the INPUT arrays themselves, so anything aliased into
    ``received`` is skipped here and given exactly once below.  Receive
    side: id-deduped (any PG may alias slots); 0-byte own slots no-op in
    ``give``.  ``exclude``: a buffer already given elsewhere (the
    allgather path's own reduced piece) that must not be double-given
    even if a PG aliases it into the result.
    """
    for r, b in enumerate(send_bufs):
        if r != my_rank and not any(b is rcv for rcv in received):
            _POOL.give(b)
    seen_ids = set()
    for b in received:
        if b is not exclude and id(b) not in seen_ids:
            seen_ids.add(id(b))
            _POOL.give(b)


def _slice_rows(rows: int, world: int) -> "List[tuple[int, int]]":
    """Contiguous row ranges per rank (last rank takes the remainder)."""
    base = rows // world
    bounds = []
    start = 0
    for r in range(world):
        n = base + (1 if r < rows % world else 0)
        bounds.append((start, start + n))
        start += n
    return bounds


def _fill_tail(src: np.ndarray, tail: np.ndarray, g0: int, cols: int) -> None:
    """Fill a pool block for a chunk spanning the padded tail: whatever of
    the FLAT source remains past global row ``g0`` (including a partial
    last row), zero-filled beyond it."""
    flat = tail.ravel()
    avail = max(src.size - g0 * cols, 0)
    if avail > 0:
        flat[:avail] = src[g0 * cols :]
    flat[avail:] = 0.0


class _ChunkPipeline:
    """Shared state + driver of one chunked quantized collective.

    Thread roles:

    - **caller thread**: captures the contribution (quantizes peer
      slices / copies the own slice into per-chunk accumulators) by
      fanning row blocks onto the codec pool, then blocks until every
      capture task ran — the call-time-snapshot contract: the caller may
      mutate its arrays the moment the submit returns;
    - **driver thread** (one per collective): submits every PG op in the
      fixed global interleave, gated on stage futures;
    - **codec pool** (process-wide): row-block tasks — pure compute,
      never blocks, so abort always drains;
    - **PG worker**: completion callbacks only timestamp, recycle and
      dispatch the next codec stage — they never block the wire.
    """

    def __init__(
        self,
        pg: ProcessGroup,
        collective: str,
        wire_dtype: str,
        divisor: int,
        cols: int,
        chunks: "List[Tuple[int, int]]",
    ) -> None:
        self.pg = pg
        self.collective = collective
        self.wire_dtype = wire_dtype
        self.divisor = divisor
        self.cols = cols
        self.chunks = chunks
        self.my_rank = pg.rank()
        self.world = pg.size()
        self.trace = _cpool.CodecTrace()
        k = len(chunks)
        self.ready: "List[Future]" = [Future() for _ in range(k)]
        self.reduce_done: "List[Future]" = [Future() for _ in range(k)]
        self.dequant_done: "List[Future]" = [Future() for _ in range(k)]
        self.send_bufs: "List[Optional[List[np.ndarray]]]" = [None] * k
        self.accs: "List[Optional[np.ndarray]]" = [None] * k
        self.pieces: "List[Optional[np.ndarray]]" = [None] * k
        self.out_fut: Future = Future()
        self.error: "Optional[BaseException]" = None
        self._latch_lock = _lockcheck.lock("quant.pipeline_latch")
        self._last_wire_done: "Optional[float]" = None
        # per-hop wire-busy accounting (PG worker thread only — the
        # single-worker FIFO serializes every completion callback)
        self.hop_wire_s: "Dict[str, float]" = {}
        self.t_call = time.perf_counter()
        # Distributed tracing: capture the submitting thread's context
        # (the Manager's round) at construction — completion callbacks
        # run on PG-worker/driver threads, where the thread-local is not
        # bound.  Per-chunk/per-hop child spans mirror the quant.chunk
        # flight records; None when tracing is off or the step unsampled.
        self.trace_ctx = _tracing.get_current()
        # per-wait budget: each PG op enforces its own deadline
        # (pg._timeout), so a stage future unresolved past that plus grace
        # means a lost callback, not a slow wire
        self.op_timeout = float(getattr(pg, "_timeout", 60.0)) + 30.0
        self.stats: "Dict[str, Any]" = {"n_chunks": k, "wire": wire_dtype}
        self.codec_s_box = [0.0]

    # -- error funnel ----------------------------------------------------

    def abort(self, exc: BaseException) -> None:
        """First error wins; queued codec tasks become no-ops; every
        pending stage future (and the result) fails so no waiter hangs."""
        first = False
        with self._latch_lock:
            if self.error is None:
                self.error = exc
                first = True
        if not first:
            return
        self.trace.abort()
        _flightrec.record(
            "quant.pipeline",
            status="error",
            collective=self.collective,
            wire=self.wire_dtype,
            chunks=len(self.chunks),
            error=repr(exc),
        )
        # failed-collective span (ok=false): the trace ledger names the
        # aborting replica from this alone
        tracer = _tracing.get_tracer()
        ctx = self.trace_ctx
        if tracer is not None and ctx is not None:
            end_ns = time.time_ns()
            tracer.export_span(
                name="quant.pipeline",
                trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id,
                start_ns=end_ns
                - int((time.perf_counter() - self.t_call) * 1e9),
                end_ns=end_ns,
                attributes={
                    "collective": self.collective,
                    "wire": self.wire_dtype,
                    "error": repr(exc),
                },
                ok=False,
            )
        for futs in self._stage_future_lists():
            for f in futs:
                try:
                    f.set_exception(exc)
                except Exception:  # noqa: BLE001 - already resolved
                    pass
        try:
            self.out_fut.set_exception(exc)
        except Exception:  # noqa: BLE001 - already resolved
            pass

    def _stage_future_lists(self) -> "Tuple[List[Future], ...]":
        """Every stage-future list ``abort`` must fail so no waiter
        hangs; plan pipelines extend this with their hop stages."""
        return (self.ready, self.reduce_done, self.dequant_done)

    def _await(self, fut: Future) -> None:
        try:
            fut.result(timeout=self.op_timeout)
        except FuturesTimeoutError:
            exc = TimeoutError(
                f"quantized {self.collective} pipeline stage did not "
                f"resolve within {self.op_timeout:.0f}s"
            )
            self.abort(exc)
            raise exc from None

    # -- stage plumbing --------------------------------------------------

    def chain(
        self, futs: "List[Future]", done_cb: "Callable[[], None]",
        stage_fut: Future,
    ) -> None:
        """When every codec future succeeds, run ``done_cb`` then resolve
        ``stage_fut``; the first failure aborts the pipeline."""
        remaining = [len(futs)]

        def _one(f: Future) -> None:
            exc = f.exception()
            if exc is not None:
                self.abort(exc)
                return
            with self._latch_lock:
                remaining[0] -= 1
                last = remaining[0] == 0
            if last:
                try:
                    done_cb()
                    stage_fut.set_result(None)
                except BaseException as e:  # noqa: BLE001 - funnel
                    self.abort(e)

        if not futs:
            try:
                done_cb()
                stage_fut.set_result(None)
            except BaseException as e:  # noqa: BLE001 - funnel
                self.abort(e)
            return
        for f in futs:
            f.add_done_callback(_one)

    def submit_wire(
        self, op: str, hop: str, k: int, work: Work, nbytes: int,
        submit_t: float, on_ok: "Callable[[Any], None]",
    ) -> None:
        """Attach the wire-accounting completion callback to a PG op: the
        op's *execution* interval is [max(submit, previous completion),
        completion] — exact under the PG's single-worker FIFO.  ``op`` is
        the PG primitive (alltoall/allgather/send/recv/sendrecv), ``hop``
        the reduction-plan stage it serves (``flat`` on the flat
        schedule; ``intra.*``/``inter.*`` on hierarchical plans)."""

        def _cb(f: Future) -> None:
            t1 = time.perf_counter()
            prev = self._last_wire_done
            t0 = submit_t if prev is None else max(submit_t, prev)
            self._last_wire_done = t1
            wire_s = max(t1 - t0, 0.0)
            if t1 > t0:
                self.trace.add_wire(t0, t1)
            self.hop_wire_s[hop] = self.hop_wire_s.get(hop, 0.0) + wire_s
            _metrics.QUANT_WIRE_SECONDS.labels(
                op=op, hop=hop, wire=self.wire_dtype
            ).observe(wire_s)
            exc = f.exception()
            _flightrec.record(
                "quant.chunk",
                status="ok" if exc is None else "error",
                collective=self.collective,
                pg_op=op,
                hop=hop,
                chunk=k,
                chunks=len(self.chunks),
                nbytes=nbytes,
                wire_s=round(wire_s, 6),
                **({"error": repr(exc)} if exc is not None else {}),
            )
            # one child span per (chunk, hop) wire op, mirroring the
            # flight record — the trace-ledger's wire attribution
            tracer = _tracing.get_tracer()
            ctx = self.trace_ctx
            if tracer is not None and ctx is not None:
                end_ns = time.time_ns()
                tracer.export_span(
                    name="quant.chunk",
                    trace_id=ctx.trace_id,
                    parent_span_id=ctx.span_id,
                    start_ns=end_ns - int(wire_s * 1e9),
                    end_ns=end_ns,
                    attributes={
                        "collective": self.collective,
                        "pg_op": op,
                        "hop": hop,
                        "chunk": k,
                        "nbytes": nbytes,
                    },
                    ok=exc is None,
                )
            if exc is not None:
                self.abort(exc)
                return
            try:
                on_ok(f.result())
            except BaseException as e:  # noqa: BLE001 - funnel
                self.abort(e)

        work.get_future().add_done_callback(_cb)

    # -- stages ----------------------------------------------------------

    def submit_alltoall(self, k: int) -> None:
        bufs = self.send_bufs[k]
        assert bufs is not None
        nbytes = sum(
            b.nbytes for r, b in enumerate(bufs) if r != self.my_rank
        )
        t = time.perf_counter()
        self.submit_wire(
            "alltoall", "flat", k, self.pg.alltoall(bufs), nbytes, t,
            lambda received: self.on_alltoall(k, received),
        )

    def on_alltoall(self, k: int, received: "List[np.ndarray]") -> None:
        """Dispatch chunk ``k``'s dequant-reduce(-requant) row blocks (PG
        worker thread: enqueue only, never compute)."""
        _check_world(received, self.world, "alltoall")
        a, b = self.chunks[k]
        ck = b - a
        acc = self.accs[k]
        if acc is not None:
            # host path: acc pre-filled with the own slice at capture
            bufs = [r for i, r in enumerate(received) if i != self.my_rank]
            overwrite_first = False
        else:
            # device path: every slot (own included) is a wire buffer
            bufs = received
            acc = _POOL.take((ck, self.cols), np.float32)
            self.accs[k] = acc
            overwrite_first = True
        # one header check per received buffer (the loud cross-rank
        # wire-format guard), hoisted off the per-row-block hot path
        for buf in bufs:
            q.validate_packed(buf, self.wire_dtype)
        requant = self.collective == "allreduce"
        piece: "Optional[np.ndarray]" = None
        if requant:
            piece = q.new_packed(ck, self.cols, self.wire_dtype, pool=_POOL)
            self.pieces[k] = piece
        t_red = time.perf_counter()

        def block(r0: int, r1: int) -> None:
            ow = overwrite_first
            for buf in bufs:
                q.fma_rows_packed(
                    buf, ck, self.cols, r0, r1, self.wire_dtype,
                    acc, r0, overwrite=ow,
                )
                ow = False
            if self.divisor:
                q.div_rows(acc, r0, r1, self.divisor)
            if requant:
                q.quantize_rows_packed(
                    acc, r0, piece, ck, self.cols, r0, r1, self.wire_dtype
                )

        # rx lane: never queued behind pending capture (tx) work, so the
        # reduce starts the moment the chunk lands even while later
        # chunks are still quantizing
        futs = _cpool.run_blocks(ck, block, self.trace, lane="rx")

        def done() -> None:
            _metrics.QUANT_CODEC_SECONDS.labels(
                stage="reduce", wire=self.wire_dtype
            ).observe(time.perf_counter() - t_red)
            send = self.send_bufs[k]
            if send is not None:
                _recycle_wire_bufs(send, received, self.my_rank)
                self.send_bufs[k] = None
            if requant:
                # allreduce: acc is scratch once requantized into piece
                _POOL.give(self.accs[k])
                self.accs[k] = None
            # reduce_scatter: acc IS the caller's output region — keep it

        self.chain(futs, done, self.reduce_done[k])

    def submit_allgather(self, k: int, full_mat: np.ndarray,
                         bounds: "List[Tuple[int, int]]") -> None:
        piece = self.pieces[k]
        assert piece is not None
        nbytes = (self.world - 1) * piece.nbytes
        t = time.perf_counter()
        self.submit_wire(
            "allgather", "flat", k, self.pg.allgather(piece), nbytes, t,
            lambda gathered: self.on_allgather(k, gathered, full_mat, bounds),
        )

    def on_allgather(
        self, k: int, gathered: "List[np.ndarray]", full_mat: np.ndarray,
        bounds: "List[Tuple[int, int]]",
    ) -> None:
        """Dequantize every rank's reduced piece straight into its offset
        of the full output matrix (PG worker thread: enqueue only)."""
        _check_world(gathered, self.world, "allgather")
        for gbuf in gathered:
            q.validate_packed(gbuf, self.wire_dtype)
        a, b = self.chunks[k]
        ck = b - a
        t_dq = time.perf_counter()
        futs: "List[Future]" = []
        for r, gbuf in enumerate(gathered):
            base = bounds[r][0] + a

            def block(r0: int, r1: int, gbuf=gbuf, base=base) -> None:
                q.dequant_rows_into(
                    gbuf, ck, self.cols, r0, r1, self.wire_dtype,
                    full_mat, base + r0,
                )

            futs += _cpool.run_blocks(ck, block, self.trace, lane="rx")

        def done() -> None:
            _metrics.QUANT_CODEC_SECONDS.labels(
                stage="dequant", wire=self.wire_dtype
            ).observe(time.perf_counter() - t_dq)
            piece = self.pieces[k]
            _POOL.give(piece)
            self.pieces[k] = None
            _recycle_wire_bufs([], gathered, self.my_rank, exclude=piece)

        self.chain(futs, done, self.dequant_done[k])

    # -- capture (caller thread) ----------------------------------------

    def capture_chunk(
        self, k: int, futs: "List[Future]", give_after: "List[np.ndarray]",
        t_cap: float,
    ) -> None:
        """Latch chunk ``k``'s capture tasks into ``ready[k]``."""

        def done() -> None:
            _metrics.QUANT_CODEC_SECONDS.labels(
                stage="quantize", wire=self.wire_dtype
            ).observe(time.perf_counter() - t_cap)
            for blk in give_after:
                _POOL.give(blk)

        self.chain(futs, done, self.ready[k])

    def capture_host_chunks(
        self,
        bounds: "List[Tuple[int, int]]",
        source_rows: np.ndarray,
        acc_for_chunk: "Callable[[int, int, int], np.ndarray]",
        src_flat: "Optional[np.ndarray]" = None,
        full_rows: "Optional[int]" = None,
    ) -> "List[Future]":
        """Caller-thread capture for the host codec path: per chunk,
        quantize every peer slice into packed pool buffers and copy the
        own slice into its accumulator (the call-time snapshot).

        ``source_rows``: C-contiguous f32 ``(*, cols)`` the slices read
        from.  ``src_flat``/``full_rows``: when set, chunks whose global
        rows extend past ``full_rows`` read a zero-padded pool tail block
        filled from the flat source (the allreduce's padded row matrix).
        ``acc_for_chunk(k, a, b)``: the chunk's f32 accumulator — a pool
        block for the allreduce, a region of the caller-visible output
        for the reduce-scatter.  Returns the capture futures for
        :meth:`wait_captured`.
        """
        futs_all: "List[Future]" = []
        for k, (a, b) in enumerate(self.chunks):
            ck = b - a
            t_cap = time.perf_counter()
            bufs_k: "List[np.ndarray]" = []
            futs_k: "List[Future]" = []
            give_after: "List[np.ndarray]" = []
            for r in range(self.world):
                g0 = bounds[r][0] + a
                if full_rows is not None and g0 + ck > full_rows:
                    tail = _POOL.take((ck, self.cols), np.float32)
                    give_after.append(tail)
                    _fill_tail(src_flat, tail, g0, self.cols)
                    block_src, row0 = tail, 0
                else:
                    block_src, row0 = source_rows, g0
                if r == self.my_rank:
                    # own slice: captured straight into the chunk's f32
                    # accumulator — no codec time, no quantization error
                    # on own data, and the reduce fma-accumulates into it
                    # in place (one fewer pass than snapshot-then-copy)
                    acc = acc_for_chunk(k, a, b)
                    self.accs[k] = acc

                    def copy_own(
                        r0: int, r1: int, acc=acc, bs=block_src, row0=row0
                    ) -> None:
                        np.copyto(acc[r0:r1], bs[row0 + r0 : row0 + r1])

                    futs_k += _cpool.run_blocks(ck, copy_own, self.trace)
                    bufs_k.append(np.empty(0, dtype=np.uint8))
                else:
                    buf = q.new_packed(
                        ck, self.cols, self.wire_dtype, pool=_POOL
                    )
                    bufs_k.append(buf)

                    def quant_peer(
                        r0: int, r1: int, buf=buf, bs=block_src, row0=row0,
                        ck=ck,
                    ) -> None:
                        q.quantize_rows_packed(
                            bs, row0 + r0, buf, ck, self.cols, r0, r1,
                            self.wire_dtype,
                        )

                    futs_k += _cpool.run_blocks(ck, quant_peer, self.trace)
            self.send_bufs[k] = bufs_k
            self.capture_chunk(k, futs_k, give_after, t_cap)
            futs_all += futs_k
        return futs_all

    # -- driver ----------------------------------------------------------

    def drive(
        self,
        on_finish: "Callable[[], Any]",
        full_mat: "Optional[np.ndarray]" = None,
        bounds: "Optional[List[Tuple[int, int]]]" = None,
    ) -> None:
        """Driver-thread body: every PG op in the fixed global interleave
        (``a2a_0, a2a_1, ag_0, a2a_2, ag_1, …``), gated on stage futures.
        The allgather leg runs when ``full_mat``/``bounds`` are given
        (allreduce); without them the pipeline ends at the reduces
        (reduce-scatter).  ``on_finish`` assembles the result after the
        last stage."""
        try:
            n = len(self.chunks)
            allgather = full_mat is not None
            for k in range(n):
                if self.error is not None:
                    return
                # chaos mid-pipeline (docs/robustness.md): the existing
                # pg.allreduce site is consulted per chunk WITHOUT step
                # context, so unconstrained rules (prob/times) inject
                # mid-pipeline while step-constrained rules keep their
                # training-step meaning; pg.allreduce.chunk carries the
                # CHUNK index for deterministic per-hop targeting.
                _faults.check("pg.allreduce")
                _faults.check("pg.allreduce.chunk", step=k)
                self._await(self.ready[k])
                self.submit_alltoall(k)
                if allgather and k >= 1:
                    self._await(self.reduce_done[k - 1])
                    self.submit_allgather(k - 1, full_mat, bounds)
            if allgather:
                self._await(self.reduce_done[n - 1])
                self.submit_allgather(n - 1, full_mat, bounds)
                waits = self.dequant_done
            else:
                waits = self.reduce_done
            for fut in waits:
                self._await(fut)
            self.finish_stats()
            self.out_fut.set_result(on_finish())
        except BaseException as e:  # noqa: BLE001 - funnel
            self.abort(e)

    def start_driver(
        self,
        on_finish: "Callable[[], Any]",
        full_mat: "Optional[np.ndarray]" = None,
        bounds: "Optional[List[Tuple[int, int]]]" = None,
    ) -> None:
        threading.Thread(
            target=self.drive,
            args=(on_finish, full_mat, bounds),
            name="tft_quant_pipeline",
            daemon=True,
        ).start()

    def wait_captured(self, futs: "List[Future]") -> None:
        """Block the caller until its contribution is fully captured —
        the call-time-snapshot contract.  A capture failure surfaces
        synchronously, like the monolithic codec's did."""
        futures_wait(futs, timeout=self.op_timeout)
        for f in futs:
            if not f.done():
                exc: BaseException = TimeoutError(
                    "codec pool did not capture the contribution in time"
                )
                self.abort(exc)
                raise exc
            e = f.exception()
            if e is not None:
                self.abort(e)
                raise e

    # -- finish ----------------------------------------------------------

    def finish_stats(self) -> None:
        """Compute the overlap accounting and publish it (driver thread,
        after the last stage)."""
        wall = time.perf_counter() - self.t_call
        codec_s = self.trace.busy_seconds()
        wire_s = self.trace.wire_seconds()
        floor = min(codec_s, wire_s)
        efficiency = (
            1.0
            if floor <= 0.0
            else max(0.0, min(1.0, (codec_s + wire_s - wall) / floor))
        )
        self.codec_s_box[0] = codec_s
        self.stats.update(
            wall_s=wall,
            codec_s=codec_s,
            wire_s=wire_s,
            overlap_efficiency=efficiency,
            hop_wire_s={
                h: round(v, 6) for h, v in sorted(self.hop_wire_s.items())
            },
        )
        _metrics.QUANT_OVERLAP_EFFICIENCY.labels(wire=self.wire_dtype).set(
            efficiency
        )
        _flightrec.record(
            "quant.pipeline",
            collective=self.collective,
            wire=self.wire_dtype,
            chunks=len(self.chunks),
            wall_s=round(wall, 6),
            codec_s=round(codec_s, 6),
            wire_s=round(wire_s, 6),
            overlap_efficiency=round(efficiency, 4),
        )
        # collective-level span: carries the codec/wire busy split the
        # trace ledger uses to attribute this wall time to codec vs wire
        tracer = _tracing.get_tracer()
        ctx = self.trace_ctx
        if tracer is not None and ctx is not None:
            end_ns = time.time_ns()
            tracer.export_span(
                name="quant.pipeline",
                trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id,
                start_ns=end_ns - int(wall * 1e9),
                end_ns=end_ns,
                attributes={
                    "collective": self.collective,
                    "wire": self.wire_dtype,
                    "chunks": len(self.chunks),
                    "codec_s": round(codec_s, 6),
                    "wire_s": round(wire_s, 6),
                    "overlap_efficiency": round(efficiency, 4),
                },
            )


class _HierPipeline(_ChunkPipeline):
    """Topology-aware multi-hop pipeline: executes a synthesized
    :class:`~torchft_tpu.ops.topology.ReductionPlan` per chunk instead of
    the flat alltoall/allgather schedule.

    Rows are sliced per *group* (slice ``j`` owned by group ``j``'s
    leader); a chunk covers rows ``[a, b)`` of every slice at once, so
    one chunk's working set is a stacked ``(m*ck, cols)`` block.  Hops
    per chunk (ops/topology.py module docstring): ``intra.reduce`` →
    ``inter.exchange`` → ``inter.gather`` → ``intra.bcast``, with
    requantization at each hop boundary.  The driver staggers hops
    across chunks (intra hops of chunk k overlap inter wire of chunk
    k-1), submitting every rank's ops in the same global (chunk, hop)
    interleave so per-socket op streams stay consistent.

    All ranks dequantize the same reduced-piece bytes at the end, so the
    result is bit-identical across every rank of the collective — the
    property the hierarchical golden fixture pins.
    """

    def __init__(
        self,
        pg: ProcessGroup,
        wire_dtype: str,
        divisor: int,
        cols: int,
        chunks: "List[Tuple[int, int]]",
        plan: Any,
        bounds: "List[Tuple[int, int]]",
        full_mat: np.ndarray,
    ) -> None:
        super().__init__(pg, "allreduce", wire_dtype, divisor, cols, chunks)
        self.plan = plan
        self.topo = plan.topology
        self.m = self.topo.n_groups
        self.gidx = plan.group_index
        self.is_leader = plan.is_leader
        self.leader_rank = self.topo.leader(self.gidx)
        self.bounds = bounds
        self.full_mat = full_mat
        k = len(chunks)
        # hop-stage futures (the driver's gates); abort fails them all
        self.s1 = [Future() for _ in range(k)]  # intra reduce complete
        self.s2 = [Future() for _ in range(k)]  # own slice reduced+requant
        self.s3 = [Future() for _ in range(k)]  # all pieces held
        self.s4 = [Future() for _ in range(k)]  # chunk dequantized
        self._s1_bufs: "List[List[Optional[np.ndarray]]]" = [[] for _ in range(k)]
        self._s1_remaining = [0] * k
        self._exch_recv: "List[List[Optional[np.ndarray]]]" = [[] for _ in range(k)]
        self._s2_remaining = [0] * k
        self._pieces_all: "List[List[Optional[np.ndarray]]]" = [
            [None] * self.m for _ in range(k)
        ]
        self._s3_remaining = [0] * k
        self._s4_parts = [0] * k
        self._s4_send_remaining = [0] * k
        self.stats["topology"] = self.topo.describe()
        self.stats["plan"] = plan.describe()

    def _stage_future_lists(self) -> "Tuple[List[Future], ...]":
        return super()._stage_future_lists() + (
            self.s1, self.s2, self.s3, self.s4,
        )

    # -- hop 1: intra.reduce ---------------------------------------------

    def submit_intra_reduce(self, k: int) -> None:
        a, b = self.chunks[k]
        ck = b - a
        rows = self.m * ck
        if not self.is_leader:
            bufs = self.send_bufs[k]
            assert bufs is not None
            buf = bufs[0]
            t = time.perf_counter()
            self.submit_wire(
                "send", "intra.reduce", k,
                self.pg.send(buf, self.leader_rank, tag=4 * k),
                buf.nbytes, t,
                lambda _res, k=k, buf=buf: self._intra_send_done(k, buf),
            )
            return
        members = self.plan.hops[0].recvs
        if not members:
            self._intra_reduce_ready(k, [])
            return
        with self._latch_lock:
            self._s1_remaining[k] = len(members)
            self._s1_bufs[k] = [None] * len(members)
        nbytes = q.packed_nbytes(rows, self.cols)
        for i, rm in enumerate(members):
            t = time.perf_counter()
            self.submit_wire(
                "recv", "intra.reduce", k, self.pg.recv(rm, tag=4 * k),
                nbytes, t,
                lambda buf, k=k, i=i: self._intra_recv_one(k, i, buf),
            )

    def _intra_send_done(self, k: int, buf: np.ndarray) -> None:
        _POOL.give(buf)
        self.send_bufs[k] = None
        self.s1[k].set_result(None)

    def _intra_recv_one(self, k: int, i: int, buf: np.ndarray) -> None:
        q.validate_packed(buf, self.wire_dtype)
        with self._latch_lock:
            self._s1_bufs[k][i] = buf
            self._s1_remaining[k] -= 1
            last = self._s1_remaining[k] == 0
        if last:
            # single codec batch over ALL member bufs once the last one
            # landed: recvs serialize on the PG worker anyway, and one
            # batch keeps concurrent += off overlapping acc rows
            self._intra_reduce_ready(k, list(self._s1_bufs[k]))
            self._s1_bufs[k] = []

    def _intra_reduce_ready(
        self, k: int, member_bufs: "List[Optional[np.ndarray]]"
    ) -> None:
        a, b = self.chunks[k]
        ck = b - a
        rows = self.m * ck
        acc = self.accs[k]
        own_bufs: "List[np.ndarray]" = []
        if acc is None:
            # device-quantize path: the leader's own contribution is a
            # packed wire buffer too (quantized on-chip in one launch)
            own_bufs = list(self.send_bufs[k] or [])
            self.send_bufs[k] = None
            acc = _POOL.take((rows, self.cols), np.float32)
            self.accs[k] = acc
            overwrite_first = True
        else:
            overwrite_first = False
        bufs = own_bufs + [m for m in member_bufs if m is not None]
        if not bufs:
            self.s1[k].set_result(None)
            return
        t_red = time.perf_counter()

        def block(r0: int, r1: int) -> None:
            ow = overwrite_first
            for buf in bufs:
                q.fma_rows_packed(
                    buf, rows, self.cols, r0, r1, self.wire_dtype,
                    acc, r0, overwrite=ow,
                )
                ow = False

        futs = _cpool.run_blocks(rows, block, self.trace, lane="rx")

        def done() -> None:
            _metrics.QUANT_CODEC_SECONDS.labels(
                stage="reduce", wire=self.wire_dtype
            ).observe(time.perf_counter() - t_red)
            for buf in bufs:
                _POOL.give(buf)

        self.chain(futs, done, self.s1[k])

    # -- hop 2: inter.exchange -------------------------------------------

    def submit_inter_exchange(self, k: int) -> None:
        if not self.is_leader:
            self.s2[k].set_result(None)
            return
        if self.m == 1:
            self._finalize_own_slice(k, [])
            return
        a, b = self.chunks[k]
        ck = b - a
        acc = self.accs[k]
        assert acc is not None
        # requantize each foreign group's slice of the partial sum (the
        # hop-boundary requant), then pairwise-exchange with the other
        # leaders in the plan's offset order
        ex_bufs: "Dict[int, np.ndarray]" = {}
        futs_by_g: "Dict[int, List[Future]]" = {}
        t_q = time.perf_counter()
        for j in range(self.m):
            if j == self.gidx:
                continue
            buf = q.new_packed(ck, self.cols, self.wire_dtype, pool=_POOL)
            ex_bufs[j] = buf

            def requant(r0: int, r1: int, buf=buf, off=j * ck) -> None:
                q.quantize_rows_packed(
                    acc, off + r0, buf, ck, self.cols, r0, r1,
                    self.wire_dtype,
                )

            futs_by_g[j] = _cpool.run_blocks(ck, requant, self.trace)
        self.chain(
            [f for fs in futs_by_g.values() for f in fs],
            lambda: _metrics.QUANT_CODEC_SECONDS.labels(
                stage="quantize", wire=self.wire_dtype
            ).observe(time.perf_counter() - t_q),
            Future(),
        )
        hop = self.plan.hops[1]
        with self._latch_lock:
            self._s2_remaining[k] = self.m - 1
            self._exch_recv[k] = [None] * (self.m - 1)
        for o, (dst, src) in enumerate(zip(hop.sends, hop.recvs)):
            dst_g = self.topo.group_index(dst)
            self.wait_captured(futs_by_g[dst_g])
            buf = ex_bufs[dst_g]
            t = time.perf_counter()
            self.submit_wire(
                "sendrecv", "inter.exchange", k,
                self.pg.sendrecv(buf, dst, src, tag=4 * k + 1),
                buf.nbytes, t,
                lambda rbuf, k=k, o=o, sbuf=buf: self._exch_one(
                    k, o, sbuf, rbuf
                ),
            )

    def _exch_one(
        self, k: int, o: int, sent: np.ndarray, rbuf: np.ndarray
    ) -> None:
        if rbuf is not sent:  # degraded PGs may alias the input back
            _POOL.give(sent)
        q.validate_packed(rbuf, self.wire_dtype)
        with self._latch_lock:
            self._exch_recv[k][o] = rbuf
            self._s2_remaining[k] -= 1
            last = self._s2_remaining[k] == 0
        if last:
            self._finalize_own_slice(
                k, [x for x in self._exch_recv[k] if x is not None]
            )
            self._exch_recv[k] = []

    def _finalize_own_slice(
        self, k: int, rbufs: "List[np.ndarray]"
    ) -> None:
        """Fold peer leaders' partial sums into the own slice, divide
        (AVG fusion), requantize into the broadcast piece."""
        a, b = self.chunks[k]
        ck = b - a
        g = self.gidx
        acc = self.accs[k]
        assert acc is not None
        piece = q.new_packed(ck, self.cols, self.wire_dtype, pool=_POOL)
        self.pieces[k] = piece
        t_red = time.perf_counter()

        def block(r0: int, r1: int) -> None:
            for rbuf in rbufs:
                q.fma_rows_packed(
                    rbuf, ck, self.cols, r0, r1, self.wire_dtype,
                    acc, g * ck + r0, overwrite=False,
                )
            if self.divisor:
                q.div_rows(acc, g * ck + r0, g * ck + r1, self.divisor)
            q.quantize_rows_packed(
                acc, g * ck + r0, piece, ck, self.cols, r0, r1,
                self.wire_dtype,
            )

        futs = _cpool.run_blocks(ck, block, self.trace, lane="rx")

        def done() -> None:
            _metrics.QUANT_CODEC_SECONDS.labels(
                stage="reduce", wire=self.wire_dtype
            ).observe(time.perf_counter() - t_red)
            seen = set()
            for rbuf in rbufs:
                if id(rbuf) not in seen:
                    seen.add(id(rbuf))
                    _POOL.give(rbuf)
            # every slice is now either requantized (sent or piece) —
            # the f32 accumulator is scratch from here
            _POOL.give(acc)
            self.accs[k] = None

        self.chain(futs, done, self.s2[k])

    # -- hop 3: inter.gather ---------------------------------------------

    def submit_inter_gather(self, k: int) -> None:
        if not self.is_leader:
            self.s3[k].set_result(None)
            return
        piece = self.pieces[k]
        assert piece is not None
        self._pieces_all[k][self.gidx] = piece
        if self.m == 1:
            self.s3[k].set_result(None)
            return
        hop = self.plan.hops[2]
        with self._latch_lock:
            self._s3_remaining[k] = self.m - 1
        for dst, src in zip(hop.sends, hop.recvs):
            src_g = self.topo.group_index(src)
            t = time.perf_counter()
            self.submit_wire(
                "sendrecv", "inter.gather", k,
                self.pg.sendrecv(piece, dst, src, tag=4 * k + 2),
                piece.nbytes, t,
                lambda rbuf, k=k, src_g=src_g: self._gather_one(
                    k, src_g, rbuf
                ),
            )

    def _gather_one(self, k: int, src_g: int, rbuf: np.ndarray) -> None:
        q.validate_packed(rbuf, self.wire_dtype)
        with self._latch_lock:
            self._pieces_all[k][src_g] = rbuf
            self._s3_remaining[k] -= 1
            last = self._s3_remaining[k] == 0
        if last:
            self.s3[k].set_result(None)

    # -- hop 4: intra.bcast ----------------------------------------------

    def _s4_part_done(self, k: int) -> None:
        with self._latch_lock:
            self._s4_parts[k] -= 1
            last = self._s4_parts[k] == 0
        if last:
            self.s4[k].set_result(None)

    def submit_intra_bcast(self, k: int) -> None:
        a, b = self.chunks[k]
        ck = b - a
        pn = q.packed_nbytes(ck, self.cols)
        if not self.is_leader:
            t = time.perf_counter()
            with self._latch_lock:
                self._s4_parts[k] = 1
            self.submit_wire(
                "recv", "intra.bcast", k,
                self.pg.recv(self.leader_rank, tag=4 * k + 3),
                self.m * pn, t,
                lambda bundle, k=k: self._bcast_recv(k, bundle),
            )
            return
        pieces = self._pieces_all[k]
        assert all(p is not None for p in pieces)
        members = self.plan.hops[3].sends
        with self._latch_lock:
            self._s4_parts[k] = 1 + (1 if members else 0)
            self._s4_send_remaining[k] = len(members)
        if members:
            bundle = _POOL.take(self.m * pn, np.uint8)
            for j, p in enumerate(pieces):
                bundle[j * pn : (j + 1) * pn] = p
            for rm in members:
                t = time.perf_counter()
                self.submit_wire(
                    "send", "intra.bcast", k,
                    self.pg.send(bundle, rm, tag=4 * k + 3),
                    bundle.nbytes, t,
                    lambda _res, k=k, bundle=bundle: self._bcast_send_done(
                        k, bundle
                    ),
                )
        self._dequant_pieces(k, list(pieces), give=pieces, owner=True)

    def _bcast_send_done(self, k: int, bundle: np.ndarray) -> None:
        with self._latch_lock:
            self._s4_send_remaining[k] -= 1
            last = self._s4_send_remaining[k] == 0
        if last:
            _POOL.give(bundle)
            self._s4_part_done(k)

    def _bcast_recv(self, k: int, bundle: np.ndarray) -> None:
        a, b = self.chunks[k]
        ck = b - a
        pn = q.packed_nbytes(ck, self.cols)
        pieces = [bundle[j * pn : (j + 1) * pn] for j in range(self.m)]
        self._dequant_pieces(k, pieces, give=[bundle], owner=False)

    def _dequant_pieces(
        self,
        k: int,
        pieces: "List[np.ndarray]",
        give: "List[Optional[np.ndarray]]",
        owner: bool,
    ) -> None:
        """Dequantize every slice's reduced piece straight into its
        offset of the full output matrix (same bytes on every rank →
        bit-identical results across the collective)."""
        a, b = self.chunks[k]
        ck = b - a
        for p in pieces:
            q.validate_packed(p, self.wire_dtype)
        t_dq = time.perf_counter()
        futs: "List[Future]" = []
        for j, p in enumerate(pieces):
            base = self.bounds[j][0] + a

            def blk(r0: int, r1: int, p=p, base=base) -> None:
                q.dequant_rows_into(
                    p, ck, self.cols, r0, r1, self.wire_dtype,
                    self.full_mat, base + r0,
                )

            futs += _cpool.run_blocks(ck, blk, self.trace, lane="rx")

        def done() -> None:
            _metrics.QUANT_CODEC_SECONDS.labels(
                stage="dequant", wire=self.wire_dtype
            ).observe(time.perf_counter() - t_dq)
            seen = set()
            for buf in give:
                if buf is not None and id(buf) not in seen:
                    seen.add(id(buf))
                    _POOL.give(buf)
            if owner:
                self.pieces[k] = None
                self._pieces_all[k] = [None] * self.m
            self._s4_part_done(k)

        self.chain(futs, done, Future())

    # -- driver ----------------------------------------------------------

    def drive(
        self,
        on_finish: "Callable[[], Any]",
        full_mat: "Optional[np.ndarray]" = None,
        bounds: "Optional[List[Tuple[int, int]]]" = None,
    ) -> None:
        """Plan-driven driver: tick t submits intra.reduce(t),
        inter.exchange(t-1), inter.gather(t-2), intra.bcast(t-3) — the
        stagger that overlaps chunk k's intra hops with chunk k-1's
        inter-host wire.  Every rank runs the identical loop, so the
        global submission interleave is uniform (per-socket stream
        consistency) and a chaos abort leaves all ranks at the same
        stream position (PG reuse after a mid-pipeline fault)."""
        try:
            n = len(self.chunks)
            for t in range(n + 3):
                if self.error is not None:
                    return
                if t < n:
                    # same chaos contract as the flat driver, per chunk
                    _faults.check("pg.allreduce")
                    _faults.check("pg.allreduce.chunk", step=t)
                    self._await(self.ready[t])
                    self.submit_intra_reduce(t)
                if 0 <= t - 1 < n:
                    self._await(self.s1[t - 1])
                    # per-hop chaos: fired before the inter-host hops of
                    # this chunk are submitted (step = chunk index)
                    _faults.check("pg.allreduce.hop", step=t - 1)
                    self.submit_inter_exchange(t - 1)
                if 0 <= t - 2 < n:
                    self._await(self.s2[t - 2])
                    self.submit_inter_gather(t - 2)
                if 0 <= t - 3 < n:
                    self._await(self.s3[t - 3])
                    self.submit_intra_bcast(t - 3)
            for fut in self.s4:
                self._await(fut)
            self.finish_stats()
            self.out_fut.set_result(on_finish())
        except BaseException as e:  # noqa: BLE001 - funnel
            self.abort(e)


def _attach_accounting(
    work: Work, pipe: "Optional[_ChunkPipeline]", wire_bytes: int,
    unquantized: int, wire_dtype: str, device_quantized: bool = False,
) -> Work:
    work.wire_bytes = wire_bytes
    work.unquantized_wire_bytes = unquantized
    work.device_quantized = device_quantized
    work.wire_dtype = wire_dtype
    if pipe is not None:
        # both written once, at pipeline completion (finish_stats) —
        # read them AFTER wait(); mid-flight reads see 0.0 / partial keys
        work.codec_s_box = pipe.codec_s_box
        work.quant_stats = pipe.stats
    return work


def _resolve_topology(
    topology: "None | str | _topo.Topology", world: int
) -> "Optional[_topo.Topology]":
    """Explicit Topology object, spec string, or (None) the
    ``TORCHFT_TOPOLOGY`` env default — ``None`` result = flat."""
    if isinstance(topology, _topo.Topology):
        if topology.world != world:
            raise ValueError(
                f"topology describes {topology.world} ranks, "
                f"collective world is {world}"
            )
        return topology
    if isinstance(topology, str):
        return _topo.parse_topology(topology, world)
    return _topo.resolve_topology(world)


def allreduce_quantized(
    arrays: "List[Any]",
    op: str,
    pg: ProcessGroup,
    average_by: "int | None" = None,
    device_quantize: "Optional[bool]" = None,
    wire_dtype: "Optional[str]" = None,
    topology: "None | str | _topo.Topology" = None,
) -> Work:
    """8-bit quantized allreduce of a list of float arrays.

    Returns a Work resolving to the dequantized reduced arrays (f32
    precision loss ~1e-2 relative; see tests for bounds).  The Work
    carries ``wire_bytes`` / ``unquantized_wire_bytes`` attributes with
    the measured per-rank wire payload, a ``codec_s_box`` (codec-busy
    seconds, filled as stages run) and ``quant_stats`` (per-collective
    pipeline accounting incl. ``overlap_efficiency``) — read after
    ``wait``.

    Args:
        average_by: divide the sum by this count (fused into the requant
            step); defaults to pg.size() when op is AVG.
        device_quantize: quantize on-device with the Pallas kernel before
            the device→host copy.  Default: auto — on when every input is
            a jax array and the default backend is TPU.  int8 wire only
            (the fp8 leg is host-codec, mirroring the reference gating
            its fp8 kernels on SM90 hardware).
        wire_dtype: ``"int8"`` (default) or ``"fp8_e4m3"`` — the payload
            format on the DCN wire (same byte count either way; the
            reference's fp8e4nv/int8 pair, torchft/quantization.py:30-41).
            Defaults to ``TORCHFT_QUANT_WIRE`` when set.
        topology: wire topology selecting the reduction plan — a
            :class:`~torchft_tpu.ops.topology.Topology`, a spec string
            (``TORCHFT_TOPOLOGY`` grammar), or None for the env default.
            Flat (unset) runs today's alltoall/allgather schedule
            bit-identically; a grouped topology runs the hierarchical
            multi-hop plan (intra-host reduce → inter-host leader
            exchange → intra-host broadcast, requantizing at hop
            boundaries).  Must agree across ranks.
    """
    if op not in (REDUCE_SUM, REDUCE_AVG):
        raise ValueError(f"quantized allreduce supports sum/avg, got {op}")
    wire_dtype = q.resolve_wire(wire_dtype)  # validate before any comm
    # normalize non-array inputs (lists, Python scalars) without touching
    # device arrays
    arrays = [a if isinstance(a, jax.Array) else np.asarray(a) for a in arrays]
    for a in arrays:
        if not jnp.issubdtype(a.dtype, jnp.floating):
            raise ValueError("quantized allreduce requires floating point arrays")
    if device_quantize is None:
        device_quantize = (
            wire_dtype == q.WIRE_INT8
            and jax.default_backend() == "tpu"
            and all(isinstance(a, jax.Array) for a in arrays)
        )
    elif device_quantize and wire_dtype != q.WIRE_INT8:
        raise ValueError(
            "device_quantize supports the int8 wire only (no fp8 quantize "
            "kernel on current TPU Mosaic — the host codec carries fp8)"
        )

    shapes = [a.shape for a in arrays]
    sizes = [int(a.size) for a in arrays]
    out_dtypes = [a.dtype for a in arrays]

    world = pg.size()
    if world <= 1:
        out = [np.array(a) for a in arrays]
        if op == REDUCE_AVG and average_by:
            out = [a / average_by for a in out]
        solo = completed_work(out)
        return _attach_accounting(solo, None, 0, 0, wire_dtype)
    divisor = average_by if average_by is not None else (world if op == REDUCE_AVG else 0)

    # Flatten all arrays into one (rows, cols) matrix of quantization rows so
    # a single pipelined alltoall/allgather schedule covers every gradient
    # (the reference fuses arrays into one comm buffer the same way).
    total = sum(sizes)
    if total == 0:
        # nothing to reduce: zero-size outputs, no wire, no pipeline
        solo = completed_work(
            [np.zeros(s, dt) for s, dt in zip(shapes, out_dtypes)]
        )
        return _attach_accounting(solo, None, 0, 0, wire_dtype)
    cols = 2048 if total >= 2048 else max(total, 1)
    topo = _resolve_topology(topology, world)
    if topo is not None:
        return _allreduce_hier(
            arrays, pg, topo, divisor, device_quantize, wire_dtype,
            shapes, sizes, out_dtypes, total, cols,
        )
    rows = -(-total // cols)
    # pad rows to a multiple of world so row-slices are even
    rows = -(-rows // world) * world
    bounds = _slice_rows(rows, world)
    slice_rows = rows // world  # identical for every rank by construction
    chunks = _chunk_bounds(slice_rows, _resolve_chunk_rows(slice_rows, cols))

    pipe = _ChunkPipeline(pg, "allreduce", wire_dtype, divisor, cols, chunks)
    my_rank = pipe.my_rank
    # The full output matrix escapes to the caller as views — never pooled.
    full_mat = np.empty((rows, cols), dtype=np.float32)

    # ---- capture: quantize peer slices / copy the own slice, per chunk --
    capture_futs: "List[Future]" = []
    if device_quantize:
        from torchft_tpu.ops import pallas_quant as pq

        flat_dev = jnp.concatenate(
            [jnp.ravel(a).astype(jnp.float32) for a in arrays]
        )
        mat = (
            jnp.zeros((rows * cols,), jnp.float32)
            .at[: flat_dev.size]
            .set(flat_dev)
        )
        scales_dev, payload_dev = pq.fused_quantize_into_int8(
            mat.reshape(rows, cols)
        )
        for k, (a, b) in enumerate(chunks):
            ck = b - a
            t_cap = time.perf_counter()
            bufs_k: "List[np.ndarray]" = []
            futs_k: "List[Future]" = []
            for r in range(world):
                g0 = bounds[r][0] + a
                buf = q.new_packed(ck, cols, wire_dtype, pool=_POOL)
                bufs_k.append(buf)

                def copy_chunk(r0: int, r1: int, g0=g0, buf=buf, ck=ck) -> None:
                    # device→host hop of this chunk's slice: overlaps the
                    # sends of earlier chunks (the PCIe/DMA leg of the
                    # pipeline). Row-range [r0, r1) is the whole chunk —
                    # transfers are not worth sub-splitting.
                    sc, pl = q._packed_views(buf, ck, cols, wire_dtype)
                    sc[r0:r1] = np.asarray(scales_dev[g0 + r0 : g0 + r1])
                    pl[r0:r1] = np.asarray(payload_dev[g0 + r0 : g0 + r1])

                futs_k += _cpool.run_blocks(
                    ck, copy_chunk, pipe.trace, min_rows=ck
                )
            pipe.send_bufs[k] = bufs_k
            pipe.capture_chunk(k, futs_k, [], t_cap)
            capture_futs += futs_k
    else:
        np_arrays = [np.asarray(a) for a in arrays]
        # Zero-copy flatten: a single contiguous f32 input (THE hot case —
        # a DiLoCo pseudograd fragment) is viewed, not copied; multi-array
        # inputs concatenate once.  Chunks then quantize straight off the
        # source; only chunks spanning the padded tail pay a small zeroed
        # copy.
        if (
            len(np_arrays) == 1
            and np_arrays[0].dtype == np.float32
            and np_arrays[0].flags.c_contiguous
        ):
            src = np_arrays[0].ravel()
        else:
            src = np.concatenate(
                [a.astype(np.float32, copy=False).ravel() for a in np_arrays]
            )
        full_rows = src.size // cols
        src2d = src[: full_rows * cols].reshape(full_rows, cols)

        capture_futs = pipe.capture_host_chunks(
            bounds,
            src2d,
            lambda k, a, b: _POOL.take((b - a, cols), np.float32),
            src_flat=src,
            full_rows=full_rows,
        )

    def assemble() -> "List[np.ndarray]":
        full = full_mat.ravel()[:total]
        out = []
        offset = 0
        for shape, size, dtype in zip(shapes, sizes, out_dtypes):
            # asarray: zero-copy view when dtype is already f32
            # (disjoint slices of the output matrix)
            out.append(
                np.asarray(
                    full[offset : offset + size].reshape(shape), dtype=dtype
                )
            )
            offset += size
        return out

    pipe.start_driver(assemble, full_mat, bounds)

    # call-time-snapshot contract: the contribution is fully captured
    # before the submit returns (capture overlaps the driver's wire ops on
    # earlier chunks, so this blocks for ~the codec's quantize leg only)
    pipe.wait_captured(capture_futs)

    out_work = Work(pipe.out_fut)
    # Observability: measured wire bytes vs the unquantized f32 equivalent
    # (the ~4x reduction the codec exists for).  alltoall leg: only slots
    # bound for peers hit the wire (self-delivery is a local copy); the
    # allgather leg then sends each reduced piece to (w-1) peers.
    # Computed from the chunk plan, not the live buffers — those recycle
    # into the pool as the pipeline drains.
    packed_total = sum(q.packed_nbytes(b - a, cols) for a, b in chunks)
    wire_bytes = 2 * (world - 1) * packed_total
    return _attach_accounting(
        out_work, pipe, wire_bytes, 4 * total, wire_dtype,
        device_quantized=bool(device_quantize),
    )


def _allreduce_hier(
    arrays: "List[Any]",
    pg: ProcessGroup,
    topo: "_topo.Topology",
    divisor: int,
    device_quantize: bool,
    wire_dtype: str,
    shapes: "List[Tuple[int, ...]]",
    sizes: "List[int]",
    out_dtypes: "List[Any]",
    total: int,
    cols: int,
) -> Work:
    """Hierarchical-plan body of :func:`allreduce_quantized`: rows are
    sliced per GROUP (padded to a multiple of the group count) and the
    synthesized plan runs per chunk on a :class:`_HierPipeline`."""
    rank = pg.rank()
    m = topo.n_groups
    rows = -(-total // cols)
    # pad rows to a multiple of the group count so group slices are even
    rows = -(-rows // m) * m
    bounds = _slice_rows(rows, m)
    slice_rows = rows // m
    chunks = _chunk_bounds(slice_rows, _resolve_chunk_rows(slice_rows, cols))
    plan = _topo.synthesize_plan(topo, rank)
    # TORCHFT_PLAN_VERIFY: validate the fleet-wide plan this rank's
    # schedule is a slice of, at the one build point every rank passes.
    from torchft_tpu.analysis import plan_verify as _pv

    if _pv.enabled():
        from torchft_tpu.analysis import plan_ir as _pir

        _pv.check_live(
            _pir.reduction_ir(topo, wire=wire_dtype,
                              slice_nbytes=slice_rows * cols)
        )
    # The full output matrix escapes to the caller as views — never pooled.
    full_mat = np.empty((rows, cols), dtype=np.float32)
    pipe = _HierPipeline(
        pg, wire_dtype, divisor, cols, chunks, plan, bounds, full_mat
    )

    capture_futs: "List[Future]" = []
    if device_quantize:
        from torchft_tpu.ops import pallas_quant as pq

        flat_dev = jnp.concatenate(
            [jnp.ravel(a).astype(jnp.float32) for a in arrays]
        )
        mat = (
            jnp.zeros((rows * cols,), jnp.float32)
            .at[: flat_dev.size]
            .set(flat_dev)
        )
        scales_dev, payload_dev = pq.fused_quantize_into_int8(
            mat.reshape(rows, cols)
        )
        for k, (a, b) in enumerate(chunks):
            ck = b - a
            t_cap = time.perf_counter()
            buf = q.new_packed(m * ck, cols, wire_dtype, pool=_POOL)
            pipe.send_bufs[k] = [buf]
            futs_k: "List[Future]" = []
            for j in range(m):
                g0 = bounds[j][0] + a

                def copy_chunk(
                    r0: int, r1: int, g0=g0, buf=buf, off=j * ck, ck=ck
                ) -> None:
                    # device→host hop of this chunk's slice rows, stacked
                    # at the slice's offset of the packed stage-1 buffer
                    sc, pl = q._packed_views(buf, m * ck, cols, wire_dtype)
                    sc[off + r0 : off + r1] = np.asarray(
                        scales_dev[g0 + r0 : g0 + r1]
                    )
                    pl[off + r0 : off + r1] = np.asarray(
                        payload_dev[g0 + r0 : g0 + r1]
                    )

                futs_k += _cpool.run_blocks(
                    ck, copy_chunk, pipe.trace, min_rows=ck
                )
            pipe.capture_chunk(k, futs_k, [], t_cap)
            capture_futs += futs_k
    else:
        np_arrays = [np.asarray(a) for a in arrays]
        if (
            len(np_arrays) == 1
            and np_arrays[0].dtype == np.float32
            and np_arrays[0].flags.c_contiguous
        ):
            src = np_arrays[0].ravel()
        else:
            src = np.concatenate(
                [a.astype(np.float32, copy=False).ravel() for a in np_arrays]
            )
        full_rows = src.size // cols
        src2d = src[: full_rows * cols].reshape(full_rows, cols)
        for k, (a, b) in enumerate(chunks):
            ck = b - a
            t_cap = time.perf_counter()
            futs_k = []
            give_after: "List[np.ndarray]" = []
            if pipe.is_leader:
                # leader contribution stays raw f32 (zero codec time and
                # zero quantization error on own data, like the flat
                # pipeline's own slice)
                acc = _POOL.take((m * ck, cols), np.float32)
                pipe.accs[k] = acc
            else:
                buf = q.new_packed(m * ck, cols, wire_dtype, pool=_POOL)
                pipe.send_bufs[k] = [buf]
            for j in range(m):
                g0 = bounds[j][0] + a
                if g0 + ck > full_rows:
                    tail = _POOL.take((ck, cols), np.float32)
                    give_after.append(tail)
                    _fill_tail(src, tail, g0, cols)
                    block_src, row0 = tail, 0
                else:
                    block_src, row0 = src2d, g0
                if pipe.is_leader:

                    def copy_own(
                        r0: int, r1: int, acc=acc, bs=block_src,
                        row0=row0, off=j * ck,
                    ) -> None:
                        np.copyto(
                            acc[off + r0 : off + r1],
                            bs[row0 + r0 : row0 + r1],
                        )

                    futs_k += _cpool.run_blocks(ck, copy_own, pipe.trace)
                else:

                    def quant_member(
                        r0: int, r1: int, buf=buf, bs=block_src,
                        row0=row0, off=j * ck, ck=ck,
                    ) -> None:
                        q.quantize_rows_packed(
                            bs, row0 + r0, buf, m * ck, cols,
                            off + r0, off + r1, wire_dtype,
                        )

                    futs_k += _cpool.run_blocks(ck, quant_member, pipe.trace)
            pipe.capture_chunk(k, futs_k, give_after, t_cap)
            capture_futs += futs_k

    def assemble() -> "List[np.ndarray]":
        full = full_mat.ravel()[:total]
        out = []
        offset = 0
        for shape, size, dtype in zip(shapes, sizes, out_dtypes):
            out.append(
                np.asarray(
                    full[offset : offset + size].reshape(shape), dtype=dtype
                )
            )
            offset += size
        return out

    pipe.start_driver(assemble)
    pipe.wait_captured(capture_futs)

    out_work = Work(pipe.out_fut)
    # Egress accounting from the plan (live buffers recycle as the
    # pipeline drains): members ship one stacked quantized copy up;
    # leaders pay the two inter-host hops plus the member broadcast.
    packed_slice = sum(q.packed_nbytes(b - a, cols) for a, b in chunks)
    packed_stacked = sum(
        q.packed_nbytes(m * (b - a), cols) for a, b in chunks
    )
    if pipe.is_leader:
        n_members = len(topo.members(pipe.gidx))
        inter = 2 * (m - 1) * packed_slice
        wire_bytes = inter + n_members * m * packed_slice
    else:
        inter = 0
        wire_bytes = packed_stacked
    work = _attach_accounting(
        out_work, pipe, wire_bytes, 4 * total, wire_dtype,
        device_quantized=bool(device_quantize),
    )
    # inter-host egress alone — the bytes the WAN RTT/bandwidth model
    # actually charges for; bench reports it next to the hop telemetry
    work.inter_wire_bytes = inter
    return work


def reduce_scatter_quantized(
    array: Any, op: str, pg: ProcessGroup, wire_dtype: "Optional[str]" = None
) -> Work:
    """8-bit quantized reduce-scatter: the alltoall+reduce legs of the
    pipeline without the allgather (reference collectives.py:159-294).
    Resolves to this rank's dequantized row-slice of the reduction.
    ``wire_dtype`` defaults to ``TORCHFT_QUANT_WIRE`` like the allreduce
    (one env knob, both collectives).  Always runs the flat plan:
    reduce-scatter's output contract is per-RANK row slices, which a
    group-sliced hierarchical plan would redefine — ``TORCHFT_TOPOLOGY``
    applies to the allreduce only (docs/architecture.md)."""
    if op not in (REDUCE_SUM, REDUCE_AVG):
        raise ValueError(f"quantized reduce_scatter supports sum/avg, got {op}")
    wire_dtype = q.resolve_wire(wire_dtype)
    np_array = np.asarray(array)
    if not jnp.issubdtype(np_array.dtype, jnp.floating):
        raise ValueError("quantized reduce_scatter requires floating point arrays")
    world = pg.size()
    if world <= 1:
        solo = completed_work(np_array.astype(np.float32))
        return _attach_accounting(solo, None, 0, 0, wire_dtype)
    if np_array.shape[0] % world != 0:
        raise ValueError(
            f"reduce_scatter dim0 {np_array.shape[0]} not divisible by {world}"
        )
    divisor = world if op == REDUCE_AVG else 0

    rows_total = np_array.shape[0]
    cols = int(np.prod(np_array.shape[1:], dtype=np.int64)) or 1
    mat = np.ascontiguousarray(
        np_array.reshape(rows_total, cols), dtype=np.float32
    )
    bounds = _slice_rows(rows_total, world)
    my_rank = pg.rank()
    my_rows = bounds[my_rank][1] - bounds[my_rank][0]
    chunks = _chunk_bounds(my_rows, _resolve_chunk_rows(my_rows, cols))
    pipe = _ChunkPipeline(
        pg, "reduce_scatter", wire_dtype, divisor, cols, chunks
    )
    out_shape = (my_rows,) + np_array.shape[1:]
    # the raw f32 result (no requant: the reduced slice stays local, so
    # requantizing would only add error) — escapes to the caller, so a
    # plain allocation, and the per-chunk accumulators are REGIONS of it
    out_mat = np.empty((my_rows, cols), dtype=np.float32)

    # own-slice accumulators ARE regions of the caller-visible output; the
    # reduce fma-accumulates peers into them in place, no requant
    capture_futs = pipe.capture_host_chunks(
        bounds, mat, lambda k, a, b: out_mat[a:b]
    )
    pipe.start_driver(lambda: out_mat.reshape(out_shape))
    pipe.wait_captured(capture_futs)

    out_work = Work(pipe.out_fut)
    # no allgather hop here: only the alltoall's peer slots cross the wire
    # (computed from the chunk plan — live buffers recycle as chunks drain)
    wire_bytes = (world - 1) * sum(
        q.packed_nbytes(b - a, cols) for a, b in chunks
    )
    return _attach_accounting(
        out_work, pipe, wire_bytes, 4 * (rows_total - my_rows) * cols,
        wire_dtype,
    )
