"""Quantized collectives: 8-bit allreduce / reduce-scatter over the FT PG.

Analog of the reference's quantized collectives
(reference: torchft/collectives.py:159-415): quantize per-rank row-slices,
``alltoall`` the slices, locally dequant-reduce-requant the owned slice,
``allgather`` the reduced slices, dequantize.  Cuts DCN bytes ~4x for f32
gradients (int8 payload + f32 row scales) at the cost of quantization error
— the DiLoCo outer-gradient path is tolerant to this by design.

Two bit-compatible quantizers feed the same wire format (the analog of the
reference wiring its Triton kernels into the collective,
reference collectives.py:297-415):

- **device path** (default for jax arrays on a TPU backend): the Pallas
  fused absmax-quantize kernel (torchft_tpu/ops/pallas_quant.py) runs
  *before* the device→host copy, so only int8 payload + f32 row scales
  cross PCIe/host memory — ~4x fewer device→host AND wire bytes;
- **host path** (numpy codec, torchft_tpu/ops/quantization.py) for host
  arrays or non-TPU backends.

SUM and AVG only, floating-point inputs only (parity: reference
collectives.py:336-344).
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from typing import Any, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from torchft_tpu.ops import quantization as q
from torchft_tpu.parallel.process_group import (
    ProcessGroup,
    REDUCE_AVG,
    REDUCE_SUM,
)
from torchft_tpu.parallel.work import Work, completed_work
from torchft_tpu.utils.bufpool import POOL as _POOL


def _check_world(received: "List[np.ndarray]", world: int, op: str) -> None:
    if len(received) != world:
        raise RuntimeError(
            f"{op} returned {len(received)} buffers for world {world} "
            "(degraded result from an error-swallowing PG?)"
        )


def _recycle_wire_bufs(
    send_bufs: "List[np.ndarray]",
    received: "List[np.ndarray]",
    my_rank: int,
    exclude: "Optional[np.ndarray]" = None,
) -> None:
    """Return dead wire buffers to the pool after a reduce consumed them.

    Send side: a packed buffer is drained to the sockets once the
    alltoall resolves — but a degraded (error-swallowing) PG can resolve
    with the INPUT arrays themselves, so anything aliased into
    ``received`` is skipped here and given exactly once below.  Receive
    side: id-deduped (any PG may alias slots); 0-byte own slots no-op in
    ``give``.  ``exclude``: a buffer already given elsewhere (the
    allgather path's own reduced piece) that must not be double-given
    even if a PG aliases it into the result.
    """
    for r, b in enumerate(send_bufs):
        if r != my_rank and not any(b is rcv for rcv in received):
            _POOL.give(b)
    seen_ids = set()
    for b in received:
        if b is not exclude and id(b) not in seen_ids:
            seen_ids.add(id(b))
            _POOL.give(b)


def _slice_rows(rows: int, world: int) -> "List[tuple[int, int]]":
    """Contiguous row ranges per rank (last rank takes the remainder)."""
    base = rows // world
    bounds = []
    start = 0
    for r in range(world):
        n = base + (1 if r < rows % world else 0)
        bounds.append((start, start + n))
        start += n
    return bounds


def _device_send_bufs(
    arrays: "List[Any]", bounds: "List[tuple[int, int]]", rows: int, cols: int
) -> "List[np.ndarray]":
    """Quantize the whole flattened matrix ON DEVICE (one Pallas launch),
    then copy only the int8 payload + f32 scales to the host and pack
    per-destination row-slices in the shared wire layout.  Quantization is
    per-row, so slicing after the kernel is bit-identical to quantizing
    each slice — and costs one device→host round trip instead of
    ``world``."""
    from torchft_tpu.ops import pallas_quant as pq

    flat = jnp.concatenate(
        [jnp.ravel(a).astype(jnp.float32) for a in arrays]
    )
    mat = jnp.zeros((rows * cols,), jnp.float32).at[: flat.size].set(flat)
    scales, payload = pq.fused_quantize_into_int8(mat.reshape(rows, cols))
    scales_np, payload_np = np.asarray(scales), np.asarray(payload)
    return [
        q.pack(scales_np[start:end], payload_np[start:end])
        for start, end in bounds
    ]


def allreduce_quantized(
    arrays: "List[Any]",
    op: str,
    pg: ProcessGroup,
    average_by: "int | None" = None,
    device_quantize: "Optional[bool]" = None,
    wire_dtype: "Optional[str]" = None,
) -> Work:
    """8-bit quantized allreduce of a list of float arrays.

    Returns a Work resolving to the dequantized reduced arrays (f32
    precision loss ~1e-2 relative; see tests for bounds).  The Work
    carries ``wire_bytes`` / ``unquantized_wire_bytes`` attributes with
    the measured per-rank alltoall payload size.

    Args:
        average_by: divide the sum by this count (fused into the requant
            step); defaults to pg.size() when op is AVG.
        device_quantize: quantize on-device with the Pallas kernel before
            the device→host copy.  Default: auto — on when every input is
            a jax array and the default backend is TPU.  int8 wire only
            (the fp8 leg is host-codec, mirroring the reference gating
            its fp8 kernels on SM90 hardware).
        wire_dtype: ``"int8"`` (default) or ``"fp8_e4m3"`` — the payload
            format on the DCN wire (same byte count either way; the
            reference's fp8e4nv/int8 pair, torchft/quantization.py:30-41).
            Defaults to ``TORCHFT_QUANT_WIRE`` when set.
    """
    if op not in (REDUCE_SUM, REDUCE_AVG):
        raise ValueError(f"quantized allreduce supports sum/avg, got {op}")
    wire_dtype = q.resolve_wire(wire_dtype)  # validate before any comm
    # normalize non-array inputs (lists, Python scalars) without touching
    # device arrays
    arrays = [a if isinstance(a, jax.Array) else np.asarray(a) for a in arrays]
    for a in arrays:
        if not jnp.issubdtype(a.dtype, jnp.floating):
            raise ValueError("quantized allreduce requires floating point arrays")
    if device_quantize is None:
        device_quantize = (
            wire_dtype == q.WIRE_INT8
            and jax.default_backend() == "tpu"
            and all(isinstance(a, jax.Array) for a in arrays)
        )
    elif device_quantize and wire_dtype != q.WIRE_INT8:
        raise ValueError(
            "device_quantize supports the int8 wire only (no fp8 quantize "
            "kernel on current TPU Mosaic — the host codec carries fp8)"
        )

    shapes = [a.shape for a in arrays]
    sizes = [int(a.size) for a in arrays]
    out_dtypes = [a.dtype for a in arrays]

    world = pg.size()
    if world <= 1:
        out = [np.array(a) for a in arrays]
        if op == REDUCE_AVG and average_by:
            out = [a / average_by for a in out]
        solo = completed_work(out)
        solo.wire_bytes = 0  # nothing crosses the wire at world 1
        solo.unquantized_wire_bytes = 0
        solo.device_quantized = False
        solo.wire_dtype = wire_dtype
        return solo
    divisor = average_by if average_by is not None else (world if op == REDUCE_AVG else 0)

    # Flatten all arrays into one (rows, cols) matrix of quantization rows so
    # a single alltoall/allgather round covers every gradient (the reference
    # fuses arrays into one comm buffer the same way).
    total = sum(sizes)
    cols = 2048 if total >= 2048 else max(total, 1)
    rows = -(-total // cols)
    # pad rows to a multiple of world so row-slices are even
    rows = -(-rows // world) * world
    bounds = _slice_rows(rows, world)

    codec_s = [0.0]  # wall spent in quantize/dequant (observability)
    my_rank = pg.rank()
    raw_self: "Optional[np.ndarray]" = None  # own slice, codec-free f32

    if device_quantize:
        send_bufs = _device_send_bufs(arrays, bounds, rows, cols)
    else:
        t0 = time.perf_counter()
        np_arrays = [np.asarray(a) for a in arrays]
        # Zero-copy flatten: a single contiguous f32 input (THE hot case —
        # a DiLoCo pseudograd fragment) is viewed, not copied; multi-array
        # inputs concatenate once.  Row-slices then quantize straight off
        # the source; only the slice that spans the padded tail pays a
        # small zeroed copy.
        if (
            len(np_arrays) == 1
            and np_arrays[0].dtype == np.float32
            and np_arrays[0].flags.c_contiguous
        ):
            src = np_arrays[0].ravel()
        else:
            src = np.concatenate(
                [a.astype(np.float32, copy=False).ravel() for a in np_arrays]
            )
        full_rows = src.size // cols

        def _slice_block(start: int, end: int) -> "Tuple[np.ndarray, bool]":
            """(block, owned): owned blocks came from the pool (the slice
            spans the padded tail, zero-filled past the source)."""
            if end <= full_rows:
                return (
                    src[start * cols : end * cols].reshape(end - start, cols),
                    False,
                )
            block = _POOL.take((end - start, cols), np.float32)
            avail = src.size - start * cols
            flat = block.ravel()
            if avail > 0:
                flat[:avail] = src[start * cols :]
                flat[avail:] = 0.0
            else:
                flat[:] = 0.0
            return block, True

        # Quantize each destination rank's row-slice separately — EXCEPT
        # our own: alltoall self-delivers locally (the slot never hits the
        # wire), so the own slice skips the codec entirely and enters the
        # reduce as raw f32 (zero codec time + zero quantization error on
        # a rank's own contribution; the reference quantizes all slices,
        # torchft/collectives.py:345-376).
        send_bufs = []
        for r, (start, end) in enumerate(bounds):
            block, owned = _slice_block(start, end)
            if r == my_rank:
                if not owned:
                    # view of the caller's array: SNAPSHOT it now (peer
                    # slices are quantized synchronously, so the whole
                    # contribution must be captured at call time — the
                    # caller may mutate its array before the reduce runs)
                    snap = _POOL.take(block.shape, np.float32)
                    np.copyto(snap, block)
                    block = snap
                raw_self = block  # pool-owned either way; given post-reduce
                send_bufs.append(np.empty(0, dtype=np.uint8))
            else:
                send_bufs.append(
                    q.quantize_packed(block, wire_dtype, pool=_POOL)
                )
                if owned:
                    # a padded PEER block is consumed by the quantize above
                    _POOL.give(block)
        codec_s[0] += time.perf_counter() - t0

    reduced_box: "List[Optional[np.ndarray]]" = [None]

    def _finish_alltoall(received: "List[np.ndarray]") -> Work:
        _check_world(received, world, "alltoall")
        my_rows = bounds[my_rank][1] - bounds[my_rank][0]
        t0 = time.perf_counter()
        # host path: own slot is the raw_self snapshot, not a wire buffer;
        # device path (raw_self None) reduces every received slot
        bufs = (
            [b for r, b in enumerate(received) if r != my_rank]
            if raw_self is not None
            else received
        )
        reduced = q.reduce_quantized(
            bufs, my_rows, cols, average_by=divisor,
            wire_dtype=wire_dtype, raw=raw_self, pool=_POOL,
        )
        if raw_self is not None:
            _POOL.give(raw_self)  # call-time snapshot, consumed by the reduce
        codec_s[0] += time.perf_counter() - t0
        # send buffers drained + received buffers consumed by the reduce
        _recycle_wire_bufs(send_bufs, received, my_rank)
        reduced_box[0] = reduced
        return pg.allgather(reduced)

    def _finish_allgather(gathered: "List[np.ndarray]") -> "List[np.ndarray]":
        # loud on short results: a partial fill of the into-place
        # reassembly below would return uninitialized rows as gradients
        _check_world(gathered, world, "allgather")
        t0 = time.perf_counter()
        # dequantize each rank's reduced piece straight into its offset of
        # the full matrix — no per-piece alloc, no concat pass
        full_mat = np.empty((rows, cols), dtype=np.float32)
        for r, buf in enumerate(gathered):
            start, end = bounds[r]
            scales, payload = q.unpack(buf, end - start, cols, wire_dtype)
            q.dequantize_into(scales, payload, full_mat[start:end])
        reduced = reduced_box[0]
        _POOL.give(reduced)  # own reduced piece: wire + decode done
        reduced_box[0] = None
        # gathered pieces are decoded into full_mat above — recycle them
        # (no send buffers at this hop; `reduced` was already given)
        _recycle_wire_bufs([], gathered, my_rank, exclude=reduced)
        full = full_mat.ravel()[:total]
        out = []
        offset = 0
        for shape, size, dtype in zip(shapes, sizes, out_dtypes):
            # asarray: zero-copy view when dtype is already f32 (disjoint
            # slices of `full`, which the concatenate just materialized)
            out.append(
                np.asarray(full[offset : offset + size].reshape(shape), dtype=dtype)
            )
            offset += size
        codec_s[0] += time.perf_counter() - t0
        return out

    # Chain: alltoall -> local fused reduce -> allgather -> dequantize.
    work = pg.alltoall(send_bufs)

    out_fut: Future = Future()

    def _stage2(f) -> None:
        exc = f.exception()
        if exc is not None:
            out_fut.set_exception(exc)
            return
        try:
            gather_work = _finish_alltoall(f.result())

            def _stage3(g) -> None:
                exc2 = g.exception()
                if exc2 is not None:
                    out_fut.set_exception(exc2)
                    return
                try:
                    out_fut.set_result(_finish_allgather(g.result()))
                except Exception as e:  # noqa: BLE001
                    out_fut.set_exception(e)

            gather_work.get_future().add_done_callback(_stage3)
        except Exception as e:  # noqa: BLE001
            out_fut.set_exception(e)

    work.get_future().add_done_callback(_stage2)
    out_work = Work(out_fut)
    # Observability: measured wire bytes vs the unquantized f32 equivalent
    # (the ~4x reduction the codec exists for).  alltoall leg: only slots
    # bound for peers hit the wire (self-delivery is a local copy); the
    # allgather ring then sends (w-1) reduced pieces per rank.
    my_rows_n = bounds[my_rank][1] - bounds[my_rank][0]
    piece_bytes = 4 + my_rows_n * 4 + my_rows_n * cols
    out_work.wire_bytes = (
        sum(b.nbytes for r, b in enumerate(send_bufs) if r != my_rank)
        + (world - 1) * piece_bytes
    )
    out_work.unquantized_wire_bytes = 4 * total
    out_work.device_quantized = bool(device_quantize)
    out_work.wire_dtype = wire_dtype
    out_work.codec_s_box = codec_s  # filled as stages run; read after wait
    return out_work


def reduce_scatter_quantized(
    array: Any, op: str, pg: ProcessGroup, wire_dtype: "Optional[str]" = None
) -> Work:
    """8-bit quantized reduce-scatter: like allreduce_quantized without the
    allgather (reference collectives.py:159-294). Resolves to this rank's
    dequantized row-slice of the reduction.  ``wire_dtype`` defaults to
    ``TORCHFT_QUANT_WIRE`` like the allreduce (one env knob, both
    collectives)."""
    if op not in (REDUCE_SUM, REDUCE_AVG):
        raise ValueError(f"quantized reduce_scatter supports sum/avg, got {op}")
    wire_dtype = q.resolve_wire(wire_dtype)
    np_array = np.asarray(array)
    if not jnp.issubdtype(np_array.dtype, jnp.floating):
        raise ValueError("quantized reduce_scatter requires floating point arrays")
    world = pg.size()
    if world <= 1:
        solo = completed_work(np_array.astype(np.float32))
        solo.wire_bytes = 0  # nothing crosses the wire at world 1
        solo.unquantized_wire_bytes = 0
        solo.wire_dtype = wire_dtype
        return solo
    if np_array.shape[0] % world != 0:
        raise ValueError(
            f"reduce_scatter dim0 {np_array.shape[0]} not divisible by {world}"
        )
    divisor = world if op == REDUCE_AVG else 0

    rows_total = np_array.shape[0]
    cols = int(np.prod(np_array.shape[1:], dtype=np.int64)) or 1
    mat = np.ascontiguousarray(
        np_array.reshape(rows_total, cols), dtype=np.float32
    )
    bounds = _slice_rows(rows_total, world)
    my_rank = pg.rank()
    # Same fast paths as the allreduce: the own slot self-delivers (never
    # hits the wire), so it skips the codec and enters the reduce as raw
    # f32; peer slices quantize straight into pooled wire buffers.  The
    # own slice is SNAPSHOTTED at call time (peer slices are quantized
    # synchronously — the whole contribution must be captured before the
    # caller can mutate its array).
    own = mat[bounds[my_rank][0] : bounds[my_rank][1]]
    raw_self = _POOL.take(own.shape, np.float32)
    np.copyto(raw_self, own)
    send_bufs = [
        np.empty(0, dtype=np.uint8)
        if r == my_rank
        else q.quantize_packed(mat[start:end], wire_dtype, pool=_POOL)
        for r, (start, end) in enumerate(bounds)
    ]

    my_rows = bounds[my_rank][1] - bounds[my_rank][0]
    out_shape = (my_rows,) + np_array.shape[1:]

    def _finish(received: "List[np.ndarray]") -> np.ndarray:
        _check_world(received, world, "alltoall")
        bufs = [b for r, b in enumerate(received) if r != my_rank]
        # raw f32 result: the reduced slice stays local, so requantizing
        # (needed in allreduce for the allgather hop) would only add error.
        # pool only feeds the accumulator's pages here (requantize=False
        # hands acc to the caller, so the pool never gets it back — a
        # warm-page win on take, replenished by the wire-buffer gives)
        acc = q.reduce_quantized(
            bufs, my_rows, cols, average_by=divisor, requantize=False,
            wire_dtype=wire_dtype, raw=raw_self, pool=_POOL,
        )
        _POOL.give(raw_self)  # call-time snapshot, consumed by the reduce
        _recycle_wire_bufs(send_bufs, received, my_rank)
        return acc.reshape(out_shape)

    out_work = pg.alltoall(send_bufs).then(_finish)
    # same wire observability the allreduce carries (no allgather hop
    # here: only the alltoall's peer slots cross the wire)
    out_work.wire_bytes = sum(
        b.nbytes for r, b in enumerate(send_bufs) if r != my_rank
    )
    out_work.unquantized_wire_bytes = (
        4 * (rows_total - my_rows) * cols
    )
    out_work.wire_dtype = wire_dtype
    return out_work
