"""Quantized collectives: 8-bit allreduce / reduce-scatter over the FT PG.

Analog of the reference's quantized collectives
(reference: torchft/collectives.py:159-415): quantize per-rank row-slices,
``alltoall`` the slices, locally dequant-reduce-requant the owned slice,
``allgather`` the reduced slices, dequantize.  Cuts DCN bytes ~4x for f32
gradients (int8 payload + f32 row scales) at the cost of quantization error
— the DiLoCo outer-gradient path is tolerant to this by design.

Two bit-compatible quantizers feed the same wire format (the analog of the
reference wiring its Triton kernels into the collective,
reference collectives.py:297-415):

- **device path** (default for jax arrays on a TPU backend): the Pallas
  fused absmax-quantize kernel (torchft_tpu/ops/pallas_quant.py) runs
  *before* the device→host copy, so only int8 payload + f32 row scales
  cross PCIe/host memory — ~4x fewer device→host AND wire bytes;
- **host path** (numpy codec, torchft_tpu/ops/quantization.py) for host
  arrays or non-TPU backends.

SUM and AVG only, floating-point inputs only (parity: reference
collectives.py:336-344).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from torchft_tpu.ops import quantization as q
from torchft_tpu.parallel.process_group import (
    ProcessGroup,
    REDUCE_AVG,
    REDUCE_SUM,
)
from torchft_tpu.parallel.work import Work, completed_work


def _slice_rows(rows: int, world: int) -> "List[tuple[int, int]]":
    """Contiguous row ranges per rank (last rank takes the remainder)."""
    base = rows // world
    bounds = []
    start = 0
    for r in range(world):
        n = base + (1 if r < rows % world else 0)
        bounds.append((start, start + n))
        start += n
    return bounds


def _device_send_bufs(
    arrays: "List[Any]", bounds: "List[tuple[int, int]]", rows: int, cols: int
) -> "List[np.ndarray]":
    """Quantize the whole flattened matrix ON DEVICE (one Pallas launch),
    then copy only the int8 payload + f32 scales to the host and pack
    per-destination row-slices in the shared wire layout.  Quantization is
    per-row, so slicing after the kernel is bit-identical to quantizing
    each slice — and costs one device→host round trip instead of
    ``world``."""
    from torchft_tpu.ops import pallas_quant as pq

    flat = jnp.concatenate(
        [jnp.ravel(a).astype(jnp.float32) for a in arrays]
    )
    mat = jnp.zeros((rows * cols,), jnp.float32).at[: flat.size].set(flat)
    scales, payload = pq.fused_quantize_into_int8(mat.reshape(rows, cols))
    scales_np, payload_np = np.asarray(scales), np.asarray(payload)
    return [
        q.pack(scales_np[start:end], payload_np[start:end])
        for start, end in bounds
    ]


def allreduce_quantized(
    arrays: "List[Any]",
    op: str,
    pg: ProcessGroup,
    average_by: "int | None" = None,
    device_quantize: "Optional[bool]" = None,
    wire_dtype: "Optional[str]" = None,
) -> Work:
    """8-bit quantized allreduce of a list of float arrays.

    Returns a Work resolving to the dequantized reduced arrays (f32
    precision loss ~1e-2 relative; see tests for bounds).  The Work
    carries ``wire_bytes`` / ``unquantized_wire_bytes`` attributes with
    the measured per-rank alltoall payload size.

    Args:
        average_by: divide the sum by this count (fused into the requant
            step); defaults to pg.size() when op is AVG.
        device_quantize: quantize on-device with the Pallas kernel before
            the device→host copy.  Default: auto — on when every input is
            a jax array and the default backend is TPU.  int8 wire only
            (the fp8 leg is host-codec, mirroring the reference gating
            its fp8 kernels on SM90 hardware).
        wire_dtype: ``"int8"`` (default) or ``"fp8_e4m3"`` — the payload
            format on the DCN wire (same byte count either way; the
            reference's fp8e4nv/int8 pair, torchft/quantization.py:30-41).
            Defaults to ``TORCHFT_QUANT_WIRE`` when set.
    """
    if op not in (REDUCE_SUM, REDUCE_AVG):
        raise ValueError(f"quantized allreduce supports sum/avg, got {op}")
    wire_dtype = q.resolve_wire(wire_dtype)  # validate before any comm
    # normalize non-array inputs (lists, Python scalars) without touching
    # device arrays
    arrays = [a if isinstance(a, jax.Array) else np.asarray(a) for a in arrays]
    for a in arrays:
        if not jnp.issubdtype(a.dtype, jnp.floating):
            raise ValueError("quantized allreduce requires floating point arrays")
    if device_quantize is None:
        device_quantize = (
            wire_dtype == q.WIRE_INT8
            and jax.default_backend() == "tpu"
            and all(isinstance(a, jax.Array) for a in arrays)
        )
    elif device_quantize and wire_dtype != q.WIRE_INT8:
        raise ValueError(
            "device_quantize supports the int8 wire only (no fp8 quantize "
            "kernel on current TPU Mosaic — the host codec carries fp8)"
        )

    shapes = [a.shape for a in arrays]
    sizes = [int(a.size) for a in arrays]
    out_dtypes = [a.dtype for a in arrays]

    world = pg.size()
    if world <= 1:
        out = [np.array(a) for a in arrays]
        if op == REDUCE_AVG and average_by:
            out = [a / average_by for a in out]
        solo = completed_work(out)
        solo.wire_bytes = 0  # nothing crosses the wire at world 1
        solo.unquantized_wire_bytes = 0
        solo.device_quantized = False
        solo.wire_dtype = wire_dtype
        return solo
    divisor = average_by if average_by is not None else (world if op == REDUCE_AVG else 0)

    # Flatten all arrays into one (rows, cols) matrix of quantization rows so
    # a single alltoall/allgather round covers every gradient (the reference
    # fuses arrays into one comm buffer the same way).
    total = sum(sizes)
    cols = 2048 if total >= 2048 else max(total, 1)
    rows = -(-total // cols)
    # pad rows to a multiple of world so row-slices are even
    rows = -(-rows // world) * world
    bounds = _slice_rows(rows, world)

    if device_quantize:
        send_bufs = _device_send_bufs(arrays, bounds, rows, cols)
    else:
        np_arrays = [np.asarray(a) for a in arrays]
        flat = np.concatenate([a.astype(np.float32).ravel() for a in np_arrays])
        mat = np.zeros((rows, cols), dtype=np.float32)
        mat.ravel()[: flat.size] = flat
        # quantize each destination rank's row-slice separately
        send_bufs = []
        for start, end in bounds:
            scales, payload = q.quantize(mat[start:end], wire_dtype)
            send_bufs.append(q.pack(scales, payload, wire_dtype))

    def _finish_alltoall(received: "List[np.ndarray]") -> Work:
        my_rows = bounds[pg.rank()][1] - bounds[pg.rank()][0]
        reduced = q.reduce_quantized(
            received, my_rows, cols, average_by=divisor, wire_dtype=wire_dtype
        )
        return pg.allgather(reduced)

    def _finish_allgather(gathered: "List[np.ndarray]") -> "List[np.ndarray]":
        pieces = []
        for r, buf in enumerate(gathered):
            n_rows = bounds[r][1] - bounds[r][0]
            scales, payload = q.unpack(buf, n_rows, cols, wire_dtype)
            pieces.append(q.dequantize(scales, payload, (n_rows, cols), np.float32))
        full = np.concatenate(pieces).ravel()[:total]
        out = []
        offset = 0
        for shape, size, dtype in zip(shapes, sizes, out_dtypes):
            out.append(full[offset : offset + size].reshape(shape).astype(dtype))
            offset += size
        return out

    # Chain: alltoall -> local fused reduce -> allgather -> dequantize.
    work = pg.alltoall(send_bufs)

    from concurrent.futures import Future

    out_fut: Future = Future()

    def _stage2(f) -> None:
        exc = f.exception()
        if exc is not None:
            out_fut.set_exception(exc)
            return
        try:
            gather_work = _finish_alltoall(f.result())

            def _stage3(g) -> None:
                exc2 = g.exception()
                if exc2 is not None:
                    out_fut.set_exception(exc2)
                    return
                try:
                    out_fut.set_result(_finish_allgather(g.result()))
                except Exception as e:  # noqa: BLE001
                    out_fut.set_exception(e)

            gather_work.get_future().add_done_callback(_stage3)
        except Exception as e:  # noqa: BLE001
            out_fut.set_exception(e)

    work.get_future().add_done_callback(_stage2)
    out_work = Work(out_fut)
    # Observability: measured wire bytes vs the unquantized f32 equivalent
    # (the ~4x reduction the codec exists for).
    out_work.wire_bytes = sum(b.nbytes for b in send_bufs)
    out_work.unquantized_wire_bytes = 4 * total
    out_work.device_quantized = bool(device_quantize)
    out_work.wire_dtype = wire_dtype
    return out_work


def reduce_scatter_quantized(
    array: Any, op: str, pg: ProcessGroup, wire_dtype: "Optional[str]" = None
) -> Work:
    """8-bit quantized reduce-scatter: like allreduce_quantized without the
    allgather (reference collectives.py:159-294). Resolves to this rank's
    dequantized row-slice of the reduction.  ``wire_dtype`` defaults to
    ``TORCHFT_QUANT_WIRE`` like the allreduce (one env knob, both
    collectives)."""
    if op not in (REDUCE_SUM, REDUCE_AVG):
        raise ValueError(f"quantized reduce_scatter supports sum/avg, got {op}")
    wire_dtype = q.resolve_wire(wire_dtype)
    np_array = np.asarray(array)
    if not jnp.issubdtype(np_array.dtype, jnp.floating):
        raise ValueError("quantized reduce_scatter requires floating point arrays")
    world = pg.size()
    if world <= 1:
        return completed_work(np_array.astype(np.float32))
    if np_array.shape[0] % world != 0:
        raise ValueError(
            f"reduce_scatter dim0 {np_array.shape[0]} not divisible by {world}"
        )
    divisor = world if op == REDUCE_AVG else 0

    rows_total = np_array.shape[0]
    cols = int(np.prod(np_array.shape[1:], dtype=np.int64)) or 1
    mat = np_array.reshape(rows_total, cols).astype(np.float32)
    bounds = _slice_rows(rows_total, world)
    send_bufs = []
    for start, end in bounds:
        scales, payload = q.quantize(mat[start:end], wire_dtype)
        send_bufs.append(q.pack(scales, payload, wire_dtype))

    my_rows = bounds[pg.rank()][1] - bounds[pg.rank()][0]
    out_shape = (my_rows,) + np_array.shape[1:]

    def _finish(received: "List[np.ndarray]") -> np.ndarray:
        # raw f32 result: the reduced slice stays local, so requantizing
        # (needed in allreduce for the allgather hop) would only add error
        acc = q.reduce_quantized(
            received, my_rows, cols, average_by=divisor, requantize=False,
            wire_dtype=wire_dtype,
        )
        return acc.reshape(out_shape)

    return pg.alltoall(send_bufs).then(_finish)
