"""Ulysses (DeepSpeed-style) all-to-all sequence parallelism.

The second long-context strategy alongside ring attention (the reference
has neither — SURVEY §2.3; both are TPU-first capabilities, not ports).
Where ring attention rotates K/V chunks around the mesh axis (N-1 ppermute
steps, attention stays sequence-sharded), Ulysses re-shards once per
direction with ``jax.lax.all_to_all``: scatter heads / gather sequence, run
plain full-sequence attention on the local head group, then the inverse
all-to-all.

Trade-off (How-to-Scale-Your-Model framing): Ulysses moves 2 all-to-alls of
activations per attention call and needs ``heads % axis_size == 0``, but
each device then runs a single dense [T, T/head-group] attention — better
MXU utilization for moderate T and cheap on all-to-all-friendly ICI
topologies; ring keeps memory strictly local-T and overlaps compute with
neighbor transfers — better for extreme T. Both compose with the same
mesh/axis contract, so models can switch per config
(models/transformer.py ``attn_impl``).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from torchft_tpu.ops.ring_attention import dense_attention, sharded_attention


def _replicated_kv_heads(h: int, hkv: int, size: int) -> int:
    """Smallest kv head count ``hkv' >= hkv`` that is a multiple of both
    ``hkv`` and ``size`` while still dividing ``h`` (so the contiguous
    ``jnp.repeat`` GQA mapping is preserved block-for-block across the
    head-split all-to-all): ``lcm(hkv, size)``.  Given the caller's
    preconditions — ``h % hkv == 0`` and ``h % size == 0`` — ``h`` is
    divisible by both, hence by their lcm, so the lcm always works (a
    number divisible by a and b is divisible by lcm(a, b))."""
    cand = math.lcm(hkv, size)
    assert h % cand == 0, (h, hkv, size)  # guaranteed by preconditions
    return cand


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    use_flash: "Optional[bool]" = None,
) -> jax.Array:
    """Per-shard Ulysses body. Must run inside shard_map over ``axis_name``;
    q/k/v are local sequence chunks ``[B, T_local, H, D]`` (rotary-embedded
    with *global* positions by the caller, same contract as ring attention).

    GQA: K/V may carry fewer heads; when ``H_kv`` divides evenly across the
    axis they cross the all-to-all *unexpanded* (H/H_kv fewer bytes) and
    are broadcast up inside the local attention.  When ``H_kv`` is NOT
    divisible by the axis size, K/V heads are minimally REPLICATED first
    (to ``lcm(H_kv, size)`` heads, which always divides H given the
    query-head constraints) —
    more all-to-all bytes on the replicated heads, but every GQA/axis
    combination runs instead of asserting.  Query heads must divide the
    axis size (queries cannot be replicated without duplicating output
    rows).

    Local attention on the gathered full sequence uses the fused Pallas
    flash kernel when the global sequence is lane-aligned
    (``T_local*size % 128 == 0``) — O(T) memory instead of the dense
    [T, T] score matrix, same flash x sequence-parallel composition the
    ring path has (``ring_flash_local``).  ``use_flash=False`` opts out
    (required inside partial-auto shard_map contexts, e.g. the pipeline,
    where pallas_call's missing vma annotation is rejected).

    Returns ``[B, T_local, H, D]``.
    """
    size = jax.lax.axis_size(axis_name)
    h, hkv = q.shape[2], k.shape[2]
    if h % size != 0:
        raise ValueError(
            f"ulysses attention needs query heads ({h}) divisible by the "
            f"sequence-parallel axis size ({size})"
        )
    if h % hkv != 0:
        raise ValueError(f"query heads {h} not a multiple of kv heads {hkv}")
    if hkv % size != 0:
        # replication path: contiguous repeat preserves the GQA block
        # mapping across the head-split all-to-all (see _replicated_kv_heads)
        target = _replicated_kv_heads(h, hkv, size)
        rep = target // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        hkv = target

    def seq_gather(x: jax.Array) -> jax.Array:
        # [B, T_local, H, D] -> [B, T_local*size, H/size, D]
        # split heads across the axis, concatenate sequence chunks in axis
        # order (contiguous sequence sharding => global order).
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def seq_scatter(x: jax.Array) -> jax.Array:
        # inverse: [B, T, H/size, D] -> [B, T_local, H, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qf, kf, vf = seq_gather(q), seq_gather(k), seq_gather(v)
    t_full = qf.shape[1]
    if use_flash is None:
        use_flash = t_full % 128 == 0
    if use_flash:
        from torchft_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qf, kf, vf, causal=causal)
    else:
        # dense_attention broadcasts GQA kv heads up locally (post-transfer)
        out = dense_attention(qf, kf, vf, causal=causal)
    return seq_scatter(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "cp",
    causal: bool = True,
    batch_axes: "Optional[tuple]" = None,
    head_axis: "Optional[str]" = None,
) -> jax.Array:
    """shard_map'd Ulysses attention over ``mesh`` axis ``axis_name``
    (same contract as :func:`ring_attention`; see
    :func:`torchft_tpu.ops.ring_attention.sharded_attention`)."""
    # flash engages when the GLOBAL sequence is lane-aligned (the local
    # body attends over the gathered full sequence, unlike ring's
    # T_local-tile check)
    return sharded_attention(
        ulysses_attention_local, q, k, v, mesh, axis_name, causal,
        batch_axes, head_axis,
        may_use_pallas=q.shape[1] % 128 == 0,
    )


__all__ = ["ulysses_attention", "ulysses_attention_local"]
