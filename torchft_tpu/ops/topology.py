"""Declarative wire topology + synthesized multi-hop reduction plans.

Multi-region DiLoCo lives at 10-100 ms RTT, where the flat ring's N-1
serialized hops dominate the outer sync (ROADMAP item 3).  DynamiQ
(PAPERS.md) shows the right shape for compressed collectives at WAN
scale — hierarchical intra-host reduce, inter-host exchange among host
leaders, intra-host broadcast, requantizing at hop boundaries — and PCCL
argues the schedule should be a *synthesized plan* over a declarative
topology, not hard-coded.  This module is that layer:

- :class:`Topology` — a partition of the collective's ranks into host
  (or slice/region) groups, parsed from ``TORCHFT_TOPOLOGY``;
- :func:`synthesize_plan` — turns (topology, rank) into a
  :class:`ReductionPlan`: the ordered hop schedule this rank executes,
  with peers resolved per hop.  ``ops/collectives.py`` executes the plan
  per pipeline chunk; ``parallel/process_group.py`` consults the same
  descriptor to charge ``TORCHFT_WIRE_RTT_MS`` only on messages that
  cross a group boundary.

``TORCHFT_TOPOLOGY`` grammar::

    (unset) | "flat"      no hierarchy: today's flat schedule, and every
                          peer counts as inter-group for the RTT model
                          (a flat ring across regions pays RTT per hop)
    "hosts:K"             contiguous groups of K ranks (rank r is in
                          group r // K); adapts to any world size, so it
                          survives elastic shrink/grow re-ranking
    "0,1;2,3"             explicit groups (every rank 0..world-1 exactly
                          once); rejected loudly on a world-size mismatch,
                          so only use it for fixed-world jobs/tests

The hierarchical plan (m groups over w ranks, rows sliced per *group*):

1. ``intra.reduce``  — members quantize their full chunk and send it to
   their group leader; the leader dequant-accumulates members over its
   own raw-f32 contribution (group partial sum, one quantization of
   member data).
2. ``inter.exchange`` — leaders requantize each foreign group's row
   slice of the partial sum (hop-boundary requant) and pairwise-exchange
   with the other leaders; each leader fully reduces its own slice.
3. ``inter.gather``  — leaders exchange their reduced, requantized
   slices so every leader holds all slices.
4. ``intra.bcast``   — leaders ship the reduced slice bundle to members;
   everyone dequantizes the same bytes, so results are bit-identical
   across ALL ranks.

Per inter-host link that is 2 serialized messages per chunk instead of
the flat schedule's 2*(w-1) — the RTT bill shrinks by ~w/m while the
inter-host payload shrinks to one group-reduced copy per host.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from torchft_tpu.utils.env import env_str

__all__ = [
    "Topology",
    "Hop",
    "ReductionPlan",
    "parse_topology",
    "resolve_topology",
    "synthesize_plan",
]


class Topology:
    """A partition of ranks ``0..world-1`` into host/slice groups.

    Group order is schedule-significant (group ``g`` owns row-slice
    ``g``; leaders exchange round-robin by group index), so it is fixed
    at parse time and must agree across ranks — like every other
    cross-rank knob (``TORCHFT_QUANT_WIRE``, chunking), divergence fails
    loudly mid-collective rather than silently corrupting.
    """

    def __init__(self, groups: "Sequence[Sequence[int]]") -> None:
        self.groups: "Tuple[Tuple[int, ...], ...]" = tuple(
            tuple(g) for g in groups
        )
        if not self.groups or not all(self.groups):
            raise ValueError("topology needs at least one non-empty group")
        ranks = [r for g in self.groups for r in g]
        self.world = len(ranks)
        if sorted(ranks) != list(range(self.world)):
            raise ValueError(
                f"topology groups must partition ranks 0..{self.world - 1} "
                f"exactly once, got {self.groups}"
            )
        self._group_of = {r: gi for gi, g in enumerate(self.groups) for r in g}

    @property
    def n_groups(self) -> int:
        return len(self.groups)

    def group_index(self, rank: int) -> int:
        return self._group_of[rank]

    def leader(self, gidx: int) -> int:
        """Group leader = the group's lowest rank (deterministic across
        ranks with no extra coordination)."""
        return min(self.groups[gidx])

    def leaders(self) -> "List[int]":
        return [self.leader(g) for g in range(self.n_groups)]

    def members(self, gidx: int) -> "List[int]":
        """Non-leader ranks of a group, in rank order."""
        lead = self.leader(gidx)
        return sorted(r for r in self.groups[gidx] if r != lead)

    def inter(self, a: int, b: int) -> bool:
        """True when ``a`` and ``b`` sit across a host/slice boundary."""
        return self._group_of[a] != self._group_of[b]

    def describe(self) -> str:
        return ";".join(",".join(str(r) for r in g) for g in self.groups)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Topology({self.describe()!r})"


def parse_topology(spec: str, world: int) -> "Optional[Topology]":
    """Parse a ``TORCHFT_TOPOLOGY`` spec for a ``world``-rank collective.

    Returns ``None`` for the flat (non-hierarchical) topology.  Raises
    ``ValueError`` on malformed specs or explicit group lists that do not
    match ``world`` — a silently-wrong topology would desync op streams.
    """
    spec = (spec or "").strip()
    if not spec or spec.lower() == "flat":
        return None
    if spec.lower().startswith(("hosts:", "groups:")):
        _, _, raw = spec.partition(":")
        try:
            k = int(raw)
        except ValueError:
            raise ValueError(f"TORCHFT_TOPOLOGY: bad group size in {spec!r}")
        if k < 1:
            raise ValueError(f"TORCHFT_TOPOLOGY: group size must be >= 1, got {k}")
        groups = [
            list(range(start, min(start + k, world)))
            for start in range(0, world, k)
        ]
        topo = Topology(groups)
    else:
        try:
            groups = [
                [int(r) for r in part.split(",") if r.strip() != ""]
                for part in spec.split(";")
                if part.strip()
            ]
        except ValueError:
            raise ValueError(f"TORCHFT_TOPOLOGY: unparseable spec {spec!r}")
        topo = Topology(groups)
        if topo.world != world:
            raise ValueError(
                f"TORCHFT_TOPOLOGY lists {topo.world} ranks but the "
                f"collective world is {world} (explicit group lists do not "
                "adapt to elastic resizing — use hosts:K for that)"
            )
    if topo.n_groups == 1 and topo.world == world and world <= 1:
        return None
    return topo


def resolve_topology(world: int) -> "Optional[Topology]":
    """The env-driven entry point: ``TORCHFT_TOPOLOGY`` for ``world``
    ranks; ``None`` = flat (today's schedule, bit-identical)."""
    return parse_topology(env_str("TORCHFT_TOPOLOGY"), world)


@dataclass(frozen=True)
class Hop:
    """One wire stage of a reduction plan, resolved for one rank.

    ``sends``/``recvs`` are peer ranks in submission order.  When
    ``paired`` is True the two lists zip into simultaneous send+recv
    exchanges (the deadlock-free pairwise form every rank submits in the
    same global order); otherwise sends and recvs are one-directional
    ops (gather/broadcast legs).  ``scope``/``paired`` are descriptive
    plan metadata (tests pin the schedule through them): the executing
    pipeline binds hop semantics by NAME, and the wire model derives
    its boundary map from :meth:`Topology.inter`, not from here.
    """

    name: str
    scope: str
    sends: "Tuple[int, ...]" = ()
    recvs: "Tuple[int, ...]" = ()
    paired: bool = False


@dataclass(frozen=True)
class ReductionPlan:
    """The synthesized multi-hop schedule one rank executes per chunk.

    ``slice_count`` row-slices (one per group) replace the flat plan's
    per-rank slices; ``hops`` run in order, every rank submitting its
    ops in the same global (chunk, hop) interleave so the single-worker
    PG streams stay consistent per socket.
    """

    topology: Topology
    rank: int
    group_index: int
    is_leader: bool
    hops: "Tuple[Hop, ...]"

    @property
    def slice_count(self) -> int:
        return self.topology.n_groups

    def describe(self) -> str:
        return " -> ".join(
            f"{h.name}[s{len(h.sends)}/r{len(h.recvs)}]" for h in self.hops
        )


def _pairwise(leaders: "List[int]", gidx: int) -> "Tuple[Tuple[int, ...], Tuple[int, ...]]":
    """Round-robin pairwise exchange peers among leaders (the alltoall
    offset schedule): at offset o, send to leader (g+o) mod m and receive
    from leader (g-o) mod m — every leader submits the same offset order,
    so no two workers ever block on each other's unposted op."""
    m = len(leaders)
    sends = tuple(leaders[(gidx + o) % m] for o in range(1, m))
    recvs = tuple(leaders[(gidx - o) % m] for o in range(1, m))
    return sends, recvs


def synthesize_plan(topo: Topology, rank: int) -> ReductionPlan:
    """Synthesize this rank's hop schedule from the declarative topology
    (module docstring describes the four hops and their numerics)."""
    gidx = topo.group_index(rank)
    lead = topo.leader(gidx)
    members = topo.members(gidx)
    leaders = topo.leaders()
    is_leader = rank == lead
    hops: "List[Hop]" = []
    if is_leader:
        hops.append(
            Hop("intra.reduce", "intra", recvs=tuple(members))
        )
        ex_sends, ex_recvs = _pairwise(leaders, gidx)
        hops.append(
            Hop("inter.exchange", "inter", ex_sends, ex_recvs, paired=True)
        )
        hops.append(
            Hop("inter.gather", "inter", ex_sends, ex_recvs, paired=True)
        )
        hops.append(Hop("intra.bcast", "intra", sends=tuple(members)))
    else:
        hops.append(Hop("intra.reduce", "intra", sends=(lead,)))
        hops.append(Hop("inter.exchange", "inter"))
        hops.append(Hop("inter.gather", "inter"))
        hops.append(Hop("intra.bcast", "intra", recvs=(lead,)))
    return ReductionPlan(
        topology=topo,
        rank=rank,
        group_index=gidx,
        is_leader=is_leader,
        hops=tuple(hops),
    )
