"""Row-scaled 8-bit quantization for bandwidth-reduced DCN collectives.

Analog of the reference's fused quantization kernels
(reference: torchft/quantization.py:44-686): per-row absmax scales, 8-bit
payload, and scales interleaved into one flat comm buffer; dequant-reduce-
requant fuses the reduction.  The reference targets fp8e4nv on SM90 with an
int8 fallback (:30-41); both wire formats exist here:

- ``wire_dtype="int8"`` (default): uniform-grid int8 — the reference's
  fallback format, and the one the Pallas ON-DEVICE quantize kernel emits
  (torchft_tpu.ops.pallas_quant);
- ``wire_dtype="fp8_e4m3"``: ml_dtypes ``float8_e4m3fn`` payloads (the
  reference's fp8e4nv analog) — same 1 byte/element wire size, non-uniform
  grid with better relative precision for small-magnitude entries.  Host
  codec only (native C fast path like int8; bit-twiddled RNE encode +
  LUT decode): like the reference gates fp8 on SM90 hardware, the device
  kernel path stays int8 (no fp8 quantize kernel on current TPU Mosaic).

``TORCHFT_QUANT_WIRE`` selects the collective layer's default.

Two implementations share the int8 wire format:
- host path (numpy) used by the TCP/DCN collective layer below;
- device path (jax / Pallas TPU kernel, torchft_tpu.ops.pallas_quant) for
  quantizing on-chip before the host copy — see fused_* wrappers there.

Wire layout per array: ``[rows x f32 scale][rows x cols payload]``
flattened (payload dtype per ``wire_dtype``).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

INT8_MAX = 127.0
# float8_e4m3fn max finite value (the "fn" variant has no inf, max 448)
FP8_MAX = 448.0

WIRE_INT8 = "int8"
WIRE_FP8 = "fp8_e4m3"


def _wire(wire_dtype: str) -> "Tuple[np.dtype, float]":
    """(numpy payload dtype, absmax scale target) for a wire format."""
    if wire_dtype == WIRE_INT8:
        return np.dtype(np.int8), INT8_MAX
    if wire_dtype == WIRE_FP8:
        import ml_dtypes

        return np.dtype(ml_dtypes.float8_e4m3fn), FP8_MAX
    raise ValueError(
        f"unknown wire_dtype {wire_dtype!r}; expected "
        f"{WIRE_INT8!r} or {WIRE_FP8!r}"
    )


def resolve_wire(wire_dtype: "str | None") -> str:
    """Resolve a wire format: explicit value, else the
    ``TORCHFT_QUANT_WIRE`` env default, else int8 — validated either way.
    The one entry point every collective uses for the env knob."""
    if wire_dtype is None:
        from torchft_tpu.utils.env import env_str

        wire_dtype = env_str("TORCHFT_QUANT_WIRE", WIRE_INT8)
    _wire(wire_dtype)
    return wire_dtype


# ---------------------------------------------------------------------------
# native fused codec (native/quant.cc via ctypes)
# ---------------------------------------------------------------------------
#
# The numpy codec below is the reference semantics; the native codec is
# the fast path for BOTH wire formats (~6-8x: row-blocked fused passes,
# no temporaries, GIL released during the call — int8 via fused
# absmax/round/narrow loops, fp8_e4m3 via a bit-twiddled RNE encoder and
# a 256-entry decode LUT built from ml_dtypes).  Bit-identical output on
# finite inputs is asserted in tests/test_pallas_quant.py
# (TestNativeHostCodec + TestNativeFp8Codec).  ``TORCHFT_NO_NATIVE_QUANT=1``
# forces the numpy path (tests exercise both).

_native_checked = False
_native_lib_handle = None

_F32P = ctypes.POINTER(ctypes.c_float)
_I8P = ctypes.POINTER(ctypes.c_int8)


def _native_lib():
    # env checked live (not cached) so tests can flip between paths
    from torchft_tpu.utils.env import env_bool

    if env_bool("TORCHFT_NO_NATIVE_QUANT"):
        return None
    global _native_checked, _native_lib_handle
    if not _native_checked:
        _native_checked = True
        try:
            from torchft_tpu._native import get_lib

            _native_lib_handle = get_lib()
        except Exception:  # noqa: BLE001 - numpy fallback is complete
            _native_lib_handle = None
    return _native_lib_handle


def _f32_ptr(a: np.ndarray, byte_off: int = 0):
    return ctypes.cast(a.ctypes.data + byte_off, _F32P)


def _i8_ptr(a: np.ndarray, byte_off: int = 0):
    return ctypes.cast(a.ctypes.data + byte_off, _I8P)


_U8P = ctypes.POINTER(ctypes.c_uint8)


def _u8_ptr(a: np.ndarray, byte_off: int = 0):
    return ctypes.cast(a.ctypes.data + byte_off, _U8P)


_fp8_lut: "Optional[np.ndarray]" = None


def _fp8_decode_lut() -> np.ndarray:
    """256-entry f32 decode table for float8_e4m3fn, built FROM ml_dtypes
    so the native decode is bit-exact by construction (NaN codes stay NaN,
    matching the numpy widen of garbage payloads)."""
    global _fp8_lut
    if _fp8_lut is None:
        import ml_dtypes

        _fp8_lut = (
            np.arange(256, dtype=np.uint8)
            .view(ml_dtypes.float8_e4m3fn)
            .astype(np.float32)
        )
    return _fp8_lut


def _native_dequant_fma(
    lib, rows2: np.ndarray, scales: np.ndarray, acc: np.ndarray, overwrite: int
) -> bool:
    """Dispatch the wire format's native dequant-accumulate kernel into
    ``acc``; False when no kernel fits this payload dtype (fallback to
    numpy).  Preconditions (checked by callers): C-contiguous payload,
    f32 contiguous scales, f32 acc sized (rows, cols)."""
    if rows2.dtype == np.int8:
        lib.tft_dequant_fma(
            _i8_ptr(rows2), _f32_ptr(scales),
            rows2.shape[0], rows2.shape[1], _f32_ptr(acc), overwrite,
        )
        return True
    if rows2.dtype.itemsize == 1 and rows2.dtype.name == "float8_e4m3fn":
        lut = _fp8_decode_lut()
        lib.tft_dequant_fp8_fma(
            _u8_ptr(rows2), _f32_ptr(scales), _f32_ptr(lut),
            rows2.shape[0], rows2.shape[1], _f32_ptr(acc), overwrite,
        )
        return True
    return False


def _native_eligible(rows: np.ndarray, wire_dtype: str) -> bool:
    # Both wire formats have native kernels.  Bit-exactness vs numpy is
    # guaranteed for FINITE inputs; rows containing NaN take the same
    # degenerate branch on both paths (NaN-propagating absmax), but the
    # garbage PAYLOAD BYTES of such rows may differ (C element conversion
    # vs numpy astype-of-NaN) — row-level semantics, not byte identity.
    return (
        wire_dtype in (WIRE_INT8, WIRE_FP8)
        and _native_lib() is not None
        and rows.dtype == np.float32
        and rows.flags.c_contiguous
    )


def _as_rows(a: np.ndarray) -> np.ndarray:
    """View as 2-D (rows, cols): leading dim preserved, rest flattened."""
    if a.ndim == 0:
        return a.reshape(1, 1)
    if a.ndim == 1:
        return a.reshape(1, -1)
    return a.reshape(a.shape[0], -1)


def quantize(
    a: np.ndarray, wire_dtype: str = WIRE_INT8
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row absmax quantization -> (scales f32 [rows], payload 8-bit).

    Memory-bandwidth-bound on big arrays (the DCN host path quantizes
    ~GB-scale pseudograd fragments), so the hot loop is pass-minimal:
    multiply by the reciprocal scale (division is the slow ufunc), round
    in place, and skip the clip — absmax scaling bounds every product to
    [-max, max] by construction (1-ulp excursions round back to max).
    """
    dt, qmax = _wire(wire_dtype)
    rows = _as_rows(np.asarray(a, dtype=np.float32))
    if _native_eligible(rows, wire_dtype):
        scales = np.empty(rows.shape[0], dtype=np.float32)
        payload = np.empty(rows.shape, dtype=dt)
        if wire_dtype == WIRE_INT8:
            _native_lib().tft_quant_int8(
                _f32_ptr(rows), rows.shape[0], rows.shape[1],
                _f32_ptr(scales), _i8_ptr(payload),
            )
        else:
            _native_lib().tft_quant_fp8(
                _f32_ptr(rows), rows.shape[0], rows.shape[1],
                _f32_ptr(scales), _u8_ptr(payload),
            )
        return scales, payload
    absmax = np.abs(rows).max(axis=1)
    # Rows with absmax below qmax/f32max would overflow the reciprocal to
    # inf (inf*0 = NaN payload); values that tiny (< ~1e-36) carry no
    # quantizable signal, so such rows encode as exact zeros (scale 1.0),
    # same as all-zero rows.
    nonzero = absmax > qmax / np.finfo(np.float32).max
    scales = np.where(nonzero, absmax / qmax, 1.0).astype(np.float32)
    inv = np.divide(
        qmax, absmax, out=np.ones_like(absmax), where=nonzero
    ).astype(np.float32)
    tmp = rows * inv[:, None]
    if dt == np.int8:
        np.rint(tmp, out=tmp)
    # float8 cast rounds to nearest representable itself — no rint pass
    payload = tmp.astype(dt)
    return scales, payload


def quantize_packed(
    a: np.ndarray, wire_dtype: str = WIRE_INT8, pool=None
) -> np.ndarray:
    """Quantize straight into one packed wire buffer (header + scales +
    payload) — skips the ``pack`` concatenate pass.  Native fast path
    writes scales/payload into the buffer in place; fallback composes
    ``pack(*quantize(...))`` (same bytes either way).  ``pool``: optional
    BufferPool the wire buffer is drawn from (give it back after the
    send completes)."""
    rows = _as_rows(np.asarray(a, dtype=np.float32))
    if not _native_eligible(rows, wire_dtype):
        return pack(*quantize(rows, wire_dtype), wire_dtype)
    n_rows, cols = rows.shape
    nbytes = _HEADER_BYTES + n_rows * 4 + n_rows * cols
    buf = (
        pool.take(nbytes, np.uint8) if pool is not None
        else np.empty(nbytes, dtype=np.uint8)
    )
    buf[0] = _PACK_VERSION
    buf[1] = _WIRE_CODES[wire_dtype]
    buf[2] = buf[3] = 0
    # scales live at byte offset 4 — 4-byte aligned (numpy bases are
    # 16-aligned), which is all f32 stores need
    if wire_dtype == WIRE_INT8:
        _native_lib().tft_quant_int8(
            _f32_ptr(rows), n_rows, cols,
            _f32_ptr(buf, _HEADER_BYTES),
            _i8_ptr(buf, _HEADER_BYTES + n_rows * 4),
        )
    else:
        _native_lib().tft_quant_fp8(
            _f32_ptr(rows), n_rows, cols,
            _f32_ptr(buf, _HEADER_BYTES),
            _u8_ptr(buf, _HEADER_BYTES + n_rows * 4),
        )
    return buf


def dequantize(
    scales: np.ndarray,
    payload: np.ndarray,
    shape: "Tuple[int, ...]",
    dtype: np.dtype,
) -> np.ndarray:
    lib = _native_lib()
    if (
        lib is not None
        and dtype == np.float32
        and scales.dtype == np.float32
        and payload.flags.c_contiguous
        and scales.flags.c_contiguous
    ):
        rows2 = _as_rows(payload)
        out = np.empty(rows2.shape, dtype=np.float32)
        # guard above requires contiguous scales — pass it directly (an
        # ascontiguousarray temporary would be unreferenced by the time
        # ctypes extracts the address if the guard were ever relaxed)
        if _native_dequant_fma(lib, rows2, scales, out, 1):
            return out.reshape(shape)
    # one fused payload x f32 -> f32 pass; asarray avoids the astype copy
    # when dtype is already float32 (the common DCN case).  ml_dtypes fp8
    # payloads lack a numpy multiply loop against f32 — widen first (still
    # one extra pass only on the fp8 leg).
    if payload.dtype != np.int8:
        payload = payload.astype(np.float32)
    out = np.multiply(payload, scales[:, None], dtype=np.float32)
    return np.asarray(out.reshape(shape), dtype=dtype)


# Packed-buffer header: [version u8, wire-format code u8, reserved u16].
# The format code travels ON the wire so two ranks with divergent
# TORCHFT_QUANT_WIRE settings (partial rollout, heterogeneous launcher
# env) fail loudly at unpack instead of silently decoding each other's
# 1-byte payloads as the wrong grid.
_PACK_VERSION = 1
_WIRE_CODES = {WIRE_INT8: 0, WIRE_FP8: 1}
_WIRE_NAMES = {v: k for k, v in _WIRE_CODES.items()}
_HEADER_BYTES = 4


def pack(
    scales: np.ndarray, payload: np.ndarray, wire_dtype: str = WIRE_INT8
) -> np.ndarray:
    """Interleave header + scales + payload into one uint8 comm buffer
    (reference quantization.py:54-165 packs fp8 payload + f32 scales)."""
    header = np.array(
        [_PACK_VERSION, _WIRE_CODES[wire_dtype], 0, 0], dtype=np.uint8
    )
    return np.concatenate(
        [header, scales.view(np.uint8).ravel(), payload.view(np.uint8).ravel()]
    )


def unpack(
    buf: np.ndarray, rows: int, cols: int, wire_dtype: str = WIRE_INT8
) -> Tuple[np.ndarray, np.ndarray]:
    """Split a packed wire buffer back into (scales, payload), validating
    the on-wire format header against the locally expected ``wire_dtype``.

    Returns VIEWS into ``buf`` (zero-copy): every consumer immediately
    widens the payload in its own f32 pass, so a defensive copy here would
    only add a full memory pass at GB fragment scale."""
    dt, _ = _wire(wire_dtype)
    version, code = int(buf[0]), int(buf[1])
    if version != _PACK_VERSION:
        raise ValueError(
            f"quantized buffer version {version} != {_PACK_VERSION} "
            "(peer running an incompatible codec)"
        )
    if code != _WIRE_CODES[wire_dtype]:
        raise ValueError(
            f"wire format mismatch: peer sent "
            f"{_WIRE_NAMES.get(code, f'code {code}')}, local expects "
            f"{wire_dtype} (check TORCHFT_QUANT_WIRE on every rank)"
        )
    scale_end = _HEADER_BYTES + rows * 4
    scales = buf[_HEADER_BYTES:scale_end].view(np.float32)
    payload = buf[scale_end : scale_end + rows * cols].view(dt).reshape(
        rows, cols
    )
    return scales, payload


def dequantize_into(
    scales: np.ndarray, payload: np.ndarray, out: np.ndarray,
) -> None:
    """Dequantize into a caller-provided f32 ``(rows, cols)`` block — the
    allgather-reassembly path writes each rank's piece straight into its
    offset of the full output, skipping the per-piece alloc + concat."""
    rows2 = _as_rows(payload)
    assert out.dtype == np.float32 and out.flags.c_contiguous
    lib = _native_lib()
    if (
        lib is not None
        and scales.dtype == np.float32
        and rows2.flags.c_contiguous
    ):
        sc = np.ascontiguousarray(scales)
        if _native_dequant_fma(lib, rows2, sc, out, 1):
            return
    pay = rows2 if rows2.dtype == np.int8 else rows2.astype(np.float32)
    np.multiply(pay, scales[:, None], dtype=np.float32, out=out.reshape(rows2.shape))


# ---------------------------------------------------------------------------
# row-range codec surface (the chunked-pipeline / worker-pool entry points)
# ---------------------------------------------------------------------------
#
# Each helper operates on a row range [r0, r1) of a PACKED wire buffer
# (header + scales + payload, layout as ``pack``), against a full 2-D f32
# source/accumulator/output with its own row offset.  Rows are independent
# in every codec kernel (per-row absmax, per-row scale), so concurrent
# calls over DISJOINT ranges of one buffer are data-race-free — this is
# what ``ops/codec_pool.py`` fans across a small worker pool, with the
# native kernels releasing the GIL (native/quant.cc ``*_rows`` entry
# points).  The numpy fallbacks apply the exact per-row math of the
# monolithic codec above, so chunked output is bit-identical to monolithic
# on finite inputs for BOTH paths (asserted in
# tests/test_quantized_collectives.py).


def packed_nbytes(rows: int, cols: int) -> int:
    """Byte size of a packed wire buffer (8-bit payload wire formats)."""
    return _HEADER_BYTES + rows * 4 + rows * cols


def new_packed(
    rows: int, cols: int, wire_dtype: str = WIRE_INT8, pool=None
) -> np.ndarray:
    """Allocate (or pool-take) a packed wire buffer and write its header;
    scales/payload regions are left uninitialized for the row-range
    writers below."""
    _wire(wire_dtype)
    nbytes = packed_nbytes(rows, cols)
    buf = (
        pool.take(nbytes, np.uint8) if pool is not None
        else np.empty(nbytes, dtype=np.uint8)
    )
    buf[0] = _PACK_VERSION
    buf[1] = _WIRE_CODES[wire_dtype]
    buf[2] = buf[3] = 0
    return buf


def _packed_views(
    buf: np.ndarray, rows: int, cols: int, wire_dtype: str
) -> "Tuple[np.ndarray, np.ndarray]":
    """(scales f32 [rows], payload [rows, cols]) views into a packed buf
    (no header validation — internal writer-side helper)."""
    dt, _ = _wire(wire_dtype)
    scale_end = _HEADER_BYTES + rows * 4
    scales = buf[_HEADER_BYTES:scale_end].view(np.float32)
    payload = buf[scale_end : scale_end + rows * cols].view(dt).reshape(
        rows, cols
    )
    return scales, payload


def _rows_native(src: np.ndarray) -> bool:
    return (
        _native_lib() is not None
        and src.dtype == np.float32
        and src.flags.c_contiguous
    )


def quantize_rows_packed(
    src: np.ndarray,
    src_row0: int,
    buf: np.ndarray,
    rows: int,
    cols: int,
    r0: int,
    r1: int,
    wire_dtype: str = WIRE_INT8,
) -> None:
    """Quantize ``src[src_row0 : src_row0 + (r1-r0)]`` into packed ``buf``
    rows ``[r0, r1)``.  ``src`` is C-contiguous f32 ``(*, cols)``."""
    if r1 <= r0:
        return
    if _rows_native(src):
        lib = _native_lib()
        # pre-offset the source base so the kernel's single row index
        # covers both sides: row r reads src[src_row0 + (r - r0)]
        in_ptr = _f32_ptr(src, (src_row0 - r0) * cols * 4)
        sc_ptr = _f32_ptr(buf, _HEADER_BYTES)
        if wire_dtype == WIRE_INT8:
            lib.tft_quant_int8_rows(
                in_ptr, r0, r1, cols, sc_ptr,
                _i8_ptr(buf, _HEADER_BYTES + rows * 4),
            )
        else:
            lib.tft_quant_fp8_rows(
                in_ptr, r0, r1, cols, sc_ptr,
                _u8_ptr(buf, _HEADER_BYTES + rows * 4),
            )
        return
    scales, payload = quantize(
        src[src_row0 : src_row0 + (r1 - r0)].reshape(r1 - r0, cols),
        wire_dtype,
    )
    sc, pl = _packed_views(buf, rows, cols, wire_dtype)
    sc[r0:r1] = scales
    pl[r0:r1] = payload


def validate_packed(buf: np.ndarray, wire_dtype: str = WIRE_INT8) -> None:
    """Validate a packed buffer's on-wire header (version + format code)
    — the same loud cross-rank wire-format guard as :func:`unpack`,
    without building the views.  The pipeline calls this ONCE per
    received buffer before fanning row blocks; the row-range writers
    below stay validation-free on the hot path."""
    unpack(buf, 0, 0, wire_dtype)


def fma_rows_packed(
    buf: np.ndarray,
    rows: int,
    cols: int,
    r0: int,
    r1: int,
    wire_dtype: str,
    acc: np.ndarray,
    acc_row0: int,
    overwrite: bool,
) -> None:
    """``acc[acc_row0 : acc_row0 + (r1-r0)]`` (op)= dequant of packed
    ``buf`` rows ``[r0, r1)`` (op: overwrite or accumulate).  The caller
    validates the buffer header once via :func:`validate_packed`."""
    if r1 <= r0:
        return
    if _rows_native(acc):
        lib = _native_lib()
        acc_ptr = _f32_ptr(acc, (acc_row0 - r0) * cols * 4)
        sc_ptr = _f32_ptr(buf, _HEADER_BYTES)
        ow = 1 if overwrite else 0
        if wire_dtype == WIRE_INT8:
            lib.tft_dequant_fma_rows(
                _i8_ptr(buf, _HEADER_BYTES + rows * 4), sc_ptr,
                r0, r1, cols, acc_ptr, ow,
            )
        else:
            lut = _fp8_decode_lut()
            lib.tft_dequant_fp8_fma_rows(
                _u8_ptr(buf, _HEADER_BYTES + rows * 4), sc_ptr,
                _f32_ptr(lut), r0, r1, cols, acc_ptr, ow,
            )
        return
    sc, pl = _packed_views(buf, rows, cols, wire_dtype)
    pay = pl[r0:r1]
    if pay.dtype != np.int8:
        pay = pay.astype(np.float32)
    target = acc[acc_row0 : acc_row0 + (r1 - r0)].reshape(r1 - r0, cols)
    if overwrite:
        np.multiply(pay, sc[r0:r1, None], dtype=np.float32, out=target)
    else:
        target += np.multiply(pay, sc[r0:r1, None], dtype=np.float32)


def div_rows(acc: np.ndarray, r0: int, r1: int, divisor: float) -> None:
    """In-place ``acc[r0:r1] /= divisor`` (the fused AVG step), native
    when available — bit-identical either way (true divide)."""
    if r1 <= r0 or not divisor:
        return
    if _rows_native(acc):
        _native_lib().tft_div_f32_rows(
            _f32_ptr(acc), r0, r1, acc.shape[-1] if acc.ndim > 1 else 1,
            float(divisor),
        )
        return
    acc[r0:r1] /= divisor


def dequant_rows_into(
    buf: np.ndarray,
    rows: int,
    cols: int,
    r0: int,
    r1: int,
    wire_dtype: str,
    out: np.ndarray,
    out_row0: int,
) -> None:
    """``out[out_row0 : out_row0 + (r1-r0)] = dequant(buf rows [r0,r1))``
    — the allgather-reassembly writer (overwrite form of
    :func:`fma_rows_packed`)."""
    fma_rows_packed(
        buf, rows, cols, r0, r1, wire_dtype, out, out_row0, overwrite=True
    )


def reduce_quantized(
    bufs: "List[np.ndarray]",
    rows: int,
    cols: int,
    average_by: int = 0,
    requantize: bool = True,
    wire_dtype: str = WIRE_INT8,
    raw: "Optional[np.ndarray]" = None,
    pool=None,
) -> np.ndarray:
    """Dequantize each packed buffer, accumulate in f32, requantize.

    Analog of the reference's fused dequant-accumulate-requant kernel
    (reference quantization.py:262-430). ``average_by > 0`` divides the
    accumulated sum (AVG fusion). ``requantize=False`` returns the raw f32
    accumulator (for results that stay local rather than going back on the
    wire).  ``raw`` is an optional f32 ``(rows, cols)`` contribution added
    WITHOUT passing through the codec — the quantized allreduce feeds a
    rank's own row-slice through here, so a rank pays neither codec time
    nor quantization error on its own data.  ``pool``: optional BufferPool
    for the accumulator and (when requantizing) the output wire buffer;
    the accumulator is returned to the pool before a requantized return.
    """
    lib = _native_lib() if wire_dtype in (WIRE_INT8, WIRE_FP8) else None

    def _fresh_acc() -> np.ndarray:
        if pool is not None:
            return pool.take((rows, cols), np.float32)
        return np.empty((rows, cols), dtype=np.float32)

    acc: "np.ndarray | None" = None
    if raw is not None:
        raw = np.ascontiguousarray(raw, dtype=np.float32).reshape(rows, cols)
        acc = _fresh_acc()
        np.copyto(acc, raw)
    for buf in bufs:
        scales, payload = unpack(buf, rows, cols, wire_dtype)
        if lib is not None and payload.flags.c_contiguous:
            first = acc is None
            if first:
                acc = _fresh_acc()
            # scales is an unaligned 4-byte-offset view into the wire
            # buffer — fine for f32 loads, but take a contiguous copy so
            # the pointer math below is plain
            sc = np.ascontiguousarray(scales)
            # unpack() derived the payload dtype from wire_dtype and lib
            # is gated on the same wire_dtype, so the dispatch always has
            # a kernel here (the bool return exists for dequantize's
            # caller-supplied payloads)
            dispatched = _native_dequant_fma(
                lib, payload, sc, acc, 1 if first else 0
            )
            assert dispatched, payload.dtype
            continue
        # numpy reference path: fused payload x f32 -> f32 product in one
        # pass; first buffer becomes the accumulator directly
        if payload.dtype != np.int8:
            payload = payload.astype(np.float32)
        if acc is None:
            acc = _fresh_acc()
            np.multiply(payload, scales[:, None], dtype=np.float32, out=acc)
        else:
            acc += np.multiply(payload, scales[:, None], dtype=np.float32)
    if acc is None:
        acc = np.zeros((rows, cols), dtype=np.float32)
    if average_by > 0:
        if lib is not None and acc.flags.c_contiguous:
            lib.tft_div_f32(_f32_ptr(acc), acc.size, float(average_by))
        else:
            acc /= average_by
    if not requantize:
        return acc  # caller takes ownership (pooled or not)
    out = quantize_packed(acc, wire_dtype, pool=pool)
    if pool is not None:
        pool.give(acc)
    return out
