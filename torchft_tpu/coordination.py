"""Coordination API: native servers + protocol clients.

Public low-level surface for building custom fault-tolerance algorithms,
analog of reference torchft/coordination.py:18-33 (which re-exports the Rust
Lighthouse/Manager client+server classes).  Servers run native C++ threads
(see ``native/``); clients speak the framed-JSON protocol directly from
Python — socket waits release the GIL, mirroring the reference's
GIL-releasing PyO3 calls (reference: src/lib.rs:153-281).

Wire format: 4-byte big-endian length + UTF-8 JSON.
Request: ``{"method": ..., "params": {...}, "timeout_ms": N,
"traceparent": "00-<trace>-<span>-<flags>"?}`` — the optional
``traceparent`` envelope field carries the distributed-tracing context
(utils/tracing.py); servers continue it into one ``rpc.<method>`` span
per request and propagate it on their own downstream RPCs.
Response: ``{"ok": true, "result": {...}}`` or
``{"ok": false, "error": msg, "code": "timeout"?}``.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Dict, List, Optional, Sequence

from torchft_tpu import _native
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import linkstats as _linkstats
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.env import env_bool
from torchft_tpu.utils.retry import RetryPolicy

__all__ = [
    "LighthouseServer",
    "LighthouseClient",
    "ManagerServer",
    "ManagerClient",
    "StoreServer",
    "StoreClient",
    "NotLeaderError",
    "Quorum",
    "QuorumMember",
    "QuorumResult",
    "parse_endpoints",
]


def _to_ms(timeout: "float | timedelta") -> int:
    if isinstance(timeout, timedelta):
        return int(timeout.total_seconds() * 1000)
    return int(timeout * 1000)


# ---------------------------------------------------------------------------
# data types (mirror reference proto/torchft.proto:37-53 and _torchft.pyi)
# ---------------------------------------------------------------------------


@dataclass
class QuorumMember:
    replica_id: str
    address: str = ""
    store_address: str = ""
    step: int = 0
    world_size: int = 1
    shrink_only: bool = False
    commit_failures: int = 0
    # Online parallelism switching (parallel/layout.py): the member's
    # current/staged layout epoch — the monotone counter the two-phase
    # layout commit is keyed on (docs/protocol.md "Layout epochs").
    layout_epoch: int = 0
    data: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "QuorumMember":
        """Build from the wire-protocol dict (tolerates missing fields)."""
        return QuorumMember(
            replica_id=d.get("replica_id", ""),
            address=d.get("address", ""),
            store_address=d.get("store_address", ""),
            step=d.get("step", 0),
            world_size=d.get("world_size", 1),
            shrink_only=d.get("shrink_only", False),
            commit_failures=d.get("commit_failures", 0),
            layout_epoch=d.get("layout_epoch", 0),
            data=d.get("data", ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        """Wire-protocol dict for RPC payloads."""
        return {
            "replica_id": self.replica_id,
            "address": self.address,
            "store_address": self.store_address,
            "step": self.step,
            "world_size": self.world_size,
            "shrink_only": self.shrink_only,
            "commit_failures": self.commit_failures,
            "layout_epoch": self.layout_epoch,
            "data": self.data,
        }


@dataclass
class Quorum:
    quorum_id: int
    participants: List[QuorumMember] = field(default_factory=list)
    created_ms: int = 0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Quorum":
        """Build from the wire-protocol dict."""
        return Quorum(
            quorum_id=d.get("quorum_id", 0),
            participants=[QuorumMember.from_dict(p) for p in d.get("participants", [])],
            created_ms=d.get("created_ms", 0),
        )


@dataclass
class QuorumResult:
    """Per-replica instructions computed from a cluster quorum.

    Field parity with reference torchft/_torchft.pyi QuorumResult.
    """

    quorum_id: int = 0
    replica_rank: int = 0
    replica_world_size: int = 1
    recover_src_manager_address: str = ""
    recover_src_replica_rank: Optional[int] = None
    recover_dst_replica_ranks: List[int] = field(default_factory=list)
    store_address: str = ""
    max_step: int = 0
    max_replica_rank: Optional[int] = None
    max_world_size: int = 1
    heal: bool = False
    commit_failures: int = 0
    # Online parallelism switching (parallel/layout.py): the min/max
    # layout epoch reported across the quorum (min == max == E is the
    # fleet-wide commit signal for a staged layout at epoch E) and the
    # participant roster in replica-rank order — each entry carries
    # replica_id, manager address, layout_epoch and the opaque shard
    # manifest, which is what lets every group compute the same reshard
    # slice-diff plan with zero extra RPCs.
    max_layout_epoch: int = 0
    min_layout_epoch: int = 0
    # roster entries are {replica_id, address, layout_epoch, data} dicts
    participants: List[Any] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "QuorumResult":
        """Build from the wire-protocol dict."""
        return QuorumResult(
            quorum_id=d.get("quorum_id", 0),
            replica_rank=d.get("replica_rank", 0),
            replica_world_size=d.get("replica_world_size", 1),
            recover_src_manager_address=d.get("recover_src_manager_address", ""),
            recover_src_replica_rank=d.get("recover_src_replica_rank"),
            recover_dst_replica_ranks=list(d.get("recover_dst_replica_ranks", [])),
            store_address=d.get("store_address", ""),
            max_step=d.get("max_step", 0),
            max_replica_rank=d.get("max_replica_rank"),
            max_world_size=d.get("max_world_size", 1),
            heal=d.get("heal", False),
            commit_failures=d.get("commit_failures", 0),
            max_layout_epoch=d.get("max_layout_epoch", 0),
            min_layout_epoch=d.get("min_layout_epoch", 0),
            participants=list(d.get("participants", [])),
        )


# ---------------------------------------------------------------------------
# protocol client
# ---------------------------------------------------------------------------


def parse_host_port(addr: str) -> "tuple[str, int]":
    """Split "host:port" (including "[v6]:port" and ":port") — the one
    address parser shared by every client/probe in the package."""
    if addr.startswith("["):
        host, _, port = addr[1:].partition("]:")
        return host, int(port)
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def parse_endpoints(addrs: str) -> "List[str]":
    """Split a ``TORCHFT_LIGHTHOUSE`` value into endpoint addresses:
    ``"host1:p1,host2:p2,host3:p3"`` -> ``["host1:p1", ...]`` (whitespace
    around entries tolerated; empty entries dropped).  A single-address
    value parses to a one-element list — every lighthouse client accepts
    both forms (coordination-plane HA, docs/architecture.md)."""
    return [part.strip() for part in addrs.split(",") if part.strip()]


class RpcError(RuntimeError):
    pass


class NotLeaderError(RpcError):
    """A follower lighthouse peer declined a leader-only method
    (coordination-plane HA).  ``leader`` is the follower's freshest hint
    for the current lease holder ("" when it knows none) — failover
    clients jump straight to it instead of walking the whole list."""

    def __init__(self, message: str, leader: str = "") -> None:
        super().__init__(message)
        self.leader = leader


#: Frame-size ceiling shared with the native side (native/net.h
#: kMaxFrameBytes): a reply header claiming more is a corrupt or hostile
#: peer, not a large message — fail the connection instead of trying to
#: buffer gigabytes.
_MAX_FRAME_BYTES = 512 * 1024 * 1024


# Connect retry: the same curve the old ad-hoc loop used (100ms base,
# x1.5, 10s cap) plus full jitter so replicas re-dialing a restarted
# server do not dogpile it in lockstep.  Retryable: any OSError (refused,
# unreachable, per-attempt socket timeout) until the deadline budget —
# the budget, not the attempt count, bounds the wait.
_CONNECT_POLICY = RetryPolicy(
    name="rpc.connect",
    base_delay=0.1,
    multiplier=1.5,
    max_delay=10.0,
    retryable=(OSError,),
)


class _RpcClient:
    """Persistent framed-JSON connection; reconnects with backoff on failure.

    ``fault_site``: optional chaos injection site consulted inside each
    call's send/recv attempt (utils/faults.py) — an injected ``drop`` takes
    exactly the broken-connection code path, an injected ``raise`` escapes
    like any non-connection error.
    """

    def __init__(
        self,
        addr: str,
        connect_timeout: float = 10.0,
        fault_site: "Optional[str]" = None,
    ) -> None:
        self._addr = addr
        self._connect_timeout = connect_timeout
        self._fault_site = fault_site
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # link-state plane (utils/linkstats.py): every round trip on this
        # connection is one rpc-plane RTT sample against the peer host —
        # resolved once here, not per call
        from torchft_tpu.utils.hostident import local_host_identities

        host, _port = parse_host_port(addr)
        self._link_host = host or "unknown"
        self._link_local = self._link_host in local_host_identities()

    def _host_port(self) -> "tuple[str, int]":
        return parse_host_port(self._addr)

    def _connect(self, deadline: float) -> socket.socket:
        host, port = self._host_port()

        def attempt(budget: "Optional[float]") -> socket.socket:
            sock = socket.create_connection(
                (host, port), timeout=min(budget if budget else 5.0, 5.0)
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock

        try:
            return _CONNECT_POLICY.run(
                attempt,
                timeout=max(deadline - time.monotonic(), 0.0),
                op="rpc.connect",
            )
        except TimeoutError as e:
            raise TimeoutError(
                f"timeout connecting to {self._addr}: {e.__cause__ or e}"
            ) from e

    def call(
        self,
        method: str,
        params: Dict[str, Any],
        timeout: "float | timedelta",
        idempotent: bool = True,
    ) -> Dict[str, Any]:
        """One RPC round trip.

        ``idempotent``: when True (default) a call that dies on a broken
        connection is re-sent ONCE after reconnecting (e.g. the server
        restarted between calls on this pooled connection).  A re-send can
        double-deliver a request whose first copy was applied before the
        connection died, so non-idempotent methods — ``should_commit``
        votes, whose double delivery could corrupt the commit barrier —
        must pass False and surface the ConnectionError to their caller
        instead.
        """
        timeout_s = (
            timeout.total_seconds() if isinstance(timeout, timedelta) else timeout
        )
        deadline = time.monotonic() + timeout_s
        attempts = 2 if idempotent else 1
        # Pooled-connection lock: one in-flight request per connection IS
        # the contract; callers queue on the round trip by design, and
        # every socket op under it is deadline-bounded (settimeout above
        # each send/recv) — hence the lint waiver.
        # Distributed tracing: the current context (bound by the Manager
        # around its round) rides the request envelope; None when tracing
        # is off or the step is unsampled — the disabled path is one
        # module-global check (budget-tested in tests/test_tracing.py).
        traceparent = _tracing.current_traceparent()
        # Opt-in WAN realism for coordination RPCs (TORCHFT_WIRE_RPC=1):
        # one serving-wire-model charge per round trip — first-byte RTT
        # across the TORCHFT_TOPOLOGY boundary (payloads are sub-KB, so
        # bandwidth debt is noise; nbytes=0 skips the bucket).  Scope:
        # the Python client side only — native peer-to-peer traffic
        # (lease exchanges, C++ heartbeats) is in-process and unshaped.
        # Default off (one env test per call; the bench flips it
        # mid-process, so it cannot be latched at import); the serving
        # import resolves lazily only when enabled.
        charged = 0.0
        if env_bool("TORCHFT_WIRE_RPC", False):
            from torchft_tpu.serving import wire as _serving_wire

            charged = _serving_wire.get_shaper().charge(self._addr, 0)
        with self._lock:  # tft-lint: allow(lock-discipline)
            for attempt in range(attempts):
                if self._sock is None:
                    self._sock = self._connect(
                        min(deadline, time.monotonic() + self._connect_timeout)
                    )
                req: "Dict[str, Any]" = {
                    "method": method,
                    "params": params,
                    "timeout_ms": max(int((deadline - time.monotonic()) * 1000), 1),
                }
                if traceparent is not None:
                    req["traceparent"] = traceparent
                payload = json.dumps(req).encode()
                try:
                    if self._fault_site is not None:
                        _faults.check(self._fault_site)
                    self._sock.settimeout(max(deadline - time.monotonic(), 0.001))
                    t0 = time.perf_counter()
                    self._sock.sendall(struct.pack(">I", len(payload)) + payload)
                    reply = self._recv_frame(deadline)
                    # rpc-plane link sample: one RTT per round trip (the
                    # whole wall IS first-byte — sub-KB payloads carry no
                    # bandwidth signal, so goodput stays unestimated on
                    # this plane).  A shaped (TORCHFT_WIRE_RPC) call to a
                    # local host keys under a WAN pseudo-host so the
                    # modeled link never averages into the local fabric.
                    rtt = charged + (time.perf_counter() - t0)
                    wan_local = charged > 0.0 and self._link_local
                    _linkstats.record(
                        self._link_host + "#wan" if wan_local
                        else self._link_host,
                        "rpc",
                        len(payload) + len(reply),
                        rtt,
                        first_byte_s=rtt,
                        local=self._link_local and charged == 0.0,
                    )
                    break
                except (OSError, ConnectionError) as e:
                    self.close()
                    if isinstance(e, socket.timeout):
                        raise TimeoutError(
                            f"rpc {method} to {self._addr} timed out: {e}"
                        ) from e
                    if attempt == attempts - 1:
                        # Connection-level failure, not a deadline: report it
                        # as such so callers can tell a crashed server from a
                        # protocol wait expiring.
                        raise ConnectionError(
                            f"rpc {method} to {self._addr} failed: {e}"
                        ) from e
                    # Broken connection (e.g. server restarted): retry once.
                    continue
            # A reply that does not parse to a JSON object is a protocol
            # violation (corrupt frame, non-UTF8 bytes, wrong peer): fail
            # the call cleanly and drop the connection so the next call
            # starts fresh instead of desynchronizing on this one.
            try:
                resp = json.loads(reply)
            except (UnicodeDecodeError, ValueError) as e:
                self.close()
                raise RpcError(
                    f"rpc {method} to {self._addr}: malformed reply frame: {e}"
                ) from e
            if not isinstance(resp, dict):
                self.close()
                raise RpcError(
                    f"rpc {method} to {self._addr}: reply is not a JSON "
                    f"object: {type(resp).__name__}"
                )
            if not resp.get("ok"):
                if resp.get("code") == "timeout":
                    raise TimeoutError(resp.get("error", "timeout"))
                if resp.get("code") == "not_leader":
                    raise NotLeaderError(
                        resp.get("error", "not the leader"),
                        leader=resp.get("leader", ""),
                    )
                raise RpcError(resp.get("error", "rpc failed"))
            return resp.get("result", {})

    def _recv_frame(self, deadline: float) -> bytes:
        assert self._sock is not None
        header = self._recv_exact(4, deadline)
        (length,) = struct.unpack(">I", header)
        if length > _MAX_FRAME_BYTES:
            raise ConnectionError(
                f"frame length {length} exceeds the {_MAX_FRAME_BYTES}-byte "
                f"protocol ceiling (corrupt or non-protocol peer)"
            )
        return self._recv_exact(length, deadline)

    def _recv_exact(self, n: int, deadline: float) -> bytes:
        assert self._sock is not None
        buf = b""
        while len(buf) < n:
            self._sock.settimeout(max(deadline - time.monotonic(), 0.001))
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("connection closed by peer")
            buf += chunk
        return buf

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


#: Per-hop connect budget inside a failover walk: a DEAD endpoint (port
#: refused/unreachable) must cost this long, not the caller's deadline —
#: the walk itself is the retry layer across endpoints, and endpoints
#: that were merely slow get revisited by the next walk pass anyway.
_FAILOVER_CONNECT_SLICE_S = 0.35

# A full failover-walk pass that found no servable leader (every peer
# dead or answering NOT_LEADER — the fleet is mid-election) is retried
# on this policy: short jittered backoff inside the caller's deadline
# budget.  The budget, never the attempt count, bounds the wait.
_WALK_POLICY = RetryPolicy(
    name="rpc.failover",
    base_delay=0.05,
    multiplier=1.5,
    max_delay=0.5,
    retryable=(ConnectionError, NotLeaderError),
)


class _FailoverRpcClient:
    """Multi-endpoint framed-JSON client (coordination-plane HA).

    Wraps one :class:`_RpcClient` per endpoint of a comma-list address,
    walks dead endpoints, follows ``NOT_LEADER`` redirects to the named
    holder, and stays pinned to whichever endpoint last answered.  One
    walk pass visits every endpoint at most once (plus bounded redirect
    hops); passes are retried on the unified retry layer while the fleet
    elects, inside the caller's deadline.  A dead endpoint costs a
    bounded connect slice, never the whole deadline — the endpoint that
    answers gets all remaining budget (quorum is a long-poll).

    With a single endpoint the behavior is exactly ``_RpcClient``'s (no
    walk, no policy wrap) — the pre-HA wire behavior.
    """

    def __init__(
        self,
        addrs: str,
        connect_timeout: float = 10.0,
        fault_site: "Optional[str]" = None,
    ) -> None:
        self._endpoints = parse_endpoints(addrs)
        if not self._endpoints:
            raise ValueError(f"no lighthouse endpoints in {addrs!r}")
        self._connect_timeout = connect_timeout
        self._fault_site = fault_site
        self._clients: "Dict[str, _RpcClient]" = {}
        self._cur = 0
        self._redirect = ""  # leader hint from a NOT_LEADER reply

    def endpoints(self) -> "List[str]":
        return list(self._endpoints)

    def current(self) -> str:
        """The endpoint the next call will try first."""
        return self._redirect or self._endpoints[self._cur]

    def _client_for(self, addr: str, connect_slice: float) -> _RpcClient:
        client = self._clients.get(addr)
        if client is None:
            client = _RpcClient(
                addr, connect_slice, fault_site=self._fault_site
            )
            self._clients[addr] = client
        else:
            # per-hop connect budget: bounded by the walk, not the ctor
            client._connect_timeout = connect_slice
        return client

    def _advance(self) -> None:
        self._redirect = ""
        self._cur = (self._cur + 1) % len(self._endpoints)

    def _walk_once(
        self,
        method: str,
        params: "Dict[str, Any]",
        budget: float,
        idempotent: bool,
        stats: "Dict[str, int]",
    ) -> "Dict[str, Any]":
        deadline = time.monotonic() + budget
        n = len(self._endpoints)
        # every endpoint once + a redirect hop per follower answer
        last: "Optional[Exception]" = None
        for _hop in range(2 * n + 2):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            addr = self._redirect or self._endpoints[self._cur]
            connect_slice = min(
                self._connect_timeout,
                _FAILOVER_CONNECT_SLICE_S,
                max(remaining, 0.05),
            )
            client = self._client_for(addr, connect_slice)
            try:
                return client.call(
                    method, params, remaining, idempotent=idempotent
                )
            except NotLeaderError as e:
                last = e
                stats["redirects"] += 1
                _metrics.HA_REDIRECTS.inc()
                if e.leader and e.leader != addr:
                    self._redirect = e.leader
                else:
                    self._advance()
            except (ConnectionError, TimeoutError, OSError) as e:
                # the caller's own deadline expiring on a live endpoint is
                # a timeout, not a dead peer: surface it unchanged
                if (
                    isinstance(e, TimeoutError)
                    and deadline - time.monotonic() <= 0.001
                ):
                    raise
                last = e
                stats["failovers"] += 1
                _metrics.HA_FAILOVERS.inc()
                # dead peer (or dead hinted leader): resume the list walk
                self._advance()
        if isinstance(last, NotLeaderError):
            raise last  # fleet mid-election: retryable by the walk policy
        raise ConnectionError(
            f"rpc {method} failed on every lighthouse endpoint "
            f"{self._endpoints}: {last}"
        ) from last

    def call(
        self,
        method: str,
        params: "Dict[str, Any]",
        timeout: "float | timedelta",
        idempotent: bool = True,
    ) -> "Dict[str, Any]":
        timeout_s = (
            timeout.total_seconds() if isinstance(timeout, timedelta) else timeout
        )
        if len(self._endpoints) == 1:
            return self._client_for(
                self._endpoints[0], self._connect_timeout
            ).call(method, params, timeout_s, idempotent=idempotent)
        stats = {"failovers": 0, "redirects": 0}
        t0_ns = time.time_ns()

        def attempt(budget: "Optional[float]") -> "Dict[str, Any]":
            return self._walk_once(
                method,
                params,
                budget if budget is not None else timeout_s,
                idempotent,
                stats,
            )

        try:
            return _WALK_POLICY.run(attempt, timeout=timeout_s, op="rpc.failover")
        finally:
            if stats["failovers"] or stats["redirects"]:
                # one record per walked call: who we ended up on and what
                # the walk cost — the post-mortem trail of a failover
                _flightrec.record(
                    "ha.failover",
                    start_ns=t0_ns,
                    method=method,
                    endpoint=self.current(),
                    failovers=stats["failovers"],
                    redirects=stats["redirects"],
                )
                tracer = _tracing.get_tracer()
                ctx = _tracing.get_current()
                if tracer is not None and ctx is not None and ctx.sampled:
                    tracer.export_span(
                        name="rpc.failover",
                        trace_id=ctx.trace_id,
                        parent_span_id=ctx.span_id,
                        start_ns=t0_ns,
                        end_ns=time.time_ns(),
                        attributes={
                            "method": method,
                            "endpoint": self.current(),
                            "failovers": stats["failovers"],
                            "redirects": stats["redirects"],
                        },
                    )

    def close(self) -> None:
        for client in self._clients.values():
            client.close()
        self._clients.clear()


# ---------------------------------------------------------------------------
# servers (native C++, lifecycle via ctypes)
# ---------------------------------------------------------------------------


class _NativeServer:
    def __init__(self, handle: int) -> None:
        if handle < 0:
            raise RuntimeError(f"server create failed: {_native.last_error()}")
        self._handle: Optional[int] = handle
        self._address = _native.take_string(
            _native.get_lib().tft_server_address(handle)
        )
        # A native server exists, so its rpc.* spans have somewhere to go:
        # register the process span sink (idempotent; no-op when no tracer
        # is installed).  force_load is safe — the lib is loaded by now.
        _tracing.install_native_span_sink(force_load=True)

    def address(self) -> str:
        """``host:port`` the server is listening on (resolves port 0)."""
        return self._address

    def shutdown(self) -> None:
        """Stop the server and release its socket; idempotent."""
        if self._handle is not None:
            _native.get_lib().tft_server_shutdown(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:
            pass

    def __enter__(self) -> "_NativeServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()


class LighthouseServer(_NativeServer):
    """Cluster quorum authority (C++). Reference: src/lighthouse.rs.

    Binds ``[::]:port`` (port 0 = ephemeral); serves framed-JSON RPC, an
    HTML dashboard, and Prometheus ``GET /metrics`` on the same port.  The
    /metrics exposition is the native lighthouse counters plus this
    process's ``torchft_tpu.utils.metrics`` registry, rendered live via a
    provider callback — the one scrape endpoint a single-host job needs.
    """

    def __init__(
        self,
        bind: str = ":0",
        min_replicas: int = 1,
        join_timeout_ms: int = 100,
        quorum_tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5000,
        status_page_size: "Optional[int]" = None,
        straggler_topk: "Optional[int]" = None,
        timeline_ring: "Optional[int]" = None,
        serving_fanout: "Optional[int]" = None,
        peers: "Optional[Sequence[str] | str]" = None,
        lease_timeout_ms: "Optional[int]" = None,
    ) -> None:
        from torchft_tpu.utils.env import env_int

        host, _, port = bind.rpartition(":")
        # Coordination-plane HA: ``peers`` names the OTHER lighthouse
        # peers of the replicated coordination plane (list or comma
        # string; self-exclusion is the caller's job — ha.fleet and the
        # CLI handle it).  Empty = single-process mode, wire-identical to
        # the pre-HA server.
        if peers is None:
            peers_csv = ""
        elif isinstance(peers, str):
            peers_csv = peers
        else:
            peers_csv = ",".join(peers)
        lib = _native.get_lib()
        handle = lib.tft_lighthouse_create(
            host.encode(),
            int(port or 0),
            min_replicas,
            join_timeout_ms,
            quorum_tick_ms,
            heartbeat_timeout_ms,
            # fleet-scale status plane sizing (docs/observability.md):
            # rows per /status.json + dashboard page, worst-K straggler
            # export, and the cluster step-timeline ring length
            status_page_size
            if status_page_size is not None
            else env_int("TORCHFT_STATUS_PAGE_SIZE", 16, minimum=1),
            straggler_topk
            if straggler_topk is not None
            else env_int("TORCHFT_STRAGGLER_TOPK", 8, minimum=1),
            timeline_ring
            if timeline_ring is not None
            else env_int("TORCHFT_TIMELINE_RING", 256, minimum=1),
            # weight-serving distribution-tree arity (serving_plan RPC)
            serving_fanout
            if serving_fanout is not None
            else env_int("TORCHFT_SERVING_FANOUT", 2, minimum=1),
            peers_csv.encode(),
            lease_timeout_ms
            if lease_timeout_ms is not None
            else env_int("TORCHFT_LIGHTHOUSE_LEASE_MS", 1000, minimum=40),
        )
        super().__init__(handle)
        self._metrics_cb: Any = None
        self._install_metrics_provider()

    def ha_info(self) -> "Dict[str, Any]":
        """Coordination-plane HA introspection: ``{"enabled", "term",
        "is_leader", "leader", "peers", "takeovers_total", "quorum_id"}``.
        Single-process mode reports ``enabled=False``, ``is_leader=True``,
        term 0."""
        if self._handle is None:
            raise RuntimeError("lighthouse server is shut down")
        ptr = _native.get_lib().tft_lighthouse_ha_info(self._handle)
        return json.loads(_native.take_string(ptr))

    def _install_metrics_provider(self) -> None:
        from torchft_tpu.utils import metrics as _metrics

        import ctypes

        def _provider(buf: Any, cap: int) -> int:
            # Contract (native/lighthouse.h MetricsProvider): write up to
            # ``cap`` bytes; return bytes written, or -needed if too small.
            # Never raise: a scrape must not be able to wedge the server.
            try:
                text = _metrics.REGISTRY.render().encode()
            except Exception:  # noqa: BLE001
                return 0
            if len(text) > cap:
                return -len(text)
            ctypes.memmove(buf, text, len(text))
            return len(text)

        # the CFUNCTYPE object must outlive the native registration
        self._metrics_cb = _native.METRICS_PROVIDER_CFUNC(_provider)
        _native.get_lib().tft_lighthouse_set_metrics_provider(
            self._handle, self._metrics_cb
        )

    def shutdown(self) -> None:
        """Stop the server and release its socket; idempotent.

        Clears the /metrics provider BEFORE tearing the server down so no
        native HTTP thread can call into a collected callback (shutdown
        drains in-flight connections before returning)."""
        if self._handle is not None and self._metrics_cb is not None:
            _native.get_lib().tft_lighthouse_set_metrics_provider(
                self._handle, _native.METRICS_PROVIDER_CFUNC()
            )
            self._metrics_cb = None
        super().shutdown()


class StoreServer(_NativeServer):
    """Rendezvous key-value store (C++). Replaces torch TCPStore usage."""

    def __init__(self, bind: str = ":0") -> None:
        host, _, port = bind.rpartition(":")
        lib = _native.get_lib()
        handle = lib.tft_store_create(host.encode(), int(port or 0))
        super().__init__(handle)


class ManagerServer(_NativeServer):
    """Per-replica-group coordination server (C++). Reference: src/manager.rs."""

    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        store_address: str,
        world_size: int,
        bind: str = ":0",
        heartbeat_interval: "float | timedelta" = 0.1,
        connect_timeout: "float | timedelta" = 10.0,
        quorum_retries: int = 0,
    ) -> None:
        host, _, port = bind.rpartition(":")
        lib = _native.get_lib()
        handle = lib.tft_manager_create(
            replica_id.encode(),
            lighthouse_addr.encode(),
            host.encode(),
            int(port or 0),
            store_address.encode(),
            world_size,
            _to_ms(heartbeat_interval),
            _to_ms(connect_timeout),
            quorum_retries,
        )
        super().__init__(handle)

    def report_progress(self, step: int, inflight_op: str = "") -> None:
        """Record this replica group's training progress; the native
        heartbeat loop piggybacks it (``step``, ``last_step_wall_ms``,
        ``inflight_op``) on every lighthouse heartbeat so the lighthouse
        can compute per-replica step lag and straggler scores."""
        if self._handle is None:
            return
        _native.get_lib().tft_manager_report_progress(
            self._handle, int(step), inflight_op.encode()
        )

    def report_summary(self, summary: "Dict[str, Any]") -> None:
        """Record this replica group's per-step digest (``step``,
        ``phase_ms`` name->ms, ``codec_busy_s``, ``wire_busy_s``); the
        next lighthouse heartbeat carries it exactly once, feeding the
        cluster step-timeline (``/timeline.json``)."""
        if self._handle is None:
            return
        rc = _native.get_lib().tft_manager_report_summary(
            self._handle, json.dumps(summary).encode()
        )
        if rc != 0:
            raise RuntimeError(_native.last_error())

    def report_links(self, links: "Dict[str, Any]") -> None:
        """Record this replica's bounded link-state digest
        (``LinkRegistry.maybe_digest``: ``{"host", "rows"}``); the next
        lighthouse heartbeat carries it exactly once (consumed-on-send,
        restored on RPC failure — the per-step-digest idiom), feeding the
        fleet host-pair matrix (``/links.json``)."""
        if self._handle is None:
            return
        # chaos site: a dropped/raised link report degrades to stale
        # matrix rows; it must never wedge the heartbeat loop
        _faults.check("lighthouse.links")
        rc = _native.get_lib().tft_manager_report_links(
            self._handle, json.dumps(links).encode()
        )
        if rc != 0:
            raise RuntimeError(_native.last_error())

    def report_fragments(self, fragments: "Dict[str, Any]") -> None:
        """Record this replica's bounded fragment-provenance digest
        (``ProvenanceRegistry.maybe_digest``: ``{"host", "frags"}``); the
        next lighthouse heartbeat carries it exactly once
        (consumed-on-send, restored on RPC failure — the links-digest
        idiom), feeding the fleet per-(host, frag_id) version matrix
        (``/fragments.json``)."""
        if self._handle is None:
            return
        # chaos site: a dropped/raised fragment report degrades to stale
        # matrix rows; it must never wedge the heartbeat loop
        _faults.check("lighthouse.fragments")
        rc = _native.get_lib().tft_manager_report_fragments(
            self._handle, json.dumps(fragments).encode()
        )
        if rc != 0:
            raise RuntimeError(_native.last_error())


# ---------------------------------------------------------------------------
# clients
# ---------------------------------------------------------------------------


class LighthouseClient:
    """Client for LighthouseServer. Reference: src/lib.rs:483-591.

    ``addr`` may be a single ``host:port`` or the HA comma list
    (``TORCHFT_LIGHTHOUSE=h1:p,h2:p,h3:p``): with multiple endpoints
    every call rides the failover walk — dead peers are skipped within a
    bounded connect slice, ``NOT_LEADER`` replies are followed to the
    current lease holder, and mid-election passes are retried on the
    unified retry layer inside the caller's timeout.
    """

    def __init__(self, addr: str, connect_timeout: "float | timedelta" = 10.0) -> None:
        ct = (
            connect_timeout.total_seconds()
            if isinstance(connect_timeout, timedelta)
            else connect_timeout
        )
        self._client = _FailoverRpcClient(addr, ct, fault_site="lighthouse.rpc")

    def quorum(
        self,
        replica_id: str,
        timeout: "float | timedelta",
        address: str = "",
        store_address: str = "",
        step: int = 0,
        world_size: int = 1,
        shrink_only: bool = False,
        commit_failures: int = 0,
        data: "Dict[str, Any] | None" = None,
    ) -> Quorum:
        """Join the next quorum as ``replica_id`` and block until it forms.

        Doubles as an implicit heartbeat (reference src/lighthouse.rs:
        498-544); ``data`` is an opaque JSON dict carried to all members.

        Id convention: the segment after the last ``:`` is the INCARNATION
        suffix (the Manager appends ``:uuid4``). A joiner supersedes any
        member sharing its non-empty prefix — the stale incarnation is
        evicted immediately so a fast-restarted replica re-forms quorum
        without waiting out heartbeat expiry. Ids without ``:`` (or with
        an empty prefix) never supersede anything.
        """
        member = QuorumMember(
            replica_id=replica_id,
            address=address,
            store_address=store_address,
            step=step,
            world_size=world_size,
            shrink_only=shrink_only,
            commit_failures=commit_failures,
            data=json.dumps(data) if data else "",
        )
        result = self._client.call("quorum", {"member": member.to_dict()}, timeout)
        return Quorum.from_dict(result["quorum"])

    def heartbeat(
        self,
        replica_id: str,
        timeout: "float | timedelta" = 5.0,
        step: "Optional[int]" = None,
        last_step_wall_ms: "Optional[int]" = None,
        inflight_op: "Optional[str]" = None,
        summary: "Optional[Dict[str, Any]]" = None,
        links: "Optional[Dict[str, Any]]" = None,
        fragments: "Optional[Dict[str, Any]]" = None,
    ) -> Dict[str, Any]:
        """Mark ``replica_id`` live; lighthouse expiry is heartbeat_timeout_ms.

        Optional progress piggyback (straggler telemetry): ``step`` is the
        replica's committed step, ``last_step_wall_ms`` the sender-clock
        wall time (ms) the step last advanced, ``inflight_op`` what the
        replica is currently doing.  The lighthouse folds these into
        per-replica step lag and straggler scores (``/status.json``
        ``stragglers``, ``/metrics`` ``torchft_replica_step_lag`` /
        ``torchft_straggler_score``).  ``summary`` is the per-step digest
        (``step``, ``phase_ms`` name->ms, ``codec_busy_s``,
        ``wire_busy_s``) aggregated into the cluster step-timeline
        (``/timeline.json``) — send a given step's digest ONCE.  ``links``
        is the replica's bounded link-state digest
        (``LinkRegistry.maybe_digest``: ``{"host", "rows"}``) folded into
        the fleet host-pair matrix (``/links.json``) — likewise send each
        digest ONCE.  Returns the server reply (e.g.
        ``{"superseded": true}`` for an evicted incarnation)."""
        # chaos site: the straggler-telemetry path must itself be
        # chaos-testable (docs/robustness.md site table)
        _faults.check("lighthouse.heartbeat", replica=replica_id)
        params: "Dict[str, Any]" = {"replica_id": replica_id}
        if step is not None:
            params["step"] = int(step)
        if last_step_wall_ms is not None:
            params["last_step_wall_ms"] = int(last_step_wall_ms)
        if inflight_op is not None:
            params["inflight_op"] = inflight_op
        if summary is not None:
            params["summary"] = summary
        if links is not None:
            # chaos site: a dropped/raised link report must degrade to
            # stale matrix rows, never wedge the heartbeat itself — the
            # caller catches and re-queues (docs/robustness.md)
            _faults.check("lighthouse.links", replica=replica_id)
            params["links"] = links
        if fragments is not None:
            # chaos site: same degrade contract as ``links`` — a lost
            # fragment digest leaves stale provenance rows, the caller
            # restores the digest and re-sends next beat
            _faults.check("lighthouse.fragments", replica=replica_id)
            params["fragments"] = fragments
        return self._client.call("heartbeat", params, timeout)

    def status(
        self,
        timeout: "float | timedelta" = 5.0,
        page: "Optional[int]" = None,
        per_page: "Optional[int]" = None,
        replica: "Optional[str]" = None,
    ) -> Dict[str, Any]:
        """Quorum/participant/heartbeat snapshot (the dashboard's data).

        The same document as ``GET /status.json``: row arrays
        (``heartbeats``, ``stragglers``, ``prev_quorum.participants``)
        are paginated — ``page``/``per_page`` select a slice (defaults:
        page 0 of the server's ``TORCHFT_STATUS_PAGE_SIZE``), ``replica``
        shards every array down to one replica id.  Fleet-wide truth is
        always present regardless of page: ``*_total`` counts, ``pages``,
        ``max_step``, and ``summary`` (counts + the worst-K stragglers by
        score).  See docs/observability.md for the schema."""
        params: "Dict[str, Any]" = {}
        if page is not None:
            params["page"] = int(page)
        if per_page is not None:
            params["per_page"] = int(per_page)
        if replica is not None:
            params["replica"] = replica
        return self._client.call("status", params, timeout)

    def serving_heartbeat(
        self,
        replica_id: str,
        address: str,
        role: str = "server",
        version: int = 0,
        capacity: int = 0,
        version_ms: int = 0,
        timeout: "float | timedelta" = 5.0,
        fragments: "Optional[Dict[str, Any]]" = None,
    ) -> Dict[str, Any]:
        """Register/refresh a weight-serving member (docs/architecture.md
        "Weight-serving tier").  ``role`` is ``publisher`` (training-side
        WeightPublisher, the tree's source) or ``server`` (relay/leaf
        serving replica); ``address`` is the member's HTTP
        checkpoint-transport base address; ``version`` the newest weight
        version it holds; ``capacity`` overrides the tree fanout for this
        node (0 = server default); ``version_ms`` is the PUBLISH
        wall-clock stamp (ms) of ``version`` — the publisher's clock,
        carried unmodified through the tree so the lighthouse can compute
        per-node serving staleness on a single clock (0 = unknown).
        ``fragments`` is the member's bounded fragment-provenance digest
        (``ProvenanceRegistry.maybe_digest``: ``{"host", "frags"}``)
        folded into the fleet fragment-version matrix
        (``/fragments.json``) — send each digest ONCE (consumed-on-send;
        restore on failure).  Expiry follows the lighthouse heartbeat
        timeout.  Returns ``{"plan_epoch", "latest_version"}`` — a
        ``plan_epoch`` differing from the adopted one means the tree
        re-formed and :meth:`serving_plan` should be re-fetched."""
        params: "Dict[str, Any]" = {
            "replica_id": replica_id,
            "address": address,
            "role": role,
            "version": int(version),
            "capacity": int(capacity),
            "version_ms": int(version_ms),
        }
        if fragments is not None:
            # chaos site: shared with the manager-heartbeat piggyback —
            # the caller restores the digest and re-sends next beat
            _faults.check("lighthouse.fragments", replica=replica_id)
            params["fragments"] = fragments
        result = self._client.call("serving_heartbeat", params, timeout)
        return {
            "plan_epoch": result["plan_epoch"],
            "latest_version": result["latest_version"],
        }

    def serving_plan(self, timeout: "float | timedelta" = 5.0) -> Dict[str, Any]:
        """The synthesized weight-distribution fan-out plan (same document
        as ``GET /serving.json``): monotone ``epoch``, ``root_source``
        (max-version publisher address), ``publishers``, and ``nodes`` —
        one entry per serving replica with ``parent`` ("" = root, pulls
        from ``root_source``), ``depth`` and ``children``.  Synthesis is
        deterministic over the replica_id-ordered membership, so every
        reader of epoch E sees the identical tree."""
        result = self._client.call("serving_plan", {}, timeout)
        return {
            "epoch": result["epoch"],
            "generated_ms": result["generated_ms"],
            "fanout": result["fanout"],
            "latest_version": result["latest_version"],
            "root_source": result["root_source"],
            "publishers": result["publishers"],
            "nodes": result["nodes"],
            "depth": result["depth"],
        }

    def lease(
        self,
        term: int,
        candidate: str,
        timeout: "float | timedelta" = 5.0,
    ) -> Dict[str, Any]:
        """One leadership-lease request against a single lighthouse peer
        (coordination-plane HA; the native electors drive this RPC in
        production — this client exists for tests, chaos drills and
        external election tooling).  ``term`` is the candidate's proposed
        monotone term, ``candidate`` its advertised RPC address.  Reply:
        ``{"granted", "term", "holder"}`` — ``granted`` is False when the
        peer already promised this term to another candidate or its
        current promise has not lapsed (lease shielding).  Note this RPC
        is served by every peer, leader or follower."""
        # chaos site: the lease/election path must itself be
        # chaos-testable (docs/robustness.md site table)
        _faults.check("lighthouse.lease", step=term)
        params: "Dict[str, Any]" = {
            "term": int(term),
            "candidate": candidate,
        }
        result = self._client.call("lease", params, timeout)
        return {
            "granted": result["granted"],
            "term": result["term"],
            "holder": result["holder"],
        }

    def timeline(self, timeout: "float | timedelta" = 5.0) -> Dict[str, Any]:
        """The rolling cluster step-timeline (same document as
        ``GET /timeline.json``): per-step buckets aggregated from the
        heartbeat-piggybacked replica digests (replicas seen, phase
        mean/max, codec/wire busy, first/last report stamps) plus the
        worst-K straggler snapshot — one scrape answers "what was the
        whole fleet doing at step N"."""
        return self._client.call("timeline", {}, timeout)

    def links(
        self,
        timeout: "float | timedelta" = 5.0,
        page: "Optional[int]" = None,
        per_page: "Optional[int]" = None,
    ) -> Dict[str, Any]:
        """The fleet link-state matrix (same document as
        ``GET /links.json``): host-pair rows aggregated from the
        heartbeat-piggybacked link digests — per (reporting host, peer
        host, plane): goodput, first-byte p50/p99, sample count and
        report age.  ``rows`` is paginated like ``/status.json``
        (``page``/``per_page``); fleet truth (``rows_total``, ``pages``,
        ``version``, ``hosts``, ``worst``) is present on every page.
        ``version`` is monotone — equal versions mean an identical
        matrix.  See docs/observability.md "Link-state plane"."""
        # chaos site: shared with the report path — a faulted links plane
        # degrades reads the same way it degrades reports
        _faults.check("lighthouse.links")
        params: "Dict[str, Any]" = {}
        if page is not None:
            params["page"] = int(page)
        if per_page is not None:
            params["per_page"] = int(per_page)
        return self._client.call("links", params, timeout)

    def fragments(
        self,
        timeout: "float | timedelta" = 5.0,
        page: "Optional[int]" = None,
        per_page: "Optional[int]" = None,
    ) -> Dict[str, Any]:
        """The fleet fragment-version matrix (same document as
        ``GET /fragments.json``): per-(holder host, fragment id) rows
        aggregated from the heartbeat-piggybacked provenance digests —
        version, digest8, publish stamp, staleness vs. the freshest
        stamp any holder reports for that fragment (publisher's clock,
        so the comparison is skew-free).  ``rows`` is paginated like
        ``/links.json`` (``page``/``per_page``); fleet truth
        (``rows_total``, ``pages``, ``version``, ``hosts``, ``frags``,
        ``stalest``) is present on every page.  ``version`` is monotone
        — equal versions mean an identical matrix.  See
        docs/observability.md "Fragment provenance plane"."""
        # chaos site: shared with the report path — a faulted fragments
        # plane degrades reads the same way it degrades reports
        _faults.check("lighthouse.fragments")
        params: "Dict[str, Any]" = {}
        if page is not None:
            params["page"] = int(page)
        if per_page is not None:
            params["per_page"] = int(per_page)
        return self._client.call("fragments", params, timeout)

    def close(self) -> None:
        """Close the underlying connection; the client is unusable after."""
        self._client.close()


class ManagerClient:
    """Client for ManagerServer. Reference: src/lib.rs:153-281."""

    def __init__(self, addr: str, connect_timeout: "float | timedelta" = 10.0) -> None:
        ct = (
            connect_timeout.total_seconds()
            if isinstance(connect_timeout, timedelta)
            else connect_timeout
        )
        self._addr = addr
        self._client = _RpcClient(addr, ct)

    def _quorum(
        self,
        group_rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        timeout: "float | timedelta",
        init_sync: bool = True,
        commit_failures: int = 0,
        layout_epoch: int = 0,
        layout_data: str = "",
    ) -> QuorumResult:
        """Per-rank quorum entry.  ``layout_epoch`` / ``layout_data`` are
        the online-parallelism-switching fields (parallel/layout.py): the
        group's current/staged layout epoch and its opaque shard manifest,
        forwarded into the lighthouse QuorumMember so every participant's
        result carries the fleet's epoch spread + manifests."""
        result = self._client.call(
            "quorum",
            {
                "group_rank": group_rank,
                "step": step,
                "checkpoint_metadata": checkpoint_metadata,
                "shrink_only": shrink_only,
                "init_sync": init_sync,
                "commit_failures": commit_failures,
                "layout_epoch": layout_epoch,
                "layout_data": layout_data,
            },
            timeout,
        )
        return QuorumResult.from_dict(result)

    def _checkpoint_metadata(self, rank: int, timeout: "float | timedelta") -> str:
        result = self._client.call("checkpoint_metadata", {"rank": rank}, timeout)
        return result["checkpoint_metadata"]

    def should_commit(
        self,
        group_rank: int,
        step: int,
        should_commit: bool,
        timeout: "float | timedelta",
    ) -> bool:
        """Vote on committing ``step``; blocks until all group ranks vote and
        returns the AND across them (reference src/manager.rs:423-479).

        Non-idempotent on the wire: a blind re-send after a broken
        connection could deliver this rank's vote twice (e.g. across a
        server restart) and release the barrier with a stale tally, so a
        connection failure surfaces to the Manager — which votes False and
        lets the protocol's normal abstain path handle it."""
        result = self._client.call(
            "should_commit",
            {"group_rank": group_rank, "step": step, "should_commit": should_commit},
            timeout,
            idempotent=False,
        )
        return result["should_commit"]

    def kill(self, msg: str = "", timeout: "float | timedelta" = 5.0) -> None:
        """Ask the remote replica's manager to exit its process."""
        try:
            self._client.call("kill", {"msg": msg}, timeout)
        except (TimeoutError, ConnectionError, RpcError):
            pass  # the remote process exits mid-RPC by design

    def close(self) -> None:
        """Close the underlying connection; the client is unusable after."""
        self._client.close()


class StoreClient:
    """Client for StoreServer: set/get(wait)/delete_prefix."""

    def __init__(self, addr: str, connect_timeout: "float | timedelta" = 10.0) -> None:
        ct = (
            connect_timeout.total_seconds()
            if isinstance(connect_timeout, timedelta)
            else connect_timeout
        )
        self._client = _RpcClient(addr, ct)

    def set(self, key: str, value: str, timeout: "float | timedelta" = 10.0) -> None:
        """Publish ``key`` (wakes any blocked ``get(wait=True)``)."""
        self._client.call("set", {"key": key, "value": value}, timeout)

    def get(
        self, key: str, timeout: "float | timedelta" = 10.0, wait: bool = True
    ) -> str:
        """Read ``key``; with ``wait`` blocks until it is set or timeout."""
        if wait:
            # the blocking rendezvous wait PG configure / manager discovery
            # park on — the chaos layer's store-barrier injection site
            _faults.check("store.barrier")
        result = self._client.call("get", {"key": key, "wait": wait}, timeout)
        return result["value"]

    def delete_prefix(self, prefix: str, timeout: "float | timedelta" = 10.0) -> int:
        """Remove all keys under ``prefix``; returns the count removed."""
        result = self._client.call("delete_prefix", {"prefix": prefix}, timeout)
        return result["removed"]

    def num_keys(self, timeout: "float | timedelta" = 10.0) -> int:
        """Total keys currently stored (tests/diagnostics)."""
        return self._client.call("num_keys", {}, timeout)["count"]

    def close(self) -> None:
        """Close the underlying connection; the client is unusable after."""
        self._client.close()


def compute_quorum_results(
    replica_id: str, group_rank: int, quorum: Quorum, init_sync: bool = True
) -> QuorumResult:
    """Pure quorum-result math (native). Reference: src/manager.rs:489-624."""
    lib = _native.get_lib()
    quorum_json = json.dumps(
        {
            "quorum_id": quorum.quorum_id,
            "participants": [p.to_dict() for p in quorum.participants],
            "created_ms": quorum.created_ms,
        }
    )
    ptr = lib.tft_compute_quorum_results(
        replica_id.encode(), group_rank, quorum_json.encode(), 1 if init_sync else 0
    )
    return QuorumResult.from_dict(json.loads(_native.take_string(ptr)))
