"""OTLP/HTTP log exporter behind the structured-event seam.

Analog of the reference's OTEL pipeline (reference: torchft/otel.py:42-86
— a Tee of ConsoleLogExporter + OTLPLogExporter behind a
BatchLogRecordProcessor, resource attributes loaded from the file named
by ``TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON``, all gated on
``TORCHFT_USE_OTEL``).  This environment ships no opentelemetry SDK, so
the exporter speaks the OTLP/HTTP **JSON** logs protocol directly
(`POST <endpoint>/v1/logs` with a `resourceLogs` document, per the OTLP
spec's stable JSON encoding) — ~100 lines of stdlib instead of an SDK
dependency, wired into the same :class:`EventExporter` registry every
other sink uses.

Pipeline shape mirrors the reference:

- **batching**: records buffer in memory and flush on a background
  thread every ``flush_interval_s`` or ``max_batch`` records, whichever
  first (the reference's BatchLogRecordProcessor);
- **resource attributes**: constructor arg, else the JSON file named by
  ``TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON`` (same env knob; the file
  maps exporter name -> attribute dict, reference otel.py:50-58);
- **console tee**: the event pipeline already tees every record to
  stdlib logging (utils/logging.py log_event), so only the OTLP leg
  lives here;
- **gating**: :func:`maybe_install_from_env` installs an exporter when
  ``TORCHFT_USE_OTEL`` is truthy, endpoint from
  ``OTEL_EXPORTER_OTLP_LOGS_ENDPOINT`` / ``OTEL_EXPORTER_OTLP_ENDPOINT``
  (the standard OTEL env vars).

Failure policy matches every sink in this framework: the collector being
down must never take down training — failed posts are dropped with a
warning and a ``dropped`` counter for tests/ops to inspect.
"""

from __future__ import annotations

import atexit
import json
import logging
import threading
import urllib.request
from typing import Any, Dict, List, Optional

from torchft_tpu.utils.env import env_bool, env_str
from torchft_tpu.utils.logging import EventExporter, register_exporter

logger = logging.getLogger(__name__)

TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON = "TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON"

_SEVERITY = {
    # event kind -> (OTLP severityNumber, severityText)
    # (mirror _LOGGERS in utils/logging.py when extending: an unmapped
    # kind silently exports as INFO, which buries errors)
    "quorum": (9, "INFO"),
    "commit": (9, "INFO"),
    "error": (17, "ERROR"),
    "abort": (17, "ERROR"),
    "heal": (9, "INFO"),
    "reconfigure": (9, "INFO"),
    # injected chaos faults are deliberate, but a collector should still
    # be able to alert on them leaking into a production deployment
    "fault": (13, "WARN"),
    # layout switches are planned membership responses, not errors; the
    # rolled_back outcome is surfaced via the event body + metrics
    "layout": (9, "INFO"),
}


def _any_value(v: Any) -> "Dict[str, Any]":
    """Encode a Python value as an OTLP AnyValue (JSON encoding)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # spec: int64 as JSON string
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, str):
        return {"stringValue": v}
    return {"stringValue": json.dumps(v, default=str)}


def _kv_list(attrs: "Dict[str, Any]") -> "List[Dict[str, Any]]":
    return [{"key": k, "value": _any_value(v)} for k, v in attrs.items()]


def load_resource_attributes(name: str = "torchft_tpu") -> "Dict[str, Any]":
    """Resource attrs for ``name`` from the file named by
    ``TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON`` (reference otel.py:50-58:
    the file maps logger name -> attribute dict).  Missing file/key -> {}.
    """
    path = env_str(TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON)
    if not path:
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        attrs = data.get(name, {}) if isinstance(data, dict) else {}
        return attrs if isinstance(attrs, dict) else {}
    except (OSError, ValueError) as e:
        logger.warning("could not load OTEL resource attributes: %s", e)
        return {}


def post_otlp(endpoint: str, body: bytes, timeout_s: float) -> None:
    """POST one OTLP JSON document; raises on non-2xx or network failure
    (callers own the drop-with-warning failure policy).  The one HTTP leg
    shared by the logs, traces, and metrics exporters."""
    req = urllib.request.Request(
        endpoint,
        data=body,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        if not (200 <= resp.status < 300):
            raise OSError(f"collector returned HTTP {resp.status}")


class BatchedOTLPHTTPExporter:
    """Shared OTLP/HTTP batch pipeline (logs + traces legs subclass this;
    the metrics leg pushes snapshots instead of batching records, so it
    only shares :func:`post_otlp`).

    Records buffer in memory and flush on a daemon thread every
    ``flush_interval_s`` or ``max_batch`` records, whichever first; an
    atexit flush ships a dying replica's last batch; exports after
    ``close()`` count into ``dropped`` rather than vanishing; failed posts
    drop with a warning — a dead collector never takes down training.

    Subclasses set ``path_suffix`` and implement ``_encode(batch)``.
    """

    path_suffix = "/v1/logs"

    def __init__(
        self,
        endpoint: str,
        resource_attributes: "Optional[Dict[str, Any]]" = None,
        service_name: str = "torchft_tpu",
        max_batch: int = 64,
        flush_interval_s: float = 2.0,
        timeout_s: float = 5.0,
    ) -> None:
        self._endpoint = endpoint.rstrip("/")
        if not self._endpoint.endswith(self.path_suffix):
            self._endpoint += self.path_suffix
        if resource_attributes is None:
            resource_attributes = load_resource_attributes(service_name)
        attrs = {"service.name": service_name, **resource_attributes}
        self._resource = {"attributes": _kv_list(attrs)}
        self._max_batch = max_batch
        self._flush_interval_s = flush_interval_s
        self._timeout_s = timeout_s
        self._buf: "List[Dict[str, Any]]" = []
        self._cv = threading.Condition()
        self._closed = False
        self._posting = False
        self.exported = 0  # records acknowledged by the collector
        self.dropped = 0  # records lost (network failure or post-close)
        self._thread = threading.Thread(
            target=self._run, name="otlp_exporter", daemon=True
        )
        self._thread.start()
        # The last records of a dying replica (the abort/error that explains
        # the death) are exactly the ones an FT postmortem needs: flush the
        # buffer at interpreter exit instead of losing the final batch.
        atexit.register(self._atexit_flush)

    def export(self, record: "Dict[str, Any]") -> None:
        with self._cv:
            if self._closed:
                # a post-close export is a lost record, not a silent no-op:
                # ops dashboards alert on `dropped`
                self.dropped += 1
                return
            self._buf.append(record)
            if len(self._buf) >= self._max_batch:
                self._cv.notify()

    def _atexit_flush(self) -> None:
        if not self._closed:
            self.flush(timeout=2.0)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        try:
            atexit.unregister(self._atexit_flush)
        except Exception:  # noqa: BLE001 - interpreter-state dependent
            pass
        self._thread.join(timeout=self._timeout_s + 1.0)

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until the current buffer has been posted (tests, and the
        pre-exit flush an FT system wants for its last records)."""
        import time as _t

        with self._cv:
            self._cv.notify()
        t0 = _t.monotonic()
        while True:
            with self._cv:
                if not self._buf and not self._posting:
                    return True
            if _t.monotonic() - t0 > timeout:
                return False
            _t.sleep(0.01)

    # -- internals ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                if not self._buf and not self._closed:
                    self._cv.wait(timeout=self._flush_interval_s)
                batch, self._buf = self._buf, []
                closed = self._closed
                self._posting = bool(batch)
            if batch:
                try:
                    self._post(batch)
                finally:
                    with self._cv:
                        self._posting = False
            if closed:
                return

    def _encode(self, batch: "List[Dict[str, Any]]") -> bytes:
        raise NotImplementedError

    def _post(self, batch: "List[Dict[str, Any]]") -> None:
        try:
            post_otlp(self._endpoint, self._encode(batch), self._timeout_s)
            self.exported += len(batch)
        except Exception as e:  # noqa: BLE001 - a sink never kills training
            self.dropped += len(batch)
            logger.warning(
                "OTLP export of %d record(s) to %s failed: %s",
                len(batch),
                self._endpoint,
                e,
            )


class OTLPHTTPExporter(BatchedOTLPHTTPExporter, EventExporter):
    """Batched OTLP/HTTP (JSON encoding) log exporter.

    Every structured event becomes one OTLP logRecord: ``ts`` ->
    timeUnixNano, ``kind`` -> severity + an attribute, ``message`` ->
    body, remaining extras -> attributes.
    """

    path_suffix = "/v1/logs"

    def _encode(self, batch: "List[Dict[str, Any]]") -> bytes:
        records = []
        for rec in batch:
            rec = dict(rec)
            ts = rec.pop("ts", None)
            kind = rec.pop("kind", "quorum")
            message = rec.pop("message", "")
            num, text = _SEVERITY.get(kind, (9, "INFO"))
            records.append(
                {
                    "timeUnixNano": str(int((ts or 0.0) * 1e9)),
                    "severityNumber": num,
                    "severityText": text,
                    "body": {"stringValue": str(message)},
                    "attributes": _kv_list({"event.kind": kind, **rec}),
                }
            )
        doc = {
            "resourceLogs": [
                {
                    "resource": self._resource,
                    "scopeLogs": [
                        {
                            "scope": {"name": "torchft_tpu"},
                            "logRecords": records,
                        }
                    ],
                }
            ]
        }
        return json.dumps(doc, default=str).encode()


def maybe_install_from_env() -> "Optional[OTLPHTTPExporter]":
    """Install an OTLP exporter into the event pipeline when
    ``TORCHFT_USE_OTEL`` is truthy (reference otel.py:43-44 gate).
    Endpoint: ``OTEL_EXPORTER_OTLP_LOGS_ENDPOINT``, else
    ``OTEL_EXPORTER_OTLP_ENDPOINT``, else the OTLP default
    ``http://localhost:4318``."""
    # explicit truthy whitelist: "off"/"no"/typos must NOT install an
    # exporter that spams connection-refused warnings all run
    if not env_bool("TORCHFT_USE_OTEL"):
        return None
    endpoint = (
        env_str("OTEL_EXPORTER_OTLP_LOGS_ENDPOINT")
        or env_str("OTEL_EXPORTER_OTLP_ENDPOINT")
        or "http://localhost:4318"
    )
    exporter = OTLPHTTPExporter(endpoint)
    register_exporter(exporter)
    return exporter
