"""Runtime lock-order cycle detector and hold-time profiler.

The static ``lock-discipline`` pass of ``tft-lint`` catches *blocking
calls under a lock*; what it cannot see is **acquisition order** — the
classic deadlock where thread 1 takes A then B while thread 2 takes B
then A.  With 80+ lock sites across the telemetry, chaos, and collective
layers, ordering discipline has to be checked by the running system, the
same stance TSan's deadlock detector takes for the C++ core
(``make -C native SANITIZE=thread``).  This module is the Python half:

- :func:`lock` / :func:`rlock` are drop-in factories the instrumented
  modules (flightrecorder, metrics, faults, rwlock, process_group) use in
  place of ``threading.Lock()`` / ``threading.RLock()``.  With
  ``TORCHFT_LOCKCHECK`` unset they return the plain ``threading``
  primitive — zero overhead, zero behavior change;
- with ``TORCHFT_LOCKCHECK=1`` they return a :class:`CheckedLock`
  wrapper that maintains a per-thread stack of held locks and a global
  **acquisition-order graph** keyed by lock *name* (one name per creation
  site, so instances aggregate like a metric family).  Each time a thread
  holding ``A`` acquires ``B``, the edge ``A -> B`` is recorded; a new
  edge that closes a cycle is a potential deadlock, reported once per
  distinct cycle via ``torchft_lock_cycles_total{edge}``, an ERROR log
  line, and :func:`cycles` (tests assert on it; production alerts on
  the counter);
- releases longer than ``TORCHFT_LOCKCHECK_HOLD_MS`` (default 250 ms)
  after acquisition count as **hold-time outliers**
  (``torchft_lock_hold_outliers_total{name}``) — a long-held lock in a
  per-step FT protocol is where stragglers are born.

Cross-thread release (legal on ``threading.Lock``, and used by
``utils/rwlock.py`` where the *last* reader releases the writer gate the
*first* reader took) is handled: a release that doesn't match the
releasing thread's stack is simply untracked.

The detector's own bookkeeping uses a raw ``threading.Lock`` plus a
thread-local reentrancy guard, so reporting through the (itself
instrumented) metrics registry cannot recurse or self-deadlock.

Enable for a test run::

    TORCHFT_LOCKCHECK=1 pytest -m 'not slow'

(tests/conftest.py sets it by default, so the tier-1 suite always runs
instrumented; export ``TORCHFT_LOCKCHECK=0`` to opt out.)
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from torchft_tpu.utils.env import env_bool, env_float

logger = logging.getLogger(__name__)

__all__ = [
    "lock",
    "rlock",
    "gate",
    "CheckedLock",
    "enabled",
    "set_enabled",
    "cycles",
    "edges",
    "reset",
    "hold_outliers",
]

# Read once at import; set_enabled() overrides (tests, embedding apps).
_enabled = env_bool("TORCHFT_LOCKCHECK")


def _hold_threshold_s() -> float:
    return env_float("TORCHFT_LOCKCHECK_HOLD_MS", 250.0, minimum=0.0) / 1000.0


class _Graph:
    """Global acquisition-order graph + reports (process-wide singleton)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()  # raw on purpose: never instrumented
        self._edges: "Dict[str, Set[str]]" = {}
        self._cycles: "List[Tuple[str, ...]]" = []
        self._seen_cycles: "Set[Tuple[str, ...]]" = set()
        self._outliers: "Dict[str, int]" = {}

    def add_edge(self, a: str, b: str) -> "Optional[Tuple[str, ...]]":
        """Record ``a`` held while acquiring ``b``; returns a cycle path
        (``b -> ... -> a -> b``) the first time one is closed, else None.

        Bounded acquire on the bookkeeping mutex: a signal handler that
        (against the lint rule) touches a checked lock must degrade to an
        untracked acquisition rather than self-deadlock on graph state
        the interrupted thread holds."""
        if not self._mu.acquire(timeout=0.2):
            return None
        try:
            if a == b:
                # same-name nesting (two instances from one site, e.g. two
                # PGs' _lock) is order-ambiguous by construction — report
                # it as the tightest cycle rather than silently
                # self-looping the graph.
                path = (a, b)
                self._edges.setdefault(a, set()).add(b)
                if path in self._seen_cycles:
                    return None
                self._seen_cycles.add(path)
                self._cycles.append(path)
                return path
            known = self._edges.setdefault(a, set())
            if b in known:
                return None
            known.add(b)
            # DFS from b looking for a path back to a (edge set is small:
            # names are per-site, not per-instance)
            path = self._find_path(b, a)
            if path is None:
                return None
            cycle = tuple(path) + (b,)
            canon = _canonical(cycle)
            if canon in self._seen_cycles:
                return None
            self._seen_cycles.add(canon)
            self._cycles.append(cycle)
            return cycle
        finally:
            self._mu.release()

    def _find_path(self, src: str, dst: str) -> "Optional[List[str]]":
        stack: "List[Tuple[str, List[str]]]" = [(src, [src])]
        visited: "Set[str]" = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in visited:
                continue
            visited.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def add_outlier(self, name: str) -> None:
        if not self._mu.acquire(timeout=0.2):
            return  # same degradation policy as add_edge
        try:
            self._outliers[name] = self._outliers.get(name, 0) + 1
        finally:
            self._mu.release()

    def snapshot_cycles(self) -> "List[Tuple[str, ...]]":
        with self._mu:
            return list(self._cycles)

    def snapshot_edges(self) -> "Dict[str, Set[str]]":
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def snapshot_outliers(self) -> "Dict[str, int]":
        with self._mu:
            return dict(self._outliers)

    def clear(self) -> None:
        with self._mu:
            self._edges.clear()
            self._cycles.clear()
            self._seen_cycles.clear()
            self._outliers.clear()


def _canonical(cycle: "Tuple[str, ...]") -> "Tuple[str, ...]":
    """Rotation-invariant key for a cycle path (first node repeated last)."""
    body = cycle[:-1]
    i = body.index(min(body))
    return body[i:] + body[:i]


_GRAPH = _Graph()


class _ThreadState(threading.local):
    def __init__(self) -> None:
        self.held: "List[CheckedLock]" = []
        self.reporting = False  # reentrancy guard for the metrics leg


_TLS = _ThreadState()


def _report_cycle(cycle: "Tuple[str, ...]") -> None:
    edge = " -> ".join(cycle)
    logger.error(
        "lock-order cycle detected (potential deadlock): %s "
        "(set a consistent acquisition order or split the critical section)",
        edge,
    )
    if _TLS.reporting:
        return
    _TLS.reporting = True
    try:
        from torchft_tpu.utils import metrics as _metrics

        _metrics.LOCK_CYCLES.labels(edge=edge).inc()
    except Exception:  # noqa: BLE001 - detector never takes down training
        logger.exception("lock cycle metric failed")
    finally:
        _TLS.reporting = False


def _report_outlier(name: str, held_s: float) -> None:
    _GRAPH.add_outlier(name)
    logger.warning("lock %s held %.3fs (> hold-time threshold)", name, held_s)
    if _TLS.reporting:
        return
    _TLS.reporting = True
    try:
        from torchft_tpu.utils import metrics as _metrics

        _metrics.LOCK_HOLD_OUTLIERS.labels(name=name).inc()
    except Exception:  # noqa: BLE001
        logger.exception("lock hold-outlier metric failed")
    finally:
        _TLS.reporting = False


class CheckedLock:
    """Order- and hold-time-instrumented wrapper over a threading lock.

    API-compatible with ``threading.Lock``/``RLock`` for every use in
    this package, including as the underlying lock of a
    ``threading.Condition`` (whose ``wait()`` releases and reacquires
    through ``acquire``/``release``, keeping the held-stack accurate).
    """

    __slots__ = ("_name", "_inner", "_reentrant", "_gate", "_acquired_ns", "_depth_tls")

    def __init__(self, name: str, reentrant: bool = False, gate: bool = False) -> None:
        self._name = name
        self._reentrant = reentrant
        # A *gate* is held on behalf of a community and may be released by
        # a different thread than acquired it (e.g. rwlock's writer gate,
        # taken by the first reader and dropped by the last): thread-local
        # ordering analysis produces nonsense for it, so gates keep only
        # hold-time instrumentation and stay out of the order graph.
        self._gate = gate
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._acquired_ns = 0  # stamped by the acquiring thread
        # per-thread reentrancy depth (RLock): only the outermost
        # acquire/release mutates the held stack and the order graph
        self._depth_tls = threading.local()

    @property
    def name(self) -> str:
        return self._name

    def _depth(self) -> int:
        return getattr(self._depth_tls, "d", 0)

    def _set_depth(self, d: int) -> None:
        self._depth_tls.d = d

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        tls = _TLS
        # The ordering fact is the *attempt* while holding: a deadlocked
        # acquire never succeeds, and the attempt is exactly the evidence
        # the order graph needs.
        track = (
            not tls.reporting
            and not self._gate
            and not (self._reentrant and self._depth() > 0)
        )
        if track and tls.held:
            # a non-blocking probe of a lock this thread already holds
            # (am-I-the-owner idiom) is not an ordering fact
            if not (not blocking and self in tls.held):
                cycle = _GRAPH.add_edge(tls.held[-1]._name, self._name)
                if cycle is not None:
                    _report_cycle(cycle)
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            return False
        if self._reentrant:
            d = self._depth()
            self._set_depth(d + 1)
            if d > 0:  # inner re-acquire: no new ordering fact
                return True
        if track:
            tls.held.append(self)
        self._acquired_ns = time.monotonic_ns()
        return True

    def release(self) -> None:
        if self._reentrant:
            d = self._depth()
            if d > 1:
                self._set_depth(d - 1)
                self._inner.release()
                return
            self._set_depth(0)
        start_ns = self._acquired_ns
        tls = _TLS
        tracked = False
        if self in tls.held:
            # usually the top of stack; out-of-order release (or a
            # cross-thread release of a lock this thread also holds) just
            # removes the entry
            tls.held.remove(self)
            tracked = True
        held_s = (time.monotonic_ns() - start_ns) / 1e9 if start_ns else 0.0
        self._inner.release()
        # report AFTER releasing: the metrics leg takes its own locks and
        # must not do so while this one is held
        if (tracked or self._gate) and held_s > _hold_threshold_s():
            _report_outlier(self._name, held_s)

    def locked(self) -> bool:
        inner = self._inner
        if self._reentrant:
            if self._depth() > 0:
                return True  # probing our own RLock would lie (reentrant)
            # RLock pre-3.12 lacks locked(); probe without blocking
            if inner.acquire(False):
                inner.release()
                return False
            return True
        return inner.locked()

    def _is_owned(self) -> bool:
        # threading.Condition adopts this hook when present.  Without it
        # the Condition FALLBACK probes lock.acquire(False) while the
        # caller holds the lock — which the attempt-time edge recording
        # above would see as a same-name self-acquisition and report as a
        # false cycle on every wait()/notify().
        if self._reentrant:
            return self._depth() > 0
        if self in _TLS.held:
            return True
        # Untracked hold (gate / reporting path) or another thread's:
        # probe the INNER lock directly — invisible to the order graph,
        # stdlib-fallback semantics otherwise.
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        self.acquire()
        return True

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<CheckedLock {self._name} {self._inner!r}>"


def enabled() -> bool:
    """Whether new :func:`lock`/:func:`rlock` calls return checked locks."""
    return _enabled


def set_enabled(value: bool) -> None:
    """Override the ``TORCHFT_LOCKCHECK`` gate for locks created *after*
    this call (tests; production uses the env var before import)."""
    global _enabled
    _enabled = bool(value)


def lock(name: str) -> Any:
    """A mutex for the creation site ``name`` (convention:
    ``module.field``, e.g. ``"flightrecorder.ring"``): checked when the
    detector is enabled, else a plain ``threading.Lock``."""
    return CheckedLock(name) if _enabled else threading.Lock()


def rlock(name: str) -> Any:
    """Reentrant variant of :func:`lock`."""
    return CheckedLock(name, reentrant=True) if _enabled else threading.RLock()


def gate(name: str) -> Any:
    """A community-held lock (acquired and released by *different*
    threads, e.g. a readers-writer gate): hold-time instrumented but
    excluded from the order graph, whose thread-local analysis would
    report false cycles for it."""
    return CheckedLock(name, gate=True) if _enabled else threading.Lock()


def cycles() -> "List[Tuple[str, ...]]":
    """Every distinct lock-order cycle observed so far (empty = no
    potential deadlock seen)."""
    return _GRAPH.snapshot_cycles()


def edges() -> "Dict[str, Set[str]]":
    """The observed acquisition-order graph ``{held: {acquired_next}}``."""
    return _GRAPH.snapshot_edges()


def hold_outliers() -> "Dict[str, int]":
    """``{lock name: outlier count}`` for holds past the threshold."""
    return _GRAPH.snapshot_outliers()


def reset() -> None:
    """Clear the graph and reports (test isolation)."""
    _GRAPH.clear()
