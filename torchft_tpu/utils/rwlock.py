"""Readers-writer lock with timeout-guarded acquisition.

TPU-native analog of the reference's checkpoint RWLock
(reference: torchft/checkpointing/_rwlock.py:41-131): many readers may hold
the lock concurrently (e.g. checkpoint transports serving a state snapshot)
while a single writer (the optimizer step mutating parameters) excludes all
readers.  Every acquisition takes a timeout so a stuck peer can never wedge
the training loop forever.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Iterator

from torchft_tpu.utils import lockcheck


class RWLock:
    """A two-mutex readers-writer lock.

    Writer preference is not enforced; fairness comes from the underlying
    primitive. All acquires raise TimeoutError on expiry rather than blocking
    forever, which is the property the fault-tolerance protocol needs.

    Not reentrant: a thread already holding the read side must not take
    the write side (upgrade deadlocks by construction), and a reader
    re-entering ``acquire_read`` while a writer waits can deadlock on
    primitives with writer preference.  Under ``TORCHFT_LOCKCHECK=1``
    both mutexes are lockcheck-instrumented: the reader gate as a full
    order-graph participant, the writer side as a hold-time-only *gate*
    (community-held, released cross-thread — order analysis would report
    a false reader<->writer cycle for it; see lockcheck.gate()).
    """

    def __init__(self, timeout: float = -1, writer_priority: bool = False) -> None:
        # Default timeout applied when an acquire doesn't pass its own.
        self._default_timeout = timeout
        self._reader_lock = lockcheck.lock("rwlock.reader_gate")
        # community gate: taken by the FIRST reader, released by the LAST
        # (possibly a different thread) — order-graph analysis is
        # thread-local and would report a false reader<->writer cycle, so
        # it gets hold-time-only instrumentation
        self._writer_lock = lockcheck.gate("rwlock.writer_gate")
        self._readers = 0
        # Writer-priority turnstile (opt-in).  The plain lock is
        # reader-preferring: with continuously overlapping readers,
        # ``_readers`` never reaches 0 and a writer starves FOREVER —
        # measured in the serving soak, where 32 polling clients held the
        # staged-snapshot read side so densely that a relay's staging
        # write lock never acquired and the whole tier 503'd.  With
        # ``writer_priority``, a waiting writer holds the turnstile while
        # it waits, new readers must pass through it first, the existing
        # readers drain, and the writer gets in; readers resume after.
        # Sharpens the documented non-reentrancy rule: under
        # writer_priority a reader RE-entering acquire_read while a
        # writer waits deadlocks by construction — don't nest readers.
        self._writer_priority = writer_priority
        self._turnstile = (
            lockcheck.lock("rwlock.turnstile") if writer_priority else None
        )

    def _resolve(self, timeout: float | None) -> float:
        return self._default_timeout if timeout is None else timeout

    def acquire_read(self, timeout: float | None = None) -> None:
        t = self._resolve(timeout)
        # Single deadline across both mutex acquisitions so the configured
        # timeout bounds the total wait, not each stage.
        deadline = time.monotonic() + t if t >= 0 else None
        if self._turnstile is not None:
            # Writer-priority: pass through the turnstile a waiting
            # writer holds, so new readers queue BEHIND the writer
            # instead of starving it (acquire-and-release: readers never
            # hold it while waiting on the gates below).
            if not self._turnstile.acquire(timeout=t):
                raise TimeoutError(f"acquire_read timed out after {t}s")
            self._turnstile.release()
            if deadline is not None:
                t = max(0.0, deadline - time.monotonic())
        if not self._reader_lock.acquire(timeout=t):
            raise TimeoutError(f"acquire_read timed out after {t}s")
        try:
            self._readers += 1
            if self._readers == 1:
                # First reader takes the writer lock on behalf of all readers.
                remaining = t if deadline is None else max(0.0, deadline - time.monotonic())
                if not self._writer_lock.acquire(timeout=remaining):
                    self._readers -= 1
                    raise TimeoutError(f"acquire_read timed out after {t}s")
        finally:
            self._reader_lock.release()

    def release_read(self) -> None:
        with self._reader_lock:
            assert self._readers > 0, "release_read without acquire_read"
            self._readers -= 1
            if self._readers == 0:
                self._writer_lock.release()

    def acquire_write(self, timeout: float | None = None) -> None:
        t = self._resolve(timeout)
        if self._turnstile is None:
            if not self._writer_lock.acquire(timeout=t):
                raise TimeoutError(f"acquire_write timed out after {t}s")
            return
        # Writer-priority: hold the turnstile while waiting on the
        # writer gate — new readers block at the turnstile, the readers
        # already in drain, and the writer acquires in bounded time
        # regardless of reader arrival rate.
        deadline = time.monotonic() + t if t >= 0 else None
        if not self._turnstile.acquire(timeout=t):
            raise TimeoutError(f"acquire_write timed out after {t}s")
        try:
            remaining = (
                t if deadline is None else max(0.0, deadline - time.monotonic())
            )
            if not self._writer_lock.acquire(timeout=remaining):
                raise TimeoutError(f"acquire_write timed out after {t}s")
        finally:
            self._turnstile.release()

    def release_write(self) -> None:
        self._writer_lock.release()

    @contextmanager
    def r_lock(self, timeout: float | None = None) -> Iterator[None]:
        self.acquire_read(timeout)
        try:
            yield
        finally:
            self.release_read()

    @contextmanager
    def w_lock(self, timeout: float | None = None) -> Iterator[None]:
        self.acquire_write(timeout)
        try:
            yield
        finally:
            self.release_write()
