"""Unified retry/backoff policy for every failure-bearing loop.

Before this module the package retried in three divergent ad-hoc loops
(RPC connect in coordination.py, checkpoint fetch in http_transport.py,
the manager-address store probe in manager.py), each with its own backoff
curve, deadline handling, and no jitter or accounting.  Centralizing the
policy is the stance of the reliable-collective literature (Prime PCCL,
"Reliable and Resilient Collective Communication Library", PAPERS.md):
retry behaviour must be one reviewable object, not folklore scattered
across call sites.

:class:`RetryPolicy` provides:

- **exponential backoff with full jitter**: each sleep is drawn uniformly
  from ``[0, min(max_delay, base_delay * multiplier**n)]`` — full jitter
  decorrelates retry storms after a shared failure (the AWS architecture
  result), which matters exactly when many replicas lose the same peer;
- **deadline budgets**: a total budget (``timeout`` per call or
  ``total_timeout`` on the policy) that is never exceeded, plus an
  optional per-attempt budget; attempts receive their remaining budget as
  an argument.  Expiry can arm an abort callback via
  :func:`torchft_tpu.utils.futures.context_timeout` (e.g. ``pg.abort``)
  so a wedged attempt is cancelled, not just abandoned;
- **retryable-exception classification**: a tuple of types and/or a
  predicate — everything else propagates immediately;
- **accounting**: every retry increments
  ``torchft_retries_total{op}`` and records its backoff in
  ``torchft_retry_backoff_seconds{op}``.

Policies are frozen dataclasses — share them module-level, derive
variants with :func:`dataclasses.replace`.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple, Type

from torchft_tpu.utils.futures import context_timeout

__all__ = ["RetryPolicy", "DEFAULT_RETRYABLE"]

#: Connection-ish failures that are safe to retry by default.  This
#: includes per-attempt socket timeouts (``TimeoutError`` subclasses
#: ``OSError`` since PEP 3151) — which is correct for connect-style
#: attempts whose budget is the *total* deadline; policies whose
#: attempts own their full timeout budget (e.g. the quorum RPC) should
#: narrow this to ``(ConnectionError,)`` so an expired attempt is not
#: doubled.  The ``TimeoutError`` :meth:`RetryPolicy.run` itself raises
#: on budget exhaustion is raised outside the attempt try and is never
#: self-retried.
DEFAULT_RETRYABLE: "Tuple[Type[BaseException], ...]" = (ConnectionError, OSError)


@dataclass(frozen=True)
class RetryPolicy:
    """Declarative retry/backoff policy; execute callables via :meth:`run`.

    Args:
        name: default metrics ``op`` label (override per call with ``op=``).
        max_attempts: total attempts allowed (``None`` = bounded only by
            the deadline budget).
        base_delay / multiplier / max_delay: the exponential backoff curve.
        jitter: full jitter (uniform in ``[0, cap]``) when True, the
            deterministic cap when False.
        total_timeout: default overall budget in seconds (``None`` =
            unbounded); ``run(timeout=...)`` overrides per call.
        attempt_timeout: optional per-attempt budget (clamped to the
            remaining total).
        retryable: exception types that may be retried.
        retry_if: optional predicate overriding ``retryable`` entirely.
    """

    name: str = "retry"
    max_attempts: "Optional[int]" = None
    base_delay: float = 0.1
    multiplier: float = 2.0
    max_delay: float = 10.0
    jitter: bool = True
    total_timeout: "Optional[float]" = None
    attempt_timeout: "Optional[float]" = None
    retryable: "Tuple[Type[BaseException], ...]" = DEFAULT_RETRYABLE
    retry_if: "Optional[Callable[[BaseException], bool]]" = None

    def is_retryable(self, exc: BaseException) -> bool:
        """Classification: predicate wins when present, else isinstance."""
        if self.retry_if is not None:
            return bool(self.retry_if(exc))
        return isinstance(exc, self.retryable)

    def backoff(self, attempt: int, rng: "Any" = random) -> float:
        """Sleep before retry number ``attempt`` (0-based): full jitter in
        ``[0, min(max_delay, base_delay * multiplier**attempt)]``."""
        cap = min(self.max_delay, self.base_delay * self.multiplier**attempt)
        return rng.uniform(0.0, cap) if self.jitter else cap

    def run(
        self,
        fn: "Callable[[Optional[float]], Any]",
        *,
        timeout: "Optional[float]" = None,
        op: "Optional[str]" = None,
        abort_cb: "Optional[Callable[[], None]]" = None,
        on_retry: "Optional[Callable[[BaseException, int, float], None]]" = None,
        rng: "Any" = random,
    ) -> Any:
        """Call ``fn(attempt_budget_seconds)`` until success/exhaustion.

        ``fn`` receives its per-attempt budget (``None`` when unbounded)
        and should pass it down as the attempt's own timeout.  When
        ``abort_cb`` is given and the attempt has a budget, the attempt is
        wrapped in ``context_timeout(abort_cb, budget)`` so expiry actively
        cancels it (e.g. ``pg.abort`` closing sockets).

        Raises ``TimeoutError`` when the deadline budget expires (the last
        attempt's error chained as ``__cause__``); re-raises the attempt's
        error when it is non-retryable or ``max_attempts`` is exhausted.
        ``on_retry(exc, attempt_number, delay)`` observes each retry.
        """
        from torchft_tpu.utils import metrics as _metrics
        from torchft_tpu.utils import flightrecorder as _flightrec

        op = op or self.name
        budget = self.total_timeout if timeout is None else timeout
        deadline = None if budget is None else time.monotonic() + budget
        attempt = 0
        last_exc: "Optional[BaseException]" = None
        while True:
            remaining: "Optional[float]" = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"{op}: retry budget ({budget}s) exhausted after "
                        f"{attempt} attempt(s): {last_exc}"
                    ) from last_exc
            att_budget = remaining
            if self.attempt_timeout is not None:
                att_budget = (
                    self.attempt_timeout
                    if remaining is None
                    else min(self.attempt_timeout, remaining)
                )
            try:
                if abort_cb is not None and att_budget is not None:
                    with context_timeout(abort_cb, att_budget):
                        return fn(att_budget)
                return fn(att_budget)
            except Exception as e:  # noqa: BLE001 - classified below
                if not self.is_retryable(e):
                    raise
                last_exc = e
                attempt += 1
                if self.max_attempts is not None and attempt >= self.max_attempts:
                    raise
                delay = self.backoff(attempt - 1, rng)
                if deadline is not None:
                    delay = min(delay, max(deadline - time.monotonic(), 0.0))
                _metrics.RETRIES.labels(op=op).inc()
                _metrics.RETRY_BACKOFF.labels(op=op).observe(delay)
                # flight record per retry: torchft-diagnose flags retry
                # storms (many of these in a short window) as a culprit
                # signal
                _flightrec.record(
                    "retry",
                    status="retry",
                    retry_op=op,
                    attempt=attempt,
                    backoff_s=round(delay, 4),
                    error=repr(e),
                )
                if on_retry is not None:
                    on_retry(e, attempt, delay)
                if delay > 0:
                    time.sleep(delay)
