"""Distributed tracing for the quorum/recovery hot path.

Third leg of the telemetry layer (logs: utils/otel.py, metrics:
utils/metrics.py), grown from the PR-1 single-process span tree into
**fleet-wide causal tracing**:

- **Per-step trace ids are deterministic** (:func:`step_trace_id` hashes
  ``(JOB_ID, step)``), so every replica group, the lighthouse, and both
  heal endpoints land in ONE trace per training step without any
  coordination RPC — the property the cross-replica critical-path ledger
  (``torchft-diagnose --trace``) joins on.
- **Causal propagation** rides a W3C-traceparent-style context
  (:class:`TraceContext`: ``trace_id``, ``span_id``, sampled flag)
  carried as the ``traceparent`` envelope field of every framed-JSON RPC
  (``coordination._RpcClient`` injects, the native servers continue it —
  see docs/protocol.md "Wire surface"), as an HTTP header on the
  checkpoint heal path, and as a metadata field on PGTransport streams.
- **Native server spans** (``rpc.<method>`` around each handler) are
  relayed back to this module's exporter through a ctypes span-sink
  callback (``_native.SPAN_SINK_CFUNC`` → ``tft_set_span_sink``), the
  same provider-callback idiom as the lighthouse /metrics supplement.
- **Sinks**: the OTLP/HTTP ``/v1/traces`` exporter (``TORCHFT_USE_OTEL``)
  and/or a crash-durable JSONL file (``TORCHFT_TRACE_FILE``) so tier-1
  tests and air-gapped post-mortems need no collector.  O_APPEND writes
  keep multi-process runs safe on one file.
- **Sampling**: ``TORCHFT_TRACE_SAMPLE`` (fraction of steps, default 1)
  decides per *step* from the deterministic trace id, so all replicas
  sample the same steps and sampled traces stay complete.

The disabled path stays zero-cost: with no tracer installed every entry
point is a ``None`` check (budget-tested like the flight recorder's).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from torchft_tpu.utils.otel import BatchedOTLPHTTPExporter, _kv_list

logger = logging.getLogger(__name__)


def new_trace_id() -> str:
    """128-bit trace id as 32 lowercase hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit span id as 16 lowercase hex chars."""
    return os.urandom(8).hex()


def step_trace_id(step: int, job_id: "Optional[str]" = None) -> str:
    """The deterministic per-step trace id every replica derives
    identically: sha256 over ``(JOB_ID, step)``.  One training step ==
    one trace across the whole fleet, with zero coordination."""
    if job_id is None:
        from torchft_tpu.utils.env import env_str

        job_id = env_str("JOB_ID", "unknown")
    digest = hashlib.sha256(
        f"torchft-step:{job_id}:{int(step)}".encode()
    ).hexdigest()
    return digest[:32]


@dataclass(frozen=True)
class TraceContext:
    """One position in a trace: (trace_id, span_id) plus the sampled
    flag.  ``span_id`` is the id child spans parent to."""

    trace_id: str
    span_id: str
    sampled: bool = True

    def child(self) -> "TraceContext":
        """A fresh context under this one (new span id, same trace)."""
        return TraceContext(self.trace_id, new_span_id(), self.sampled)

    def to_traceparent(self) -> str:
        """W3C-style ``00-<trace_id>-<span_id>-<flags>`` encoding — the
        wire form carried in RPC envelopes and HTTP headers."""
        return (
            f"00-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )

    @staticmethod
    def from_traceparent(value: "Optional[str]") -> "Optional[TraceContext]":
        """Parse the wire form; None on anything malformed (a hostile or
        stale peer must never break the server).  Exactly as strict as
        the native parser (net.cc parse_traceparent): fixed field
        lengths, pure-hex fields — the two sides must agree on what is
        a valid context or a trace silently splits between them."""
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().split("-")
        if len(parts) != 4:
            return None
        _, trace_id, span_id, flags = parts
        if len(trace_id) != 32 or len(span_id) != 16 or len(flags) != 2:
            return None
        hexdigits = "0123456789abcdefABCDEF"
        if not all(
            c in hexdigits for field in (trace_id, span_id, flags) for c in field
        ):
            return None
        return TraceContext(trace_id, span_id, sampled=flags != "00")


class OTLPHTTPSpanExporter(BatchedOTLPHTTPExporter):
    """Batched OTLP/HTTP (JSON encoding) span exporter: the shared
    ``BatchedOTLPHTTPExporter`` pipeline (daemon flush thread, atexit
    flush, dropped counter, a dead collector never kills training) with
    the ``/v1/traces`` encoding.  ``export`` takes the internal span dict
    produced by :meth:`Tracer.export_span`."""

    path_suffix = "/v1/traces"

    def __init__(self, endpoint: str, max_batch: int = 128, **kw: Any) -> None:
        super().__init__(endpoint, max_batch=max_batch, **kw)

    def _encode(self, batch: "List[Dict[str, Any]]") -> bytes:
        spans = []
        for s in batch:
            span: "Dict[str, Any]" = {
                "traceId": s["trace_id"],
                "spanId": s["span_id"],
                "name": s["name"],
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(s["start_ns"]),
                "endTimeUnixNano": str(s["end_ns"]),
                "attributes": _kv_list(s.get("attributes", {})),
                "status": {"code": 1 if s.get("ok", True) else 2},
            }
            if s.get("parent_span_id"):
                span["parentSpanId"] = s["parent_span_id"]
            spans.append(span)
        doc = {
            "resourceSpans": [
                {
                    "resource": self._resource,
                    "scopeSpans": [
                        {"scope": {"name": "torchft_tpu"}, "spans": spans}
                    ],
                }
            ]
        }
        return json.dumps(doc, default=str).encode()


class FileSpanSink:
    """Crash-durable JSONL span sink (``TORCHFT_TRACE_FILE``): one JSON
    object per finished span, written with a single O_APPEND ``write``
    so concurrent processes sharing the file never interleave lines.
    This is the sink the tier-1 round-trip test and the diagnose ledger
    read — no collector required."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._fd: "Optional[int]" = None
        self._closed = False

    def export(self, span: "Dict[str, Any]") -> None:
        line = (json.dumps(span, default=str) + "\n").encode()
        try:
            with self._lock:
                if self._closed:
                    # a racing emitter that grabbed the tracer before
                    # uninstall must not silently reopen the file and
                    # leak the fd — late spans are dropped instead
                    return
                if self._fd is None:
                    self._fd = os.open(
                        self.path,
                        os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                        0o644,
                    )
                os.write(self._fd, line)
        except OSError:
            logger.debug("trace file write failed", exc_info=True)

    def flush(self, timeout: "Optional[float]" = None) -> bool:
        return True  # every export is already a completed write()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None


class Tracer:
    """Span factory over the configured sinks (OTLP exporter and/or the
    JSONL file sink).  One call per finished span; context PROPAGATION is
    the thread-local module state below plus the wire fields — the
    tracer itself stays a dumb emitter."""

    def __init__(
        self,
        exporter: "Optional[OTLPHTTPSpanExporter]" = None,
        sink: "Optional[FileSpanSink]" = None,
        sample: float = 1.0,
    ) -> None:
        self.exporter = exporter
        self.sink = sink
        self.sample = min(max(float(sample), 0.0), 1.0)

    def sample_step(self, step: int, job_id: "Optional[str]" = None) -> bool:
        """Deterministic per-step sampling decision, identical on every
        replica (derived from the step trace id, not local randomness),
        so a sampled step's trace is always COMPLETE across the fleet."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        frac = int(step_trace_id(step, job_id)[:8], 16) / float(1 << 32)
        return frac < self.sample

    def export_span(
        self,
        name: str,
        trace_id: str,
        start_ns: int,
        end_ns: int,
        span_id: "Optional[str]" = None,
        parent_span_id: "Optional[str]" = None,
        attributes: "Optional[Dict[str, Any]]" = None,
        ok: bool = True,
    ) -> str:
        """Record one finished span; returns its span id."""
        sid = span_id or new_span_id()
        span = {
            "name": name,
            "trace_id": trace_id,
            "span_id": sid,
            "parent_span_id": parent_span_id,
            "start_ns": int(start_ns),
            "end_ns": int(end_ns),
            "attributes": attributes or {},
            "ok": ok,
        }
        if self.exporter is not None:
            self.exporter.export(span)
        if self.sink is not None:
            self.sink.export(span)
        return sid

    def close(self) -> None:
        if self.exporter is not None:
            self.exporter.close()
        if self.sink is not None:
            self.sink.close()


_tracer: "Optional[Tracer]" = None
_tracer_lock = threading.Lock()
_tls = threading.local()

# Keeps the ctypes callback object alive while registered natively.
_native_sink_cfunc: Any = None


def install_tracer(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process-wide tracer spans are emitted to."""
    global _tracer
    with _tracer_lock:
        _tracer = tracer
    # If the native coordination core is already loaded, wire its span
    # sink now; otherwise server construction does it (coordination.py).
    install_native_span_sink()
    return tracer


def uninstall_tracer() -> None:
    global _tracer
    with _tracer_lock:
        old, _tracer = _tracer, None
    _uninstall_native_span_sink()
    if old is not None:
        old.close()


def get_tracer() -> "Optional[Tracer]":
    """The installed tracer, or None (the common case — callers must treat
    tracing as strictly optional and zero-cost when absent)."""
    return _tracer


# ---------------------------------------------------------------------------
# thread-local current context (the propagation anchor)
# ---------------------------------------------------------------------------


def set_current(ctx: "Optional[TraceContext]") -> None:
    """Bind ``ctx`` as this thread's current trace position.  The Manager
    sets its round context on the caller and async-quorum threads; RPC
    clients and the heal transports read it back for injection."""
    _tls.ctx = ctx


def get_current() -> "Optional[TraceContext]":
    """This thread's current context, or None.  Zero-cost fast path:
    with no tracer installed this returns None without touching the
    thread-local at all."""
    if _tracer is None:
        return None
    return getattr(_tls, "ctx", None)


def current_traceparent() -> "Optional[str]":
    """The wire form of the current context, or None when tracing is off,
    no context is bound, or the step was not sampled — the ONE call every
    injection point (RPC envelope, HTTP header, PG metadata) makes."""
    if _tracer is None:
        return None
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or not ctx.sampled:
        return None
    return ctx.to_traceparent()


# ---------------------------------------------------------------------------
# native span sink (rpc.* server spans -> this process's tracer)
# ---------------------------------------------------------------------------


def _on_native_span(payload: bytes) -> None:
    """ctypes callback target: one finished native server span as JSON.
    Must never raise into native code."""
    tracer = _tracer
    if tracer is None:
        return
    try:
        span = json.loads(payload.decode())
        tracer.export_span(
            name=str(span["name"]),
            trace_id=str(span["trace_id"]),
            span_id=span.get("span_id") or None,
            parent_span_id=span.get("parent_span_id") or None,
            start_ns=int(span["start_ns"]),
            end_ns=int(span["end_ns"]),
            attributes=dict(span.get("attributes") or {}),
            ok=bool(span.get("ok", True)),
        )
    except Exception:  # noqa: BLE001 - telemetry must not wedge a server
        logger.debug("bad native span payload", exc_info=True)


def install_native_span_sink(force_load: bool = False) -> bool:
    """Register the span-sink callback with the native library so the
    coordination servers' ``rpc.<method>`` spans reach the Python
    exporter.  By default only wires up when the native lib is ALREADY
    loaded (installing a tracer must not trigger a native build);
    ``coordination._NativeServer`` calls with ``force_load=True`` once a
    server exists.  Idempotent; no-op without an installed tracer."""
    global _native_sink_cfunc
    if _tracer is None:
        return False
    from torchft_tpu import _native

    if not force_load and not _native.loaded():
        return False
    with _tracer_lock:
        if _native_sink_cfunc is not None:
            return True  # already registered
        cb = _native.SPAN_SINK_CFUNC(_on_native_span)
        _native.get_lib().tft_set_span_sink(cb)
        _native_sink_cfunc = cb
    return True


def _uninstall_native_span_sink() -> None:
    global _native_sink_cfunc
    with _tracer_lock:
        cb, _native_sink_cfunc = _native_sink_cfunc, None
    if cb is None:
        return
    from torchft_tpu import _native

    if _native.loaded():
        _native.get_lib().tft_set_span_sink(_native.SPAN_SINK_CFUNC())


# ---------------------------------------------------------------------------
# env wiring
# ---------------------------------------------------------------------------


def maybe_install_from_env() -> "Optional[Tracer]":
    """Install the process tracer when either trace surface is enabled:
    ``TORCHFT_USE_OTEL`` (OTLP/HTTP exporter; endpoint from
    ``OTEL_EXPORTER_OTLP_TRACES_ENDPOINT`` / ``OTEL_EXPORTER_OTLP_ENDPOINT``)
    and/or ``TORCHFT_TRACE_FILE`` (JSONL span sink).  Step sampling from
    ``TORCHFT_TRACE_SAMPLE`` (fraction of steps, default 1.0)."""
    from torchft_tpu.utils.env import env_bool, env_float, env_str

    use_otel = env_bool("TORCHFT_USE_OTEL")
    trace_file = env_str("TORCHFT_TRACE_FILE")
    if not use_otel and not trace_file:
        return None
    if _tracer is not None:
        return _tracer
    exporter: "Optional[OTLPHTTPSpanExporter]" = None
    if use_otel:
        endpoint = (
            env_str("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT")
            or env_str("OTEL_EXPORTER_OTLP_ENDPOINT")
            or "http://localhost:4318"
        )
        exporter = OTLPHTTPSpanExporter(endpoint)
    sink = FileSpanSink(trace_file) if trace_file else None
    sample = env_float("TORCHFT_TRACE_SAMPLE", 1.0, minimum=0.0)
    return install_tracer(Tracer(exporter, sink, sample=sample))
