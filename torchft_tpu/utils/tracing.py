"""OTLP/HTTP trace export for the quorum/recovery hot path.

Third leg of the telemetry layer (logs: utils/otel.py, metrics:
utils/metrics.py): the Manager emits one root span per quorum round
("quorum_round", start_quorum -> should_commit) with child spans for each
protocol phase (quorum_rpc, pg_configure, heal_send, heal_recv, commit,
...).  Spans carry ``step`` / ``quorum_id`` / ``replica_id`` attributes —
the same keys the structured events carry — so a trace backend and a log
backend can be joined on them.

No opentelemetry SDK in this environment: spans are encoded directly as
the OTLP/HTTP **JSON** traces protocol (``POST <endpoint>/v1/traces``,
``resourceSpans`` documents) with the same batching, gating
(``TORCHFT_USE_OTEL``) and failure policy as the log exporter — a dead
collector never takes down training.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional

from torchft_tpu.utils.otel import BatchedOTLPHTTPExporter, _kv_list

logger = logging.getLogger(__name__)


def new_trace_id() -> str:
    """128-bit trace id as 32 lowercase hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit span id as 16 lowercase hex chars."""
    return os.urandom(8).hex()


class OTLPHTTPSpanExporter(BatchedOTLPHTTPExporter):
    """Batched OTLP/HTTP (JSON encoding) span exporter: the shared
    ``BatchedOTLPHTTPExporter`` pipeline (daemon flush thread, atexit
    flush, dropped counter, a dead collector never kills training) with
    the ``/v1/traces`` encoding.  ``export`` takes the internal span dict
    produced by :meth:`Tracer.export_span`."""

    path_suffix = "/v1/traces"

    def __init__(self, endpoint: str, max_batch: int = 128, **kw: Any) -> None:
        super().__init__(endpoint, max_batch=max_batch, **kw)

    def _encode(self, batch: "List[Dict[str, Any]]") -> bytes:
        spans = []
        for s in batch:
            span: "Dict[str, Any]" = {
                "traceId": s["trace_id"],
                "spanId": s["span_id"],
                "name": s["name"],
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(s["start_ns"]),
                "endTimeUnixNano": str(s["end_ns"]),
                "attributes": _kv_list(s.get("attributes", {})),
                "status": {"code": 1 if s.get("ok", True) else 2},
            }
            if s.get("parent_span_id"):
                span["parentSpanId"] = s["parent_span_id"]
            spans.append(span)
        doc = {
            "resourceSpans": [
                {
                    "resource": self._resource,
                    "scopeSpans": [
                        {"scope": {"name": "torchft_tpu"}, "spans": spans}
                    ],
                }
            ]
        }
        return json.dumps(doc, default=str).encode()


class Tracer:
    """Thin span factory over an exporter; the Manager is the only caller
    on the hot path, so the API is one call per finished span (no context
    propagation machinery needed for a single-process span tree)."""

    def __init__(self, exporter: OTLPHTTPSpanExporter) -> None:
        self.exporter = exporter

    def export_span(
        self,
        name: str,
        trace_id: str,
        start_ns: int,
        end_ns: int,
        span_id: "Optional[str]" = None,
        parent_span_id: "Optional[str]" = None,
        attributes: "Optional[Dict[str, Any]]" = None,
        ok: bool = True,
    ) -> str:
        """Record one finished span; returns its span id."""
        sid = span_id or new_span_id()
        self.exporter.export(
            {
                "name": name,
                "trace_id": trace_id,
                "span_id": sid,
                "parent_span_id": parent_span_id,
                "start_ns": int(start_ns),
                "end_ns": int(end_ns),
                "attributes": attributes or {},
                "ok": ok,
            }
        )
        return sid


_tracer: "Optional[Tracer]" = None
_tracer_lock = threading.Lock()


def install_tracer(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process-wide tracer the Manager emits to."""
    global _tracer
    with _tracer_lock:
        _tracer = tracer
    return tracer


def uninstall_tracer() -> None:
    global _tracer
    with _tracer_lock:
        old, _tracer = _tracer, None
    if old is not None:
        old.exporter.close()


def get_tracer() -> "Optional[Tracer]":
    """The installed tracer, or None (the common case — callers must treat
    tracing as strictly optional and zero-cost when absent)."""
    return _tracer


def maybe_install_from_env() -> "Optional[Tracer]":
    """Install an OTLP span exporter when ``TORCHFT_USE_OTEL`` is truthy.
    Endpoint: ``OTEL_EXPORTER_OTLP_TRACES_ENDPOINT``, else
    ``OTEL_EXPORTER_OTLP_ENDPOINT``, else the OTLP default."""
    from torchft_tpu.utils.env import env_bool, env_str

    if not env_bool("TORCHFT_USE_OTEL"):
        return None
    if _tracer is not None:
        return _tracer
    endpoint = (
        env_str("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT")
        or env_str("OTEL_EXPORTER_OTLP_ENDPOINT")
        or "http://localhost:4318"
    )
    return install_tracer(Tracer(OTLPHTTPSpanExporter(endpoint)))
