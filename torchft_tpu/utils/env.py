"""The one sanctioned reader of environment knobs.

Every ``TORCHFT_*`` configuration knob in this package is read through the
typed helpers below (``env_str`` / ``env_int`` / ``env_float`` /
``env_bool``) instead of ad-hoc ``os.environ`` access.  Centralizing the
reads buys three properties the scattered form can't:

- **uniform garbage handling**: a typo'd value warns and falls back to the
  default instead of crashing training at import (several knobs are read
  at ``import torchft_tpu``);
- **a statically checkable surface**: the ``env-hygiene`` pass of
  ``tft-lint`` (torchft_tpu/analysis/) flags any direct
  ``os.environ``/``os.getenv`` read outside this module, requires helper
  arguments to be ``TORCHFT_*``-named (or allowlisted externals like the
  ``OTEL_*`` standard vars), and cross-checks every knob against the docs
  tables — an undocumented knob fails CI;
- **one grep target** for "what can I configure".

``env_int`` began life as ``flightrecorder.env_int`` (PR 3) and is
re-exported from there for compatibility.

Writes (``os.environ[...] = ...`` for child-process propagation, as the
launcher and test harness do) are not routed through here — the lint pass
only polices reads.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

__all__ = ["env_str", "env_int", "env_float", "env_bool"]

# Values env_bool treats as true (case-insensitive); everything else —
# including the empty string — is false.  Matches the historical
# TORCHFT_USE_OTEL gate ("true"/"1"/"yes") plus the conventional "on".
_TRUTHY = ("1", "true", "yes", "on")


def env_str(name: str, default: str = "") -> str:
    """Read a string env knob; empty/unset returns ``default``."""
    return os.environ.get(name) or default


def env_int(name: str, default: int, minimum: "Optional[int]" = 1) -> int:
    """Parse an integer env knob: warn-and-default on garbage, clamp to
    ``minimum`` (pass ``minimum=None`` or a smaller bound for knobs where
    0 or negatives are meaningful, e.g. an ephemeral-port 0)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        logger.warning("invalid %s=%r, using %d", name, raw, default)
        return default
    return value if minimum is None else max(value, minimum)


def env_float(name: str, default: float, minimum: "Optional[float]" = None) -> float:
    """Parse a float env knob: warn-and-default on garbage, optional clamp."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        logger.warning("invalid %s=%r, using %s", name, raw, default)
        return default
    return value if minimum is None else max(value, minimum)


def env_bool(name: str, default: bool = False) -> bool:
    """Parse a boolean env knob: truthy values are ``1/true/yes/on``
    (case-insensitive); unset/empty returns ``default``."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    return raw.lower() in _TRUTHY
