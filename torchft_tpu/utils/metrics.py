"""Unified metrics layer: registry, Prometheus exposition, OTLP export.

The structured-event pipeline (utils/logging.py) answers "what happened";
this module answers "how often / how long / how many bytes" — the live,
NON-destructive observability surface an elastic trainer needs (consumers
take deltas of ``Manager.phase_times`` snapshots).  Reliable-collective
systems (Prime PCCL, PAPERS.md) treat per-phase counters as first-class
diagnostics; same stance here.

Three building blocks, stdlib only (this environment ships no
prometheus_client / opentelemetry SDK):

- a thread-safe :class:`Registry` of :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` families with labeled children
  (``.labels(replica_id=..., phase=...)``).  Counter and Histogram
  families additionally maintain an **unlabeled aggregate series** (the
  sum over all children) so a fresh process — or a scraper that wants the
  cluster-wide total without PromQL — always sees every family's series,
  zero-valued before first use;
- Prometheus text exposition (:meth:`Registry.render`, text format 0.0.4
  with full label escaping) served by the lighthouse dashboard port
  (native ``GET /metrics``, see coordination.py), by the opt-in
  per-manager :class:`MetricsHTTPServer` (``TORCHFT_METRICS_PORT``), and
  parseable back via :func:`parse_text_exposition` (tests + the tier-1
  smoke check);
- an OTLP/HTTP **metrics** exporter (``POST /v1/metrics``, JSON encoding,
  cumulative temporality) in the style of ``utils/otel.py``'s log
  exporter, gated on the same ``TORCHFT_USE_OTEL`` env.

Failure policy matches every sink in this framework: a dead collector or
a wedged scraper never takes down training.

Every torchft-exported instrument is defined at the bottom of this module
(one source of truth for the docs table in docs/observability.md).
"""

from __future__ import annotations

import atexit
import bisect
import json
import logging
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

from torchft_tpu.utils import lockcheck
from torchft_tpu.utils.env import env_bool, env_float, env_int, env_str

logger = logging.getLogger(__name__)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Fixed exponential latency buckets: 1 ms .. ~65 s doubling, suitable for
# everything from a sub-ms fast quorum to a full heal over a slow link.
DEFAULT_BUCKETS: "Tuple[float, ...]" = tuple(0.001 * 2**i for i in range(17))

# Process start, the OTLP cumulative-sum start timestamp.
_START_NS = time.time_ns()


def _fmt_value(v: float) -> str:
    """Prometheus sample-value formatting (ints without the trailing .0)."""
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(items: "Sequence[Tuple[str, str]]") -> str:
    if not items:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in items
    )
    return "{" + inner + "}"


class _Metric:
    """One metric family: name, help, label names, children keyed by label
    values.  All mutation goes through ``self._lock`` — increments arrive
    from the training loop, the async quorum thread, PG worker threads and
    checkpoint server threads concurrently."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: "Sequence[str]" = (),
        registry: "Optional[Registry]" = None,
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lockcheck.lock(f"metrics.{name}")
        self._children: "Dict[Tuple[str, ...], Any]" = {}
        self._default = self._new_state()
        if registry is None:
            registry = REGISTRY
        registry.register(self)

    # subclass hooks ------------------------------------------------------
    def _new_state(self) -> Any:
        raise NotImplementedError

    def labels(self, **labelvalues: Any) -> "_BoundChild":
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_state()
                self._children[key] = child
        return _BoundChild(self, child)

    def _series(self) -> "List[Tuple[Tuple[Tuple[str, str], ...], Any]]":
        """Snapshot [(label_items, state_copy)] — default series first.
        The default (unlabeled) series renders for counters/histograms
        always, and for gauges only when the family is unlabeled (a sum
        of last-set gauge values is not a meaningful gauge)."""
        with self._lock:
            out: "List[Tuple[Tuple[Tuple[str, str], ...], Any]]" = []
            if not self.labelnames or self.kind != "gauge":
                out.append(((), self._copy_state(self._default)))
            for key, child in self._children.items():
                out.append(
                    (tuple(zip(self.labelnames, key)), self._copy_state(child))
                )
            return out

    def _copy_state(self, state: Any) -> Any:
        return state


class _BoundChild:
    """A (family, child-state) pair returned by ``labels()``; updates fan
    into the child AND the family's unlabeled aggregate (counters and
    histograms — see module docstring)."""

    __slots__ = ("_metric", "_state")

    def __init__(self, metric: _Metric, state: Any) -> None:
        self._metric = metric
        self._state = state

    def inc(self, amount: float = 1) -> None:
        self._metric._inc_state(self._state, amount, aggregate=True)

    def dec(self, amount: float = 1) -> None:
        self.inc(-amount)

    def set(self, value: float) -> None:
        self._metric._set_state(self._state, value)

    def observe(self, value: float) -> None:
        self._metric._observe_state(self._state, value, aggregate=True)

    def get(self) -> Any:
        return self._metric._read_state(self._state)


class Counter(_Metric):
    kind = "counter"

    def _new_state(self) -> "List[float]":
        return [0.0]

    def inc(self, amount: float = 1) -> None:
        self._inc_state(self._default, amount, aggregate=False)

    def get(self) -> float:
        return self._read_state(self._default)

    def _inc_state(self, state: "List[float]", amount: float, aggregate: bool) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            state[0] += amount
            if aggregate and state is not self._default:
                self._default[0] += amount

    def _set_state(self, state: Any, value: float) -> None:
        raise TypeError("set() is not valid on a counter")

    def _observe_state(self, state: Any, value: float, aggregate: bool) -> None:
        raise TypeError("observe() is not valid on a counter")

    def _read_state(self, state: "List[float]") -> float:
        with self._lock:
            return state[0]

    def _copy_state(self, state: "List[float]") -> float:
        return state[0]


class Gauge(_Metric):
    kind = "gauge"

    def _new_state(self) -> "List[float]":
        return [0.0]

    def set(self, value: float) -> None:
        self._set_state(self._default, value)

    def inc(self, amount: float = 1) -> None:
        self._inc_state(self._default, amount, aggregate=False)

    def dec(self, amount: float = 1) -> None:
        self.inc(-amount)

    def get(self) -> float:
        return self._read_state(self._default)

    def _inc_state(self, state: "List[float]", amount: float, aggregate: bool) -> None:
        with self._lock:
            state[0] += amount

    def _set_state(self, state: "List[float]", value: float) -> None:
        with self._lock:
            state[0] = float(value)

    def _observe_state(self, state: Any, value: float, aggregate: bool) -> None:
        raise TypeError("observe() is not valid on a gauge")

    def _read_state(self, state: "List[float]") -> float:
        with self._lock:
            return state[0]

    def _copy_state(self, state: "List[float]") -> float:
        return state[0]


class _HistState:
    __slots__ = ("buckets", "sum", "count")

    def __init__(self, nbuckets: int) -> None:
        self.buckets = [0] * nbuckets  # per-bucket counts (not cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: "Sequence[str]" = (),
        buckets: "Optional[Sequence[float]]" = None,
        registry: "Optional[Registry]" = None,
    ) -> None:
        bounds = tuple(sorted(DEFAULT_BUCKETS if buckets is None else buckets))
        if not bounds or any(
            b >= n for b, n in zip(bounds, bounds[1:])
        ):
            raise ValueError("histogram buckets must be strictly increasing")
        self.bounds = bounds  # upper bounds, +Inf implicit
        super().__init__(name, help, labelnames, registry)

    def _new_state(self) -> _HistState:
        return _HistState(len(self.bounds) + 1)

    def observe(self, value: float) -> None:
        self._observe_state(self._default, value, aggregate=False)

    def get(self) -> "Dict[str, Any]":
        return self._read_state(self._default)

    def _observe_state(self, state: _HistState, value: float, aggregate: bool) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            for s in (
                (state, self._default)
                if aggregate and state is not self._default
                else (state,)
            ):
                s.buckets[idx] += 1
                s.sum += value
                s.count += 1

    def _inc_state(self, state: Any, amount: float, aggregate: bool) -> None:
        raise TypeError("inc() is not valid on a histogram")

    def _set_state(self, state: Any, value: float) -> None:
        raise TypeError("set() is not valid on a histogram")

    def _read_state(self, state: _HistState) -> "Dict[str, Any]":
        with self._lock:
            return self._copy_state(state)

    def _copy_state(self, state: _HistState) -> "Dict[str, Any]":
        # cumulative bucket counts, Prometheus-style
        cum: "List[int]" = []
        total = 0
        for c in state.buckets:
            total += c
            cum.append(total)
        return {
            "bounds": self.bounds,
            "buckets": cum,  # len(bounds)+1, last == count (+Inf)
            "sum": state.sum,
            "count": state.count,
        }


class Registry:
    """Named collection of metric families; renders and snapshots them."""

    def __init__(self) -> None:
        self._lock = lockcheck.lock("metrics.registry")
        self._metrics: "Dict[str, _Metric]" = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str) -> "Optional[_Metric]":
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> "List[_Metric]":
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every family."""
        lines: "List[str]" = []
        for m in self.metrics():
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for label_items, value in m._series():
                if m.kind == "histogram":
                    for bound, cum in zip(
                        list(value["bounds"]) + [float("inf")], value["buckets"]
                    ):
                        items = label_items + (("le", _fmt_value(bound)),)
                        lines.append(
                            f"{m.name}_bucket{_render_labels(items)} {cum}"
                        )
                    lines.append(
                        f"{m.name}_sum{_render_labels(label_items)} "
                        f"{_fmt_value(value['sum'])}"
                    )
                    lines.append(
                        f"{m.name}_count{_render_labels(label_items)} "
                        f"{value['count']}"
                    )
                else:
                    lines.append(
                        f"{m.name}{_render_labels(label_items)} "
                        f"{_fmt_value(value)}"
                    )
        return "\n".join(lines) + "\n"

    def collect(self) -> "List[Dict[str, Any]]":
        """Structured snapshot for the OTLP encoder (and tests)."""
        out: "List[Dict[str, Any]]" = []
        for m in self.metrics():
            out.append(
                {
                    "name": m.name,
                    "help": m.help,
                    "kind": m.kind,
                    "series": [
                        {"labels": dict(items), "value": value}
                        for items, value in m._series()
                    ],
                }
            )
        return out


REGISTRY = Registry()


def _get_or_create(
    cls: type, name: str, help: str, labelnames: "Sequence[str]", registry: "Optional[Registry]", **kw: Any
) -> Any:
    reg = registry if registry is not None else REGISTRY
    existing = reg.get(name)
    if existing is not None:
        if not isinstance(existing, cls) or existing.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered with a different "
                f"kind/labels"
            )
        return existing
    return cls(name, help, labelnames, registry=reg, **kw)


def counter(
    name: str, help: str, labelnames: "Sequence[str]" = (), registry: "Optional[Registry]" = None
) -> Counter:
    """Get-or-create a :class:`Counter` in ``registry`` (default global)."""
    return _get_or_create(Counter, name, help, labelnames, registry)


def gauge(
    name: str, help: str, labelnames: "Sequence[str]" = (), registry: "Optional[Registry]" = None
) -> Gauge:
    """Get-or-create a :class:`Gauge` in ``registry`` (default global)."""
    return _get_or_create(Gauge, name, help, labelnames, registry)


def histogram(
    name: str,
    help: str,
    labelnames: "Sequence[str]" = (),
    buckets: "Optional[Sequence[float]]" = None,
    registry: "Optional[Registry]" = None,
) -> Histogram:
    """Get-or-create a :class:`Histogram` in ``registry`` (default global)."""
    return _get_or_create(
        Histogram, name, help, labelnames, registry, buckets=buckets
    )


# ---------------------------------------------------------------------------
# text-exposition parser (round-trip tests + the tier-1 /metrics smoke check)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)(?: (?P<ts>-?[0-9]+))?$"
)
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"\s*(?:,|$)'
)


def _unescape_label_value(v: str) -> str:
    # single left-to-right scan: sequential str.replace would corrupt a
    # literal backslash followed by 'n' ('a\\nb' escapes to 'a\\\\nb'; the
    # naive '\\n'-first replace turns that into backslash+newline)
    out: "List[str]" = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v) and v[i + 1] in ('n', '\\', '"'):
            out.append({"n": "\n", "\\": "\\", '"': '"'}[v[i + 1]])
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_value(v: str) -> float:
    if v == "+Inf":
        return float("inf")
    if v == "-Inf":
        return float("-inf")
    return float(v)  # raises ValueError on garbage — the validator's job


def parse_text_exposition(text: str) -> "Dict[str, Dict[str, Any]]":
    """Strict parser for the Prometheus text format subset this module
    (and the native lighthouse endpoint) emits.

    Returns ``{family: {"type": ..., "help": ..., "samples":
    {(sample_name, ((label, value), ...)): float}}}``; raises
    ``ValueError`` on any malformed line — the tier-1 smoke check runs the
    whole scrape through this to catch label-escaping regressions.
    """
    families: "Dict[str, Dict[str, Any]]" = {}

    def family_for(sample_name: str) -> "Dict[str, Any]":
        for suffix in ("_bucket", "_sum", "_count", ""):
            base = sample_name[: -len(suffix)] if suffix else sample_name
            if base in families and (
                not suffix or families[base]["type"] == "histogram"
            ):
                return families[base]
        return families.setdefault(
            sample_name, {"type": "untyped", "help": "", "samples": {}}
        )

    for lineno, line in enumerate(text.split("\n"), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP ") :]
            name, _, help_text = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise ValueError(f"line {lineno}: bad HELP name {name!r}")
            families.setdefault(
                name, {"type": "untyped", "help": "", "samples": {}}
            )["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE ") :].split(" ")
            if len(parts) != 2 or not _NAME_RE.match(parts[0]) or parts[
                1
            ] not in ("counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE line {line!r}")
            families.setdefault(
                parts[0], {"type": "untyped", "help": "", "samples": {}}
            )["type"] = parts[1]
            continue
        if line.startswith("#"):
            continue  # comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        labels: "List[Tuple[str, str]]" = []
        raw = m.group("labels")
        if raw is not None:
            pos = 0
            while pos < len(raw):
                lm = _LABEL_PAIR_RE.match(raw, pos)
                if not lm:
                    raise ValueError(
                        f"line {lineno}: malformed labels {raw!r}"
                    )
                labels.append(
                    (lm.group("name"), _unescape_label_value(lm.group("value")))
                )
                pos = lm.end()
        try:
            value = _parse_value(m.group("value"))
        except ValueError as e:
            raise ValueError(f"line {lineno}: bad value in {line!r}") from e
        fam = family_for(m.group("name"))
        key = (m.group("name"), tuple(labels))
        if key in fam["samples"]:
            raise ValueError(f"line {lineno}: duplicate sample {key!r}")
        fam["samples"][key] = value
    return families


def quantile_from_histogram(
    families: "Dict[str, Dict[str, Any]]",
    name: str,
    q: float,
    labels: "Sequence[Tuple[str, str]]" = (),
) -> float:
    """Estimate the ``q`` quantile (0..1) of a parsed histogram family.

    Standard Prometheus upper-bound estimation: find the first bucket
    whose cumulative count reaches ``q * count`` and return its ``le``
    bound (conservative — the true value is at or below it; ``+Inf``
    degrades to the largest finite bound).  ``labels`` narrows to one
    child's series, exactly as rendered.  Raises ``KeyError`` for a
    missing family and ``ValueError`` for an empty histogram — a p99
    assertion against a histogram nobody observed must fail loudly, not
    return 0.
    """
    fam = families[name]
    want = tuple(sorted(labels))
    buckets: "List[Tuple[float, float]]" = []  # (le, cumulative count)
    total = 0.0
    for (sample, sample_labels), value in fam["samples"].items():
        rest = tuple(
            sorted((k, v) for k, v in sample_labels if k != "le")
        )
        if rest != want:
            continue
        if sample == f"{name}_bucket":
            le = dict(sample_labels).get("le", "")
            buckets.append(
                (float("inf") if le == "+Inf" else float(le), value)
            )
        elif sample == f"{name}_count":
            total = value
    if total <= 0 or not buckets:
        raise ValueError(f"histogram {name}{dict(want)} has no observations")
    buckets.sort()
    rank = q * total
    largest_finite = max(
        (le for le, _ in buckets if le != float("inf")), default=float("inf")
    )
    for le, cum in buckets:
        if cum >= rank:
            return largest_finite if le == float("inf") else le
    return largest_finite


# ---------------------------------------------------------------------------
# per-process HTTP scrape server (the per-manager surface)
# ---------------------------------------------------------------------------


class _MetricsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    registry: Registry  # injected per-server

    def log_message(self, fmt: str, *args: Any) -> None:  # quiet
        logger.debug("metrics http: " + fmt, *args)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404, "try /metrics")
            return
        try:
            body = self.registry.render().encode()
        except Exception as e:  # noqa: BLE001 - a scrape never kills training
            logger.warning("metrics render failed: %s", e)
            self.send_error(500, "render failed")
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsHTTPServer:
    """Tiny threaded scrape endpoint: ``GET /metrics`` on ``port``.

    ``port=0`` picks an ephemeral port (tests).  Serving runs on a daemon
    thread; ``close()`` stops it.
    """

    def __init__(self, port: int = 0, registry: "Optional[Registry]" = None) -> None:
        handler = type(
            "_BoundMetricsHandler",
            (_MetricsHandler,),
            {"registry": registry if registry is not None else REGISTRY},
        )
        self._server = ThreadingHTTPServer(("", port), handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.1),
            name="torchft_metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def address(self) -> str:
        return f"{socket.gethostname()}:{self.port}"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


_env_server: "Optional[MetricsHTTPServer]" = None
_env_server_lock = threading.Lock()


def maybe_serve_from_env() -> "Optional[MetricsHTTPServer]":
    """Start the process-wide scrape server when ``TORCHFT_METRICS_PORT``
    is set (idempotent — every Manager in the process calls this; the
    first one wins).  Port conflicts are logged, never raised: a taken
    metrics port must not take down training."""
    global _env_server
    port = env_int("TORCHFT_METRICS_PORT", 0, minimum=0)
    if not port:
        return None
    with _env_server_lock:
        if _env_server is not None:
            return _env_server
        try:
            _env_server = MetricsHTTPServer(port)
        except (OSError, ValueError) as e:
            logger.warning(
                "could not start metrics server on port %s: %s", port, e
            )
            return None
        return _env_server


# ---------------------------------------------------------------------------
# OTLP/HTTP metrics exporter (POST /v1/metrics, JSON encoding)
# ---------------------------------------------------------------------------


class OTLPMetricsExporter:
    """Periodic cumulative-snapshot push of a registry to an OTLP/HTTP
    collector, in the style of ``utils/otel.py``'s log exporter: daemon
    flush thread, same resource-attribute loading, same failure policy
    (failed posts drop with a warning and a ``dropped`` counter)."""

    def __init__(
        self,
        endpoint: str,
        registry: "Optional[Registry]" = None,
        resource_attributes: "Optional[Dict[str, Any]]" = None,
        service_name: str = "torchft_tpu",
        interval_s: float = 10.0,
        timeout_s: float = 5.0,
    ) -> None:
        from torchft_tpu.utils.otel import _kv_list, load_resource_attributes

        self._endpoint = endpoint.rstrip("/")
        if not self._endpoint.endswith("/v1/metrics"):
            self._endpoint += "/v1/metrics"
        self._registry = registry if registry is not None else REGISTRY
        if resource_attributes is None:
            resource_attributes = load_resource_attributes(service_name)
        attrs = {"service.name": service_name, **resource_attributes}
        self._resource = {"attributes": _kv_list(attrs)}
        self._interval_s = interval_s
        self._timeout_s = timeout_s
        self._stop = threading.Event()
        self.exported = 0  # successful posts
        self.dropped = 0  # failed posts
        self._thread = threading.Thread(
            target=self._run, name="otlp_metrics_exporter", daemon=True
        )
        self._thread.start()
        atexit.register(self._atexit_flush)

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            self.flush()

    def flush(self) -> bool:
        """Encode + post the current cumulative snapshot; True on 2xx."""
        from torchft_tpu.utils.otel import post_otlp

        try:
            post_otlp(self._endpoint, self.encode(), self._timeout_s)
            self.exported += 1
            return True
        except Exception as e:  # noqa: BLE001 - a sink never kills training
            self.dropped += 1
            logger.warning("OTLP metrics export failed: %s", e)
            return False

    def encode(self) -> bytes:
        """OTLP JSON ``resourceMetrics`` document for the current snapshot
        (cumulative temporality; counters are monotonic sums)."""
        from torchft_tpu.utils.otel import _kv_list

        now = str(time.time_ns())
        start = str(_START_NS)
        metrics_out: "List[Dict[str, Any]]" = []
        for fam in self._registry.collect():
            entry: "Dict[str, Any]" = {
                "name": fam["name"],
                "description": fam["help"],
            }
            if fam["kind"] == "histogram":
                points = []
                for s in fam["series"]:
                    v = s["value"]
                    # OTLP bucketCounts are per-bucket, not cumulative
                    cum = v["buckets"]
                    per = [c - p for c, p in zip(cum, [0] + cum[:-1])]
                    points.append(
                        {
                            "attributes": _kv_list(s["labels"]),
                            "startTimeUnixNano": start,
                            "timeUnixNano": now,
                            "count": str(v["count"]),
                            "sum": v["sum"],
                            "bucketCounts": [str(c) for c in per],
                            "explicitBounds": list(v["bounds"]),
                        }
                    )
                entry["histogram"] = {
                    "dataPoints": points,
                    "aggregationTemporality": 2,  # CUMULATIVE
                }
            else:
                points = [
                    {
                        "attributes": _kv_list(s["labels"]),
                        "startTimeUnixNano": start,
                        "timeUnixNano": now,
                        "asDouble": float(s["value"]),
                    }
                    for s in fam["series"]
                ]
                if fam["kind"] == "counter":
                    entry["sum"] = {
                        "dataPoints": points,
                        "aggregationTemporality": 2,
                        "isMonotonic": True,
                    }
                else:
                    entry["gauge"] = {"dataPoints": points}
            metrics_out.append(entry)
        doc = {
            "resourceMetrics": [
                {
                    "resource": self._resource,
                    "scopeMetrics": [
                        {
                            "scope": {"name": "torchft_tpu"},
                            "metrics": metrics_out,
                        }
                    ],
                }
            ]
        }
        return json.dumps(doc, default=str).encode()

    def _atexit_flush(self) -> None:
        if not self._stop.is_set():
            self.flush()

    def close(self) -> None:
        self._stop.set()
        try:
            atexit.unregister(self._atexit_flush)
        except Exception:  # noqa: BLE001 - interpreter-state dependent
            pass
        self._thread.join(timeout=self._timeout_s + 1.0)


_env_metrics_exporter: "Optional[OTLPMetricsExporter]" = None


def maybe_export_from_env() -> "Optional[OTLPMetricsExporter]":
    """Start the OTLP metrics push when ``TORCHFT_USE_OTEL`` is truthy
    (same gate and endpoint resolution as the log exporter:
    ``OTEL_EXPORTER_OTLP_METRICS_ENDPOINT``, else
    ``OTEL_EXPORTER_OTLP_ENDPOINT``, else the OTLP default)."""
    global _env_metrics_exporter
    if not env_bool("TORCHFT_USE_OTEL"):
        return None
    if _env_metrics_exporter is not None:
        return _env_metrics_exporter
    endpoint = (
        env_str("OTEL_EXPORTER_OTLP_METRICS_ENDPOINT")
        or env_str("OTEL_EXPORTER_OTLP_ENDPOINT")
        or "http://localhost:4318"
    )
    # runs at `import torchft_tpu`: a typo'd env var degrades to the
    # default inside env_float, never crashes training
    interval = env_float("TORCHFT_METRICS_EXPORT_INTERVAL_S", 10.0)
    _env_metrics_exporter = OTLPMetricsExporter(endpoint, interval_s=interval)
    return _env_metrics_exporter


# ---------------------------------------------------------------------------
# torchft instruments — the one place every exported metric is defined
# (docs/observability.md carries the rendered table; keep the two in sync)
# ---------------------------------------------------------------------------

QUORUM_DURATION = histogram(
    "torchft_quorum_duration_seconds",
    "Wall-clock seconds per FT protocol phase (quorum_wait/quorum_rpc/"
    "pg_configure/heal_send/heal_recv/host_sync/ring/commit)",
    ("replica_id", "phase"),
)
QUORUM_CHANGES = counter(
    "torchft_quorum_changes_total",
    "Quorum membership changes observed (PG reconfigures triggered)",
    ("replica_id",),
)
COMMITS = counter(
    "torchft_commits_total",
    "should_commit votes by outcome",
    ("replica_id", "result"),
)
ERRORS = counter(
    "torchft_errors_total",
    "Errors latched into the step protocol (report_error)",
    ("replica_id",),
)
HEALS = counter(
    "torchft_heals_total",
    "Live checkpoint transfers by direction (send=to peers, recv=healing)",
    ("replica_id", "direction"),
)
ALLREDUCES = counter(
    "torchft_allreduce_total",
    "Fault-tolerant allreduce submissions",
    ("replica_id",),
)
STEP = gauge(
    "torchft_step",
    "Current committed step of this replica",
    ("replica_id",),
)
PARTICIPANTS = gauge(
    "torchft_participants",
    "Live participant count of the current quorum",
    ("replica_id",),
)
PG_RECONFIGURES = counter(
    "torchft_pg_reconfigures_total",
    "Process-group configure() completions by transport",
    ("transport",),
)
PG_ABORTS = counter(
    "torchft_pg_aborts_total",
    "Process-group abort() calls by transport",
    ("transport",),
)
CHECKPOINT_BYTES = counter(
    "torchft_checkpoint_bytes_total",
    "Checkpoint payload bytes streamed by transport and direction",
    ("transport", "direction"),
)
CHECKPOINT_DURATION = histogram(
    "torchft_checkpoint_duration_seconds",
    "Checkpoint send/recv wall-clock seconds by transport and direction",
    ("transport", "direction"),
)
CHECKPOINT_RETRIES = counter(
    "torchft_checkpoint_retries_total",
    "Checkpoint fetch retries (sender not yet staged / transient errors)",
    ("transport",),
)
HEAL_INTO_FALLBACKS = counter(
    "torchft_heal_into_fallbacks_total",
    "Heal receives that could NOT reuse the retained leaf buffers "
    "(state_dict_fn failed/mismatched — the decode allocates fresh "
    "arrays; a nonzero rate means the zero-alloc heal path regressed)",
)
HEAL_FRAG_FAILOVERS = counter(
    "torchft_heal_frag_failovers_total",
    "Striped-heal fragments that failed over to another stripe source "
    "(dead source, budget expiry, or digest mismatch)",
)
HEAL_STRIPE_SOURCES = gauge(
    "torchft_heal_stripe_sources",
    "Stripe sources the most recent striped heal fetched across "
    "(1 = primary only)",
)
HEAL_WIRE_BYTES = counter(
    "torchft_heal_wire_bytes_total",
    "Striped-heal fragment bytes fetched, by mode (full vs delta — "
    "delta bytes scale with the changed-fragment count)",
    ("mode",),
)
HEAL_CHANGED_FRAGMENTS = gauge(
    "torchft_heal_changed_fragments",
    "Fragments the most recent delta heal actually fetched (digest "
    "diff vs the rejoiner's own state); equals the fragment count on "
    "a full heal",
)
PLAN_VERIFY_TOTAL = counter(
    "torchft_plan_verify_total",
    "Live topology plans validated at their commit point under "
    "TORCHFT_PLAN_VERIFY, by plane (reduction/serving/stripe) and "
    "verdict (accept/reject/error) — any reject is a synthesized plan "
    "that violated a named invariant (see tft-verify --scenario plan)",
    ("plane", "verdict"),
)
STORE_SPILL_BYTES = counter(
    "torchft_store_spill_bytes_total",
    "Fragment bytes newly written by the durable store spill path "
    "(dedup by digest: unchanged fragments cost zero — steady-state "
    "write amplification scales with the update delta)",
)
STORE_SPILL_FAILURES = counter(
    "torchft_store_spill_failures_total",
    "Spill attempts that failed and were skipped (the spill tier "
    "degrades — it never raises into or stalls a training step)",
)
STORE_RESTORE_BYTES = counter(
    "torchft_store_restore_bytes_total",
    "Wire bytes fetched by whole-fleet cold restore, by mode (delta "
    "restores reuse surviving local fragments and fetch only the diff)",
    ("mode",),
)
STORE_TORN_BLOBS = counter(
    "torchft_store_torn_blobs_total",
    "Store blob reads that failed sha256 digest verify (torn write or "
    "bit rot) — treated as missing so restore fails over, never served",
)
STORE_VERSIONS = gauge(
    "torchft_store_versions",
    "Durable store versions currently on this rank's disk after "
    "retirement under the TORCHFT_STORE_VERSIONS window",
)
DILOCO_SYNC_SECONDS = gauge(
    "torchft_diloco_last_sync_seconds",
    "Duration of the most recent DiLoCo fragment sync (perform_sync)",
    ("fragment",),
)
DILOCO_WIRE_BYTES = gauge(
    "torchft_diloco_last_wire_bytes",
    "Wire bytes of the most recent DiLoCo fragment allreduce (quantized "
    "actual when available, else payload bytes)",
    ("fragment",),
)
QUANT_CODEC_SECONDS = histogram(
    "torchft_quant_codec_seconds",
    "Quantized-collective codec wall per pipeline chunk by stage "
    "(quantize/reduce/dequant) and wire format (ops/collectives.py)",
    ("stage", "wire"),
)
QUANT_WIRE_SECONDS = histogram(
    "torchft_quant_wire_seconds",
    "Quantized-collective wire-op execution seconds per pipeline chunk "
    "by PG op (alltoall/allgather/send/recv/sendrecv), reduction-plan "
    "hop (flat, or intra.reduce/inter.exchange/inter.gather/intra.bcast "
    "on hierarchical plans) and wire format",
    ("op", "hop", "wire"),
)
QUANT_OVERLAP_EFFICIENCY = gauge(
    "torchft_quant_overlap_efficiency",
    "Codec/wire overlap achieved by the last quantized collective: "
    "(codec_s + wire_s - wall) / min(codec_s, wire_s), 1.0 = perfectly "
    "pipelined, 0.0 = fully serialized",
    ("wire",),
)
LAYOUT_EPOCH = gauge(
    "torchft_layout_epoch",
    "Active layout epoch of the online-parallelism-switching protocol "
    "(parallel/layout.py; monotone, bumped per committed switch)",
    ("replica_id",),
)
LAYOUT_SWITCHES = counter(
    "torchft_layout_switches_total",
    "Layout-switch commit rounds by outcome (committed = the whole "
    "fleet activated the staged layout; rolled_back = the epoch was "
    "burned and the old layout kept)",
    ("replica_id", "result"),
)
RESHARD_BYTES = counter(
    "torchft_reshard_bytes_total",
    "Bytes fetched from peers by the live-reshard slice-diff transfers "
    "(parallel/layout.py; only missing intervals cross the wire)",
    ("replica_id",),
)
FAULTS_INJECTED = counter(
    "torchft_faults_injected_total",
    "Chaos faults injected by site and action (utils/faults.py registry)",
    ("site", "action"),
)
RETRIES = counter(
    "torchft_retries_total",
    "RetryPolicy retries by operation (utils/retry.py)",
    ("op",),
)
RETRY_BACKOFF = histogram(
    "torchft_retry_backoff_seconds",
    "Backoff slept before each retry attempt, by operation",
    ("op",),
)
FLIGHT_DUMPS = counter(
    "torchft_flight_dumps_total",
    "Flight-recorder dumps written, by trigger "
    "(pg_abort/manager_error/signal/manual; utils/flightrecorder.py)",
    ("trigger",),
)
LOCK_CYCLES = counter(
    "torchft_lock_cycles_total",
    "Distinct lock-order cycles (potential deadlocks) observed by the "
    "TORCHFT_LOCKCHECK runtime detector (utils/lockcheck.py)",
    ("edge",),
)
LOCK_HOLD_OUTLIERS = counter(
    "torchft_lock_hold_outliers_total",
    "Lock holds exceeding TORCHFT_LOCKCHECK_HOLD_MS, by lock name "
    "(utils/lockcheck.py; straggler-origin telemetry)",
    ("name",),
)
SERVING_PUBLISHES = counter(
    "torchft_serving_versions_published_total",
    "Weight versions published into the serving tier by wire format "
    "(serving/publisher.py; f32 = raw, int8 = quantized payload)",
    ("wire",),
)
SERVING_PUBLISH_SECONDS = histogram(
    "torchft_serving_publish_seconds",
    "Wall seconds to encode + stage one published weight version "
    "(serving/publisher.py) by wire format",
    ("wire",),
)
SERVING_FETCH_SECONDS = histogram(
    "torchft_serving_fetch_seconds",
    "Weight-version fetch wall seconds by role (relay = tree node "
    "pulling from its parent, client = inference client fetch incl. "
    "failover)",
    ("role",),
)
SERVING_FETCH_BYTES = counter(
    "torchft_serving_fetch_bytes_total",
    "Bytes received by serving-tier fetches, by role (relay/client)",
    ("role",),
)
SERVING_FAILOVERS = counter(
    "torchft_serving_failovers_total",
    "Serving fetches that moved to another source after a failure "
    "(dead parent / killed server mid-fetch), by role",
    ("role",),
)
SERVING_PLAN_EPOCH = gauge(
    "torchft_serving_plan_epoch",
    "Distribution-tree plan epoch this process last adopted, by role "
    "(publisher/server/client; monotone — lags the lighthouse's "
    "torchft_lighthouse_serving_epoch only during a tree switch)",
    ("role",),
)
SERVING_TREE_DEPTH = gauge(
    "torchft_serving_tree_depth",
    "Depth of the adopted distribution tree (serving_plan max node "
    "depth; 0 = every server pulls the publisher directly)",
    (),
)
SERVING_VERSION = gauge(
    "torchft_serving_version",
    "Newest weight version this process holds/has published, by role",
    ("role",),
)
SERVING_WIRE_WAIT = counter(
    "torchft_serving_wire_wait_seconds_total",
    "Seconds serving-tier fetches slept to honor the WAN wire model "
    "(TORCHFT_WIRE_RTT_MS + TORCHFT_WIRE_GBPS across the "
    "TORCHFT_TOPOLOGY boundary; serving/wire.py), by source peer host — "
    "worst-K bounded tier (TORCHFT_LINK_TOPK names + 'other'); the "
    "unlabeled aggregate is the process total",
    ("peer",),
)
SERVING_RELAY_DECODE = histogram(
    "torchft_serving_relay_decode_seconds",
    "Seconds a serving relay spent deserializing pulled payload content "
    "per pull, by mode (serving/replica.py): flat = whole-payload "
    "store-and-forward decode, stream = cut-through passthrough — "
    "manifest-only, ~0 (fragments are verified opaque bytes, never "
    "decoded on the relay)",
    ("mode",),
)
SERVING_CUT_OCCUPANCY = gauge(
    "torchft_serving_cut_through_occupancy",
    "Pipeline occupancy of the last streamed relay pull: overlap of "
    "fragment wire time (UNION of the in-flight fetch intervals, so "
    "parallel fetches don't double-count) with verify/stage time, "
    "(wire_s + proc_s - wall_s) / min(wire_s, proc_s) clamped to "
    "[0, 1] — the serving twin of torchft_quant_overlap_efficiency "
    "(serving/replica.py)",
    (),
)
PG_WIRE_WAIT = counter(
    "torchft_pg_wire_wait_seconds_total",
    "Seconds ProcessGroupTCP sends slept to honor the WAN wire model "
    "(first-byte RTT + token-bucket debt on boundary-crossing messages; "
    "parallel/process_group.py), by peer host — worst-K bounded tier "
    "(TORCHFT_LINK_TOPK names + 'other'); the unlabeled aggregate is "
    "the process total",
    ("peer",),
)
LINK_GOODPUT = gauge(
    "torchft_link_goodput_bytes_per_s",
    "Passively measured link goodput by peer host and transfer plane "
    "(reduction/fragments/rpc; utils/linkstats.py) — worst-K WAN links "
    "only (TORCHFT_LINK_TOPK); fleet-local truth in "
    "torchft_link_pairs_tracked / torchft_link_goodput_min_bytes_per_s",
    ("peer", "plane"),
)
LINK_RTT_P99 = gauge(
    "torchft_link_rtt_p99_seconds",
    "Windowed p99 first-byte latency of a measured link by peer host "
    "and plane (TORCHFT_LINK_WINDOW samples; utils/linkstats.py) — "
    "worst-K WAN links only",
    ("peer", "plane"),
)
LINK_PAIRS = gauge(
    "torchft_link_pairs_tracked",
    "Links (peer, plane) in this process's full passive link table "
    "(worst-K of these export per-peer series)",
    (),
)
LINK_GOODPUT_MIN = gauge(
    "torchft_link_goodput_min_bytes_per_s",
    "Lowest measured WAN-link goodput in the full link table (one "
    "series at any fleet size — the aggregate under the worst-K tier)",
    (),
)
FRAG_HELD = gauge(
    "torchft_frag_held",
    "Fragments in this process's provenance version vector "
    "(checkpointing/provenance.py) — every fragment this holder has "
    "staged/verified/spilled, any payload family",
    (),
)
FRAG_HOPS = counter(
    "torchft_frag_hops_total",
    "Fragment transfers audited by the provenance plane, by transfer "
    "plane (serving/heal/restore) and digest verdict (ok / mismatch / "
    "torn) — a nonzero mismatch or torn count is a poisoned-fragment "
    "signal (triage with torchft-diagnose --fragment)",
    ("plane", "verdict"),
)
FRAG_STAMP_AGE = gauge(
    "torchft_frag_stamp_age_seconds",
    "Publish-stamp age of a held fragment at digest-refresh time, by "
    "frag id — worst-K stalest only (TORCHFT_FRAG_TOPK names + "
    "'other'); fleet per-fragment staleness on one clock lives in the "
    "lighthouse /fragments.json matrix",
    ("frag",),
)
FRAG_STAMP_AGE_MAX = gauge(
    "torchft_frag_stamp_age_max_seconds",
    "Oldest publish stamp across the full local provenance vector (one "
    "series at any fragment count — the aggregate under the worst-K "
    "tier)",
    (),
)
SERVING_STALENESS = histogram(
    "torchft_serving_staleness_seconds",
    "Serving staleness ledger: publish-stamp age of a weight version at "
    "the moment a node finished holding/fetching it, by role "
    "(publisher = encode+stage+advertise lag, server = publish-to-relay "
    "propagation, client = publish-to-consumer; stamps ride the payload "
    "manifest on the publisher's clock, so depth legs compare on ONE "
    "clock)",
    ("role",),
)
HA_FAILOVERS = counter(
    "torchft_ha_failovers_total",
    "Lighthouse RPCs that moved to another endpoint of the "
    "TORCHFT_LIGHTHOUSE list after a dead/unreachable peer "
    "(coordination-plane HA failover walk)",
    (),
)
HA_REDIRECTS = counter(
    "torchft_ha_redirects_total",
    "Lighthouse RPCs redirected to the current leader after a "
    "NOT_LEADER reply from a follower peer",
    (),
)
