from torchft_tpu.utils.faults import (
    FAULTS,
    FaultRegistry,
    FaultRule,
    InjectedConnectionDrop,
    InjectedFault,
)
from torchft_tpu.utils.futures import (
    context_timeout,
    future_timeout,
    future_wait,
)
from torchft_tpu.utils.logging import ReplicaLogger, log_event, recent_events
from torchft_tpu.utils.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsHTTPServer,
    counter,
    gauge,
    histogram,
    parse_text_exposition,
)
from torchft_tpu.utils.retry import RetryPolicy
from torchft_tpu.utils.rwlock import RWLock

__all__ = [
    "Counter",
    "FAULTS",
    "FaultRegistry",
    "FaultRule",
    "Gauge",
    "Histogram",
    "InjectedConnectionDrop",
    "InjectedFault",
    "MetricsHTTPServer",
    "REGISTRY",
    "RWLock",
    "RetryPolicy",
    "context_timeout",
    "counter",
    "future_timeout",
    "future_wait",
    "gauge",
    "histogram",
    "log_event",
    "parse_text_exposition",
    "recent_events",
    "ReplicaLogger",
]
