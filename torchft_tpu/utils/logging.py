"""Structured event logging for quorums / commits / errors.

Analog of the reference's structured-event pipeline (reference:
torchft/otel.py:42-86 and manager.py:659-669,848-858): three well-known
loggers receive one record per protocol event, each carrying
``extra={job_id, replica_id, rank, quorum_id, step, ...}``.  OTLP export is
out of scope for this environment (zero egress); the pipeline here has
three sinks:

- stdlib logging with the extras rendered inline;
- an in-memory ring of recent events that the lighthouse dashboard and
  tests can inspect;
- a **persistent JSONL file** (the crash-durable sink — an FT system's
  logs matter most when the process dies): set ``TORCHFT_EVENTS_FILE`` to
  a path and every event is appended as one JSON line, flushed per event,
  with size-based rotation to ``<path>.1`` at ``TORCHFT_EVENTS_MAX_BYTES``
  (default 16 MiB).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Deque, Dict, Optional, TextIO

_EVENT_RING_SIZE = 256

_quorum_logger = logging.getLogger("torchft_quorums")
_commit_logger = logging.getLogger("torchft_commits")
_error_logger = logging.getLogger("torchft_errors")

_lock = threading.Lock()
_recent_events: Deque[Dict[str, Any]] = collections.deque(maxlen=_EVENT_RING_SIZE)


_LOGGERS = {
    "quorum": _quorum_logger,
    "commit": _commit_logger,
    "error": _error_logger,
}


class _FileExporter:
    """Append-per-event JSONL writer with size-based rotation.

    Flushes after every event: a SIGKILLed replica must leave its last
    quorum/commit/error on disk (reference's OTLP exporter flushes per
    batch for the same reason, torchft/otel.py:42-86).
    """

    def __init__(self, path: str, max_bytes: int) -> None:
        self._path = path
        self._max_bytes = max_bytes
        self._fh: "Optional[TextIO]" = None

    def write(self, record: "Dict[str, Any]") -> None:
        try:
            if self._fh is None:
                self._fh = open(self._path, "a", encoding="utf-8")
            elif self._stale():
                # another process rotated the shared file out from under us
                # (WatchedFileHandler pattern): reopen before writing so we
                # never keep appending to the rotated inode
                self._fh.close()
                self._fh = open(self._path, "a", encoding="utf-8")
            if self._fh.tell() > self._max_bytes:
                self._fh.close()
                self._fh = None
                # racing rotators: os.replace is atomic, and the loser's
                # reopen lands on the fresh file via the _stale() check
                os.replace(self._path, self._path + ".1")
                self._fh = open(self._path, "a", encoding="utf-8")
            json.dump(record, self._fh, default=str)
            self._fh.write("\n")
            self._fh.flush()
        except OSError as e:  # never take down training for a log sink
            logging.getLogger(__name__).warning(
                "event file write failed (%s): %s", self._path, e
            )

    def _stale(self) -> bool:
        assert self._fh is not None
        try:
            disk = os.stat(self._path)
        except FileNotFoundError:
            return True
        ours = os.fstat(self._fh.fileno())
        return (disk.st_ino, disk.st_dev) != (ours.st_ino, ours.st_dev)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


_exporter: "Optional[_FileExporter]" = None
_exporter_env: "Optional[str]" = None  # env value the exporter was built for


def _file_exporter() -> "Optional[_FileExporter]":
    """Resolve the JSONL exporter from ``TORCHFT_EVENTS_FILE`` (re-resolved
    when the env value changes, so tests and launchers can redirect)."""
    global _exporter, _exporter_env
    path = os.environ.get("TORCHFT_EVENTS_FILE") or None
    if path != _exporter_env:
        if _exporter is not None:
            _exporter.close()
        _exporter = (
            _FileExporter(
                path,
                int(os.environ.get("TORCHFT_EVENTS_MAX_BYTES", 16 * 1024 * 1024)),
            )
            if path
            else None
        )
        _exporter_env = path
    return _exporter


def log_event(kind: str, message: str, **extra: Any) -> None:
    """Record a structured protocol event (kind in {quorum, commit, error})."""
    if kind not in _LOGGERS:
        raise ValueError(f"unknown event kind {kind!r}, expected one of {sorted(_LOGGERS)}")
    record = {"kind": kind, "message": message, **extra}
    with _lock:
        _recent_events.append(record)
        exporter = _file_exporter()
        if exporter is not None:
            exporter.write({"ts": time.time(), **record})
    logger = _LOGGERS[kind]
    rendered = " ".join(f"{k}={v}" for k, v in extra.items())
    if kind == "error":
        logger.error("%s %s", message, rendered)
    else:
        logger.info("%s %s", message, rendered)


def recent_events() -> "list[Dict[str, Any]]":
    with _lock:
        return list(_recent_events)


class ReplicaLogger:
    """Prefixes log lines with ``[replica_id/rank - step N]``.

    Analog of reference torchft/manager.py:991-1008.
    """

    def __init__(self, manager: Any, replica_id: str, rank: int) -> None:
        self._logger = logging.getLogger("torchft_tpu.manager")
        self._manager = manager
        self._replica_id = replica_id
        self._rank = rank

    def _prefix(self) -> str:
        return f"[{self._replica_id}/{self._rank} - step {self._manager.current_step()}]"

    def info(self, msg: str) -> None:
        self._logger.info("%s %s", self._prefix(), msg)

    def warning(self, msg: str) -> None:
        self._logger.warning("%s %s", self._prefix(), msg)

    def exception(self, msg: str) -> None:
        self._logger.exception("%s %s", self._prefix(), msg)
