"""Structured event logging for quorums / commits / errors / aborts.

Analog of the reference's structured-event pipeline (reference:
torchft/otel.py:42-86 and manager.py:659-669,848-858).  The reference's
OTEL layer is an exporter *interface* (a Tee of console + OTLP sinks);
this module mirrors that shape: ``log_event`` fans every record out to a
registry of :class:`EventExporter` objects.  OTLP itself is out of scope
in a zero-egress environment, but the seam is what a deployment needs —
``register_exporter`` installs any custom sink without monkeypatching.

Built-in exporters:

- :class:`RingExporter` — in-memory ring of recent events the lighthouse
  dashboard and tests inspect (always installed; ``recent_events()``).
- :class:`JSONLFileExporter` — the crash-durable sink (an FT system's
  logs matter most when the process dies): set ``TORCHFT_EVENTS_FILE``
  to a path and every event is appended as one JSON line, flushed per
  event, with size-based rotation to ``<path>.1`` at
  ``TORCHFT_EVENTS_MAX_BYTES`` (default 16 MiB).  Auto-installed from
  the env var.

Every record additionally lands on stdlib logging with the extras
rendered inline (the reference's console leg of the Tee).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from abc import ABC, abstractmethod
from typing import Any, Callable, Deque, Dict, List, Optional, TextIO

def _event_ring_size() -> int:
    """Ring capacity from ``TORCHFT_EVENTS_RING`` (default 256).  Read at
    import (the ring is a module singleton) — set the env before the first
    ``import torchft_tpu`` to size it."""
    from torchft_tpu.utils.env import env_int

    return env_int("TORCHFT_EVENTS_RING", 256)


_EVENT_RING_SIZE = _event_ring_size()

_LOGGERS = {
    "quorum": logging.getLogger("torchft_quorums"),
    "commit": logging.getLogger("torchft_commits"),
    "error": logging.getLogger("torchft_errors"),
    "abort": logging.getLogger("torchft_aborts"),
    # telemetry-layer kinds: live checkpoint transfer and PG membership
    # reconfiguration (mirror _SEVERITY in utils/otel.py when extending)
    "heal": logging.getLogger("torchft_heals"),
    "reconfigure": logging.getLogger("torchft_reconfigures"),
    # chaos layer: every injected fault (utils/faults.py)
    "fault": logging.getLogger("torchft_faults"),
    # online parallelism switching: layout plans, reshard staging,
    # fleet-wide commit/rollback (parallel/layout.py)
    "layout": logging.getLogger("torchft_layouts"),
}

_lock = threading.Lock()


class EventExporter(ABC):
    """One sink in the event pipeline (reference otel.py:42-86 exporter
    shape).  ``export`` receives every structured record; exceptions are
    swallowed by the pipeline (a sink must never take down training) but
    logged.  ``close`` releases resources; an exporter may be registered
    and unregistered at runtime."""

    @abstractmethod
    def export(self, record: "Dict[str, Any]") -> None: ...

    def close(self) -> None:  # noqa: B027 - optional hook
        pass


class RingExporter(EventExporter):
    """Bounded in-memory ring of the most recent events.

    Internally locked: exports arrive from any thread (the pipeline calls
    exporters outside its own lock for re-entrancy) while readers snapshot
    — iterating a deque concurrently with appends raises RuntimeError."""

    def __init__(self, maxlen: int = _EVENT_RING_SIZE) -> None:
        self._events: "Deque[Dict[str, Any]]" = collections.deque(maxlen=maxlen)
        self._ring_lock = threading.Lock()

    def export(self, record: "Dict[str, Any]") -> None:
        with self._ring_lock:
            self._events.append(record)

    def events(self) -> "List[Dict[str, Any]]":
        with self._ring_lock:
            return list(self._events)

    def clear(self) -> None:
        with self._ring_lock:
            self._events.clear()


class CallbackExporter(EventExporter):
    """Adapter: wrap a plain callable as an exporter (the cheapest way for
    user code to tap the event stream)."""

    def __init__(self, fn: "Callable[[Dict[str, Any]], None]") -> None:
        self._fn = fn

    def export(self, record: "Dict[str, Any]") -> None:
        self._fn(record)


class JSONLFileExporter(EventExporter):
    """Append-per-event JSONL writer with size-based rotation.

    Flushes after every event: a SIGKILLed replica must leave its last
    quorum/commit/error on disk (reference's OTLP exporter flushes per
    batch for the same reason, torchft/otel.py:42-86).
    """

    def __init__(self, path: str, max_bytes: int = 16 * 1024 * 1024) -> None:
        self._path = path
        self._max_bytes = max_bytes
        self._fh: "Optional[TextIO]" = None
        # exports may arrive from multiple threads (the pipeline calls
        # exporters outside its own lock to allow re-entrancy)
        self._write_lock = threading.Lock()

    def export(self, record: "Dict[str, Any]") -> None:
        with self._write_lock:
            self._export_locked(record)

    def _export_locked(self, record: "Dict[str, Any]") -> None:
        try:
            if self._fh is None:
                self._fh = open(self._path, "a", encoding="utf-8")
            elif self._stale():
                # another process rotated the shared file out from under us
                # (WatchedFileHandler pattern): reopen before writing so we
                # never keep appending to the rotated inode
                self._fh.close()
                self._fh = open(self._path, "a", encoding="utf-8")
            if self._fh.tell() > self._max_bytes:
                self._fh.close()
                self._fh = None
                # racing rotators: os.replace is atomic, and the loser's
                # reopen lands on the fresh file via the _stale() check
                os.replace(self._path, self._path + ".1")
                self._fh = open(self._path, "a", encoding="utf-8")
            json.dump(record, self._fh, default=str)
            self._fh.write("\n")
            self._fh.flush()
        except OSError as e:  # never take down training for a log sink
            logging.getLogger(__name__).warning(
                "event file write failed (%s): %s", self._path, e
            )

    def _stale(self) -> bool:
        assert self._fh is not None
        try:
            disk = os.stat(self._path)
        except FileNotFoundError:
            return True
        ours = os.fstat(self._fh.fileno())
        return (disk.st_ino, disk.st_dev) != (ours.st_ino, ours.st_dev)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


# --- exporter registry ------------------------------------------------------

_ring = RingExporter()
_registered: "List[EventExporter]" = []
_env_exporter: "Optional[JSONLFileExporter]" = None
_env_exporter_path: "Optional[str]" = None  # env value it was built for


def register_exporter(exporter: EventExporter) -> EventExporter:
    """Install an exporter into the pipeline; returns it (for later
    :func:`unregister_exporter`).  No monkeypatching required."""
    with _lock:
        _registered.append(exporter)
    return exporter


def unregister_exporter(exporter: EventExporter) -> None:
    """Remove (and close) a previously registered exporter."""
    with _lock:
        if exporter in _registered:
            _registered.remove(exporter)
    exporter.close()


def _env_jsonl_exporter() -> "Optional[JSONLFileExporter]":
    """Resolve the JSONL exporter from ``TORCHFT_EVENTS_FILE`` (re-resolved
    when the env value changes, so tests and launchers can redirect)."""
    from torchft_tpu.utils.env import env_int, env_str

    global _env_exporter, _env_exporter_path
    path = env_str("TORCHFT_EVENTS_FILE") or None
    if path != _env_exporter_path:
        if _env_exporter is not None:
            _env_exporter.close()
        _env_exporter = (
            JSONLFileExporter(
                path,
                env_int("TORCHFT_EVENTS_MAX_BYTES", 16 * 1024 * 1024, minimum=0),
            )
            if path
            else None
        )
        _env_exporter_path = path
    return _env_exporter


def log_event(kind: str, message: str, **extra: Any) -> None:
    """Record a structured protocol event
    (kind in {quorum, commit, error, abort, heal, reconfigure})."""
    if kind not in _LOGGERS:
        raise ValueError(f"unknown event kind {kind!r}, expected one of {sorted(_LOGGERS)}")
    record = {"ts": time.time(), "kind": kind, "message": message, **extra}
    # Snapshot the sink list under the lock, but call export() OUTSIDE it:
    # a custom exporter is allowed to re-enter this module (recent_events,
    # even log_event) without deadlocking.  Each exporter handles its own
    # thread safety (JSONLFileExporter serializes internally; the ring's
    # deque append is atomic).
    with _lock:
        sinks: "List[EventExporter]" = [_ring]
        env = _env_jsonl_exporter()
        if env is not None:
            sinks.append(env)
        sinks.extend(_registered)
    for sink in sinks:
        try:
            sink.export(record)
        except Exception as e:  # noqa: BLE001 - a sink never kills training
            logging.getLogger(__name__).warning(
                "event exporter %r failed: %s", type(sink).__name__, e
            )
    logger = _LOGGERS[kind]
    rendered = " ".join(f"{k}={v}" for k, v in extra.items())
    if kind in ("error", "abort"):
        logger.error("%s %s", message, rendered)
    else:
        logger.info("%s %s", message, rendered)


def recent_events() -> "list[Dict[str, Any]]":
    with _lock:
        return _ring.events()


class ReplicaLogger:
    """Prefixes log lines with ``[replica_id/rank - step N]``.

    Analog of reference torchft/manager.py:991-1008.
    """

    def __init__(self, manager: Any, replica_id: str, rank: int) -> None:
        self._logger = logging.getLogger("torchft_tpu.manager")
        self._manager = manager
        self._replica_id = replica_id
        self._rank = rank

    def _prefix(self) -> str:
        return f"[{self._replica_id}/{self._rank} - step {self._manager.current_step()}]"

    def info(self, msg: str) -> None:
        self._logger.info("%s %s", self._prefix(), msg)

    def warning(self, msg: str) -> None:
        self._logger.warning("%s %s", self._prefix(), msg)

    def exception(self, msg: str) -> None:
        self._logger.exception("%s %s", self._prefix(), msg)
