"""Structured event logging for quorums / commits / errors.

Analog of the reference's structured-event pipeline (reference:
torchft/otel.py:42-86 and manager.py:659-669,848-858): three well-known
loggers receive one record per protocol event, each carrying
``extra={job_id, replica_id, rank, quorum_id, step, ...}``.  OTLP export is
out of scope for this environment (zero egress); the pipeline here writes
structured records to stdlib logging with the extras rendered inline, and an
in-memory ring of recent events that the lighthouse dashboard and tests can
inspect.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Any, Deque, Dict

_EVENT_RING_SIZE = 256

_quorum_logger = logging.getLogger("torchft_quorums")
_commit_logger = logging.getLogger("torchft_commits")
_error_logger = logging.getLogger("torchft_errors")

_lock = threading.Lock()
_recent_events: Deque[Dict[str, Any]] = collections.deque(maxlen=_EVENT_RING_SIZE)


_LOGGERS = {
    "quorum": _quorum_logger,
    "commit": _commit_logger,
    "error": _error_logger,
}


def log_event(kind: str, message: str, **extra: Any) -> None:
    """Record a structured protocol event (kind in {quorum, commit, error})."""
    if kind not in _LOGGERS:
        raise ValueError(f"unknown event kind {kind!r}, expected one of {sorted(_LOGGERS)}")
    record = {"kind": kind, "message": message, **extra}
    with _lock:
        _recent_events.append(record)
    logger = _LOGGERS[kind]
    rendered = " ".join(f"{k}={v}" for k, v in extra.items())
    if kind == "error":
        logger.error("%s %s", message, rendered)
    else:
        logger.info("%s %s", message, rendered)


def recent_events() -> "list[Dict[str, Any]]":
    with _lock:
        return list(_recent_events)


class ReplicaLogger:
    """Prefixes log lines with ``[replica_id/rank - step N]``.

    Analog of reference torchft/manager.py:991-1008.
    """

    def __init__(self, manager: Any, replica_id: str, rank: int) -> None:
        self._logger = logging.getLogger("torchft_tpu.manager")
        self._manager = manager
        self._replica_id = replica_id
        self._rank = rank

    def _prefix(self) -> str:
        return f"[{self._replica_id}/{self._rank} - step {self._manager.current_step()}]"

    def info(self, msg: str) -> None:
        self._logger.info("%s %s", self._prefix(), msg)

    def warning(self, msg: str) -> None:
        self._logger.warning("%s %s", self._prefix(), msg)

    def exception(self, msg: str) -> None:
        self._logger.exception("%s %s", self._prefix(), msg)
