"""Per-process flight recorder: the blackbox a postmortem replays.

torchft_tpu's whole value is surviving per-step failures, but a failure
that *degrades* a run is diagnosed from whatever the dying/wedged process
left behind.  Before this module that evidence was fragmented: a one-off
``_flight`` dict inside ``ProcessGroupTCP`` (dumped only as a log event),
the event ring, and per-signal metrics.  Both Prime's PCCL report and
"Reliable and Resilient Collective Communication Library for LLM Training
and Serving" (PAPERS.md) treat the in-flight-op blackbox as a first-class
subsystem of a fault-tolerant collective stack — this module is that
subsystem:

- a **lock-cheap ring** of structured records (``op``, ``status``,
  ``start_ns``/``end_ns``, plus whatever context the site supplies:
  ``step``, ``quorum_id``, ``replica_id``, ``attempt``, ``fault``,
  transfer bytes/peers).  Hot-path budget: ~2 us per :func:`record`
  (same bar as the metrics layer's ``observe``), enforced by a unit
  test;
- **in-flight op tracking** (:meth:`FlightRecorder.start` →
  :class:`FlightOp`): the op a thread is *currently blocked inside* is
  exactly what a wedged-collective postmortem needs; open ops appear in
  every snapshot/dump with ``status="inflight"``.  This subsumes the old
  ``ProcessGroupTCP._flight`` dict;
- a **crash-durable dump**: :func:`dump` appends a meta line plus the
  full ring snapshot as JSONL to ``TORCHFT_FLIGHT_FILE``, fsync-free but
  flushed, so a SIGKILL one instruction later still leaves the file
  parseable.  Triggers wired through the stack: process-group abort and
  collective failure (parallel/process_group.py), unhandled manager
  errors (manager.py ``report_error``), fatal signals
  (SIGTERM/SIGABRT, installed when ``TORCHFT_FLIGHT_FILE`` is set), and
  on demand.  Each written dump increments
  ``torchft_flight_dumps_total{trigger}``.

``python -m torchft_tpu.diagnose`` merges N replicas' dumps (plus
``TORCHFT_EVENTS_FILE`` logs) into one cross-replica timeline and flags
the likely culprit — see docs/observability.md "post-mortem workflow".

Env knobs: ``TORCHFT_FLIGHT_FILE`` (dump path; unset = dumps are no-ops),
``TORCHFT_FLIGHT_RING`` (ring capacity, default 512),
``TORCHFT_FLIGHT_MAX_BYTES`` (rotate the dump file to ``<path>.1`` past
this size, default 64 MiB).

Failure policy matches every telemetry surface in this package: the
recorder must never take down (or mask an error in) training — dump
failures log and return ``None``.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import signal
import time
from typing import Any, Dict, Iterator, List, Optional

from torchft_tpu.utils import lockcheck
from torchft_tpu.utils.env import env_int, env_str

logger = logging.getLogger(__name__)

__all__ = [
    "env_int",  # re-export: moved to utils/env.py (PR 4), kept for compat
    "FlightOp",
    "FlightRecorder",
    "RECORDER",
    "record",
    "start",
    "track",
    "snapshot",
    "dump",
    "dump_path",
    "install_signal_hooks",
    "register_companion_dump",
]

_DEFAULT_RING = 512


def _ring_capacity() -> int:
    return env_int("TORCHFT_FLIGHT_RING", _DEFAULT_RING)


class FlightOp:
    """Handle for one in-flight operation.

    Created by :meth:`FlightRecorder.start`; the owning thread (and any
    helper threads, e.g. a PG's sender thread) call :meth:`update` /
    :meth:`add_bytes` as the transfer progresses, then exactly one caller
    :meth:`finish`\\ es it — writing the completed record into the ring.
    All methods are thread-safe and idempotent-on-finish (a double finish
    is a no-op returning the already-finished record).
    """

    __slots__ = ("_recorder", "_fields", "_lock", "_done")

    def __init__(self, recorder: "FlightRecorder", fields: "Dict[str, Any]") -> None:
        self._recorder = recorder
        self._fields = fields
        self._lock = lockcheck.lock("flightrecorder.flight_op")
        self._done = False

    def update(self, **fields: Any) -> None:
        """Merge transfer state (peer, tag, bytes, deadline...) into the op."""
        with self._lock:
            if not self._done:
                self._fields.update(fields)

    def add_bytes(self, nbytes: int) -> None:
        """Accumulate transfer progress into ``bytes_done``."""
        with self._lock:
            if not self._done:
                f = self._fields
                f["bytes_done"] = f.get("bytes_done", 0) + nbytes

    def finish(self, status: str = "ok", **fields: Any) -> "Dict[str, Any]":
        """Complete the op: stamp ``end_ns``/``status``, move the record
        from the open set into the ring.  Returns the completed record."""
        with self._lock:
            if self._done:
                return dict(self._fields)
            self._done = True
            self._fields.update(fields)
            self._fields["status"] = status
            self._fields["end_ns"] = time.time_ns()
            rec = dict(self._fields)
        self._recorder._complete(self, rec)
        return rec

    def snapshot(self, blocking: bool = True) -> "Optional[Dict[str, Any]]":
        """Copy of the op's fields; with ``blocking=False`` (the
        signal-handler path) returns None instead of risking a deadlock
        on a lock the interrupted thread holds."""
        if blocking:
            self._lock.acquire()
        elif not self._lock.acquire(timeout=0.05):
            return None
        try:
            return dict(self._fields)
        finally:
            self._lock.release()


class FlightRecorder:
    """Bounded ring of structured flight records + open-op registry."""

    def __init__(self, capacity: "Optional[int]" = None) -> None:
        cap = capacity if capacity is not None else _ring_capacity()
        self._cap = max(int(cap), 1)
        self._ring: "List[Optional[Dict[str, Any]]]" = [None] * self._cap
        self._idx = 0  # total records ever written (monotone)
        self._lock = lockcheck.lock("flightrecorder.ring")
        self._open: "Dict[int, FlightOp]" = {}
        self._dump_lock = lockcheck.lock("flightrecorder.dump")

    # -- hot path ----------------------------------------------------------

    def record(
        self,
        op: str,
        status: str = "ok",
        start_ns: "Optional[int]" = None,
        end_ns: "Optional[int]" = None,
        **fields: Any,
    ) -> None:
        """Append one completed record.  ~1 us: one dict build, one lock,
        one slot assignment — safe on the allreduce hot path."""
        now = time.time_ns()
        rec = {
            "op": op,
            "status": status,
            "start_ns": start_ns if start_ns is not None else now,
            "end_ns": end_ns if end_ns is not None else now,
            **fields,
        }
        with self._lock:
            self._ring[self._idx % self._cap] = rec
            self._idx += 1

    # -- in-flight ops -----------------------------------------------------

    def start(self, op: str, **fields: Any) -> FlightOp:
        """Open an in-flight op; it appears in snapshots/dumps as
        ``status="inflight"`` until :meth:`FlightOp.finish`."""
        rec = {"op": op, "status": "inflight", "start_ns": time.time_ns(), **fields}
        handle = FlightOp(self, rec)
        with self._lock:
            self._open[id(handle)] = handle
        return handle

    def _complete(self, handle: FlightOp, rec: "Dict[str, Any]") -> None:
        with self._lock:
            self._open.pop(id(handle), None)
            self._ring[self._idx % self._cap] = rec
            self._idx += 1

    # -- introspection -----------------------------------------------------

    def snapshot(self, blocking: bool = True) -> "List[Dict[str, Any]]":
        """Completed records (oldest first) followed by open ops.

        ``blocking=False`` is the signal-handler path: the handler runs ON
        the interrupted thread, which may be holding ``self._lock`` inside
        ``record()`` — a blocking acquire there would self-deadlock the
        dying process.  Try briefly, then read unlocked: ring slots are
        replaced wholesale (a read sees the old or new dict, never a torn
        one), which is exactly good enough for a last-gasp dump."""
        if blocking:
            self._lock.acquire()
            acquired = True
        else:
            acquired = self._lock.acquire(timeout=0.25)
        try:
            idx, cap = self._idx, self._cap
            if idx <= cap:
                ring = [r for r in self._ring[:idx] if r is not None]
            else:
                cut = idx % cap
                ring = [
                    r for r in self._ring[cut:] + self._ring[:cut] if r is not None
                ]
            open_ops = list(self._open.values())
        finally:
            if acquired:
                self._lock.release()
        out = [dict(r) for r in ring]
        for o in open_ops:
            snap = o.snapshot(blocking=blocking)
            if snap is not None:
                out.append(snap)
        return out

    def total_recorded(self) -> int:
        with self._lock:
            return self._idx

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self._cap
            self._idx = 0
            self._open.clear()

    # -- crash-durable dump ------------------------------------------------

    def dump(
        self,
        reason: str,
        trigger: str = "manual",
        path: "Optional[str]" = None,
        blocking: bool = True,
    ) -> "Optional[str]":
        """Append a dump (meta line + ring snapshot, one JSON object per
        line) to ``path`` or ``TORCHFT_FLIGHT_FILE``.  Returns the path
        written, or None when no sink is configured / the write failed —
        never raises (the recorder must never mask the error that
        triggered it).  ``blocking=False`` is for signal handlers: every
        lock is acquired with a short timeout so a handler running on a
        thread that already holds one cannot self-deadlock."""
        target = path or env_str("TORCHFT_FLIGHT_FILE") or None
        if target is None:
            return None
        records = self.snapshot(blocking=blocking)
        meta = {
            "flight": "meta",
            "reason": reason,
            "trigger": trigger,
            "ts": time.time(),
            "pid": os.getpid(),
            "records": len(records),
        }
        if blocking:
            self._dump_lock.acquire()
            have_dump_lock = True
        else:
            # best effort: a torn interleaved dump beats a wedged death
            have_dump_lock = self._dump_lock.acquire(timeout=0.25)
        try:
            # Size-based rotation (same policy as the events sink): a run
            # flapping for hours writes one full-ring snapshot per
            # trigger, and an unbounded append could fill the disk out
            # from under training.
            try:
                if os.path.getsize(target) > env_int(
                    "TORCHFT_FLIGHT_MAX_BYTES", 64 * 1024 * 1024, minimum=4096
                ):
                    os.replace(target, target + ".1")
            except OSError:
                pass  # missing file / rotation race: append below anyway
            with open(target, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(meta, default=str) + "\n")
                for rec in records:
                    fh.write(
                        json.dumps({"flight": "rec", **rec}, default=str) + "\n"
                    )
                fh.flush()
        except OSError as e:
            logger.warning("flight dump to %s failed: %s", target, e)
            return None
        finally:
            if have_dump_lock:
                self._dump_lock.release()
        try:
            from torchft_tpu.utils import metrics as _metrics

            _metrics.FLIGHT_DUMPS.labels(trigger=trigger).inc()
        except Exception:  # noqa: BLE001 - accounting never masks the dump
            logger.exception("flight dump metric failed")
        # Companion rings (e.g. the fragment-provenance hop ring) dump
        # alongside every PROCESS-recorder dump so one trigger — signal,
        # abort, manager error — leaves the whole postmortem evidence set
        # next to each other on disk.  Private recorders don't cascade.
        if self is RECORDER:
            for fn in list(_companion_dumps):
                try:
                    fn(reason, trigger, blocking, target)
                except Exception:  # noqa: BLE001 - companions never mask
                    logger.exception("companion flight dump failed")
        return target


#: Callables ``fn(reason, trigger, blocking, target)`` fired after every
#: successful dump of the process-wide ``RECORDER`` (never of private
#: rings) — subsystems with their own bounded rings register here so a
#: crash dump carries their evidence too (checkpointing/provenance.py).
_companion_dumps: "List[Any]" = []


def register_companion_dump(fn: Any) -> None:
    """Register a companion dump hook (idempotent)."""
    if fn not in _companion_dumps:
        _companion_dumps.append(fn)


#: The process-wide recorder every production site feeds.
RECORDER = FlightRecorder()

# module-level shorthands (the form the production call sites use)
record = RECORDER.record
start = RECORDER.start
snapshot = RECORDER.snapshot
dump = RECORDER.dump


@contextlib.contextmanager
def track(op: str, **fields: Any) -> "Iterator[FlightOp]":
    """Scope an in-flight op: finish ``ok`` on normal exit, ``error``
    (with the exception's repr) when the body raises.  The yielded
    :class:`FlightOp` takes mid-flight ``update``/``add_bytes`` calls."""
    flight = RECORDER.start(op, **fields)
    try:
        yield flight
    except BaseException as e:
        flight.finish("error", error=repr(e))
        raise
    flight.finish("ok")


def dump_path() -> "Optional[str]":
    """The configured dump sink, or None (dumps are then no-ops)."""
    return env_str("TORCHFT_FLIGHT_FILE") or None


# ---------------------------------------------------------------------------
# fatal-signal hook
# ---------------------------------------------------------------------------

_prev_handlers: "Dict[int, Any]" = {}
_hooks_installed = False


def _on_fatal_signal(signum: int, frame: Any) -> None:
    # Non-blocking: the handler runs ON the interrupted thread, which may
    # hold a recorder lock mid-record — a blocking dump would swallow the
    # signal and wedge the process instead of letting it die.
    RECORDER.dump(f"fatal signal {signum}", trigger="signal", blocking=False)
    prev = _prev_handlers.get(signum)
    if prev is signal.SIG_IGN:
        return  # the process deliberately ignores this signal; keep doing so
    if callable(prev):
        prev(signum, frame)
    else:
        # SIG_DFL / unknown: restore the default disposition and re-deliver
        # so the process still dies with the signal's semantics (exit code,
        # core dump)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_signal_hooks(signals: "Optional[List[int]]" = None) -> bool:
    """Dump the flight ring on fatal signals (SIGTERM/SIGABRT by default),
    then chain to the previous handler (or re-deliver the default).  Only
    installable from the main thread; returns True when installed."""
    global _hooks_installed
    if _hooks_installed:
        return True
    sigs = signals if signals is not None else [signal.SIGTERM, signal.SIGABRT]
    try:
        for s in sigs:
            _prev_handlers[s] = signal.signal(s, _on_fatal_signal)
    except ValueError:
        # not the main thread: the embedding process owns signal dispatch
        return False
    _hooks_installed = True
    return True


# A process that configures a dump sink wants the signal legs armed too:
# SIGTERM is how schedulers kill replicas, and the dying flight ring is
# exactly the evidence torchft-diagnose needs.
if env_str("TORCHFT_FLIGHT_FILE"):
    install_signal_hooks()
