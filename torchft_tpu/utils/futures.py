"""Timeout engine: deadline-armed futures, context timeouts, and a watchdog.

TPU-native analog of the reference timeout/futures machinery
(reference: torchft/futures.py:45-315).  The reference wraps torch Futures and
CUDA events; here the unit of async work is a ``concurrent.futures.Future``
(JAX dispatch is asynchronous on its own — device-side completion is observed
with ``jax.block_until_ready`` at the points the protocol requires).

A single daemon timer thread owns a heap of deadlines.  A separate watchdog
thread kills the process (``sys.exit(1)``) if the timer thread itself stops
making progress for ``TORCHFT_WATCHDOG_TIMEOUT_SEC`` (default 30s) — a stuck
timeout engine means timeouts no longer fire, which in a fault-tolerance
system is itself a fault.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import sys
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeoutError
from contextlib import contextmanager
from datetime import timedelta
from typing import Callable, Iterator, Optional, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")

from torchft_tpu.utils.env import env_float

WATCHDOG_TIMEOUT_SEC = env_float("TORCHFT_WATCHDOG_TIMEOUT_SEC", 30.0)


def _to_seconds(timeout: "float | timedelta") -> float:
    if isinstance(timeout, timedelta):
        return timeout.total_seconds()
    return float(timeout)


class _Timer:
    __slots__ = ("deadline", "seq", "callback", "cancelled")

    def __init__(self, deadline: float, seq: int, callback: Callable[[], None]) -> None:
        self.deadline = deadline
        self.seq = seq
        self.callback = callback
        self.cancelled = False

    def __lt__(self, other: "_Timer") -> bool:
        return (self.deadline, self.seq) < (other.deadline, other.seq)


class _TimerHandle:
    def __init__(self, manager: "_TimeoutManager", timer: _Timer) -> None:
        self._manager = manager
        self._timer = timer

    def cancel(self) -> None:
        self._timer.cancelled = True
        # Only wake the timer thread when this timer is the heap head (it may
        # be sleeping until exactly this deadline); cancelled non-head timers
        # are lazily dropped when they surface.
        mgr = self._manager
        with mgr._cond:
            if mgr._heap and mgr._heap[0] is self._timer:
                mgr._cond.notify()


class _TimeoutManager:
    """Singleton timer-heap thread plus stuck-loop watchdog."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._heap: list[_Timer] = []
        self._seq = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        # Monotonic tick the timer thread bumps each loop; watchdog checks it.
        self._last_tick = time.monotonic()

    def _ensure_started(self) -> None:
        with self._cond:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="torchft_timeout", daemon=True
                )
                self._thread.start()
                self._watchdog = threading.Thread(
                    target=self._run_watchdog, name="torchft_watchdog", daemon=True
                )
                self._watchdog.start()

    def schedule(self, timeout_sec: float, callback: Callable[[], None]) -> _TimerHandle:
        self._ensure_started()
        timer = _Timer(time.monotonic() + timeout_sec, next(self._seq), callback)
        with self._cond:
            heapq.heappush(self._heap, timer)
            self._cond.notify()
        return _TimerHandle(self, timer)

    def _run(self) -> None:
        while True:
            due: list[_Timer] = []
            with self._cond:
                now = time.monotonic()
                self._last_tick = now
                while self._heap and (
                    self._heap[0].cancelled or self._heap[0].deadline <= now
                ):
                    timer = heapq.heappop(self._heap)
                    if not timer.cancelled:
                        due.append(timer)
                if not due:
                    wait = (
                        self._heap[0].deadline - now if self._heap else None
                    )
                    self._cond.wait(timeout=wait)
            for timer in due:
                # Re-check: cancel() may have run after the pop. A callback
                # already executing can't be stopped — cancel is best-effort
                # once the deadline has passed.
                if timer.cancelled:
                    continue
                try:
                    timer.callback()
                except Exception:
                    logger.exception("timeout callback raised")

    def _run_watchdog(self) -> None:
        # The timer thread refreshes _last_tick whenever it wakes. If there is
        # pending work whose deadline has long passed and the tick is stale,
        # the loop is wedged (e.g. a callback deadlocked) — abort the process
        # so the job supervisor can restart this replica.
        while True:
            time.sleep(WATCHDOG_TIMEOUT_SEC / 4)
            with self._cond:
                stale = time.monotonic() - self._last_tick
                overdue = (
                    self._heap
                    and self._heap[0].deadline < time.monotonic() - WATCHDOG_TIMEOUT_SEC
                )
            if overdue and stale > WATCHDOG_TIMEOUT_SEC:
                logger.error(
                    "torchft timeout engine stuck for %.0fs — exiting process", stale
                )
                sys.stderr.write("torchft_tpu watchdog: timeout engine stuck, exiting\n")
                sys.stderr.flush()
                os._exit(1)


_TIMEOUT_MANAGER = _TimeoutManager()


def future_timeout(fut: "Future[T]", timeout: "float | timedelta") -> "Future[T]":
    """Return a future mirroring ``fut`` that fails with TimeoutError on expiry."""
    out: Future[T] = Future()

    def _expire() -> None:
        try:
            out.set_exception(TimeoutError(f"future timed out after {timeout}"))
        except Exception:
            pass  # lost the race with _copy

    handle = _TIMEOUT_MANAGER.schedule(_to_seconds(timeout), _expire)

    def _copy(f: "Future[T]") -> None:
        handle.cancel()
        try:
            if f.cancelled():
                out.cancel()
                return
            exc = f.exception()
            if exc is not None:
                out.set_exception(exc)
            else:
                out.set_result(f.result())
        except Exception:
            pass  # lost the race with the timeout callback

    fut.add_done_callback(_copy)
    return out


def future_wait(fut: "Future[T]", timeout: "float | timedelta") -> T:
    """Block on ``fut`` for at most ``timeout``; raises TimeoutError."""
    try:
        return fut.result(timeout=_to_seconds(timeout))
    except (TimeoutError, FuturesTimeoutError):
        # concurrent.futures.TimeoutError is only an alias of the builtin
        # from Python 3.11; on 3.10 result() raises the distinct class.
        # A future may legitimately complete *with* a TimeoutError (e.g. one
        # produced by future_timeout) — re-raise that as-is rather than
        # misreporting it as this wait expiring.
        if fut.done():
            raise
        raise TimeoutError(f"future did not complete within {timeout}")


@contextmanager
def context_timeout(
    callback: Callable[[], None], timeout: "float | timedelta"
) -> Iterator[None]:
    """Run ``callback`` (e.g. ``pg.abort``) if the with-block outlives the deadline."""
    handle = _TIMEOUT_MANAGER.schedule(_to_seconds(timeout), callback)
    try:
        yield
    finally:
        handle.cancel()
