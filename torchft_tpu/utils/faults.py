"""Chaos layer: first-class, production-grade fault injection.

torchft's value proposition is surviving per-step failures, so the fault
paths must be *continuously exercisable* — not only through test-local
monkeypatching.  Prime's PCCL report and "Reliable and Resilient Collective
Communication Library for LLM Training and Serving" (PAPERS.md) both argue
that reliability features rot unless the failure surface is first-class;
this module is that surface: a process-wide registry of **named injection
sites** that every failure-bearing layer consults, with deterministic
seeded schedules, per-site accounting, metrics, and structured events.

Injection sites wired through the production stack:

====================  =====================================================
site                  fires in
====================  =====================================================
``lighthouse.rpc``    ``LighthouseClient`` framed-JSON calls
                      (coordination.py)
``lighthouse.heartbeat``  ``LighthouseClient.heartbeat`` — the Python
                      heartbeat/progress-piggyback client (tests and
                      custom FT algorithms; the native manager's C++
                      heartbeat loop does not consult this registry)
``lighthouse.lease``  ``LighthouseClient.lease`` — the Python
                      leadership-lease client of the replicated
                      lighthouse (``step`` = proposed term; the native
                      electors' C++ lease exchanges do not consult this
                      registry)
``lighthouse.links``  link-state digest reporting — the Python
                      ``LighthouseClient.heartbeat(links=...)`` /
                      ``links()`` readers and ``ManagerServer.
                      report_links`` handoff (a dropped report degrades
                      the fleet matrix to stale rows; the heartbeat
                      itself never carries the fault)
``lighthouse.fragments``  fragment-provenance digest reporting — the
                      Python ``LighthouseClient.heartbeat(fragments=
                      ...)`` / ``serving_heartbeat(fragments=...)`` /
                      ``fragments()`` readers and ``ManagerServer.
                      report_fragments`` handoff (a dropped digest is
                      restored and retried next beat; the version
                      matrix degrades to older rows, never wedges)
``manager.quorum``    ``Manager._async_quorum`` before the quorum RPC
``manager.heal``      ``Manager._async_quorum`` heal send/recv branches
``pg.reconfigure``    ``ProcessGroupTCP.configure`` /
                      ``ProcessGroupBaby.configure``
``pg.allreduce``      ``Manager.allreduce`` before collective submission;
                      also per chunk in the quantized pipeline drivers
``pg.allreduce.chunk``  quantized pipeline drivers, per chunk
                      (``step`` = chunk index)
``pg.allreduce.hop``  hierarchical plan driver before each chunk's
                      inter-host hops (``step`` = chunk index)
``mesh.reshard``      ``parallel/layout.py`` reshard staging, before each
                      per-source slice-diff fetch (``step`` = layout
                      epoch)
``manager.layout_commit``  ``Manager._async_quorum`` before the layout
                      commit round is resolved (``step`` = quorum
                      max_step)
``transport.send``    ``send_checkpoint`` of both checkpoint transports
``transport.recv``    ``recv_checkpoint`` of both checkpoint transports
``transport.heal.frag`` each striped-heal fragment fetch
                      (checkpointing/fragments.py ``fetch_raw`` with the
                      heal role; ``step`` = the fragment's stripe index)
``serving.publish``   ``WeightPublisher.publish`` before a weight
                      version is encoded/staged (``step`` = version)
``serving.fetch``     serving-tier fetch attempts — relay pull from the
                      tree parent and client fetches (``step`` =
                      version)
``serving.frag``      serving-tier per-fragment raw fetches
                      (serving/fetcher.py) — manifest and fragment
                      pulls of the streaming relay and the pipelined
                      client delta path (``step`` = fragment index in
                      the stream, version for single fetches)
``serving.tree_commit``  ``ServingReplica`` adopting a new
                      distribution-tree plan epoch (``step`` = epoch)
``store.barrier``     blocking ``StoreClient.get(wait=True)`` (the
                      rendezvous-barrier wait PG configure relies on)
``store.spill``       durable fragment-store spill — ``FragmentStore.
                      put_state`` / ``put_doc`` before blobs are written
                      (checkpointing/store.py; ``step`` = version; a
                      failed spill skips the version, never stalls a
                      training step)
``store.restore``     ``Manager`` whole-fleet cold-start restore before
                      catalog discovery (``step`` = 0; a failed restore
                      degrades to fresh initialization, never a wedge)
``local_sgd.sync``    ``LocalSGD.sync`` / DiLoCo fragment sync entry
``train.step``        user training loops that opt in by calling
                      :func:`check` at the top of each step (the chaos
                      suite's replica-crash hook)
====================  =====================================================

Schedules are :class:`FaultRule` objects — fail replica R at step S, fail
with probability p after step S, inject latency, drop the connection vs.
raise — registered programmatically (``FAULTS.configure([...], seed=...)``)
or via ``TORCHFT_FAULTS=<spec>`` (grammar below) + ``TORCHFT_FAULTS_SEED``.
Every injection increments ``torchft_faults_injected_total{site,action}``
and emits a structured ``fault`` event, so a chaos run can assert that the
faults observed match the schedule.

Spec grammar (round-trips through :func:`parse_spec` / :func:`format_spec`)::

    spec  := rule (';' rule)*
    rule  := site [':' kv (',' kv)*]
    kv    := key '=' value
    keys  := action  (raise | drop | delay; default raise)
             replica (match the id prefix before ':'; default any)
             step    (fire only at exactly this step)
             after_step (eligible once step >= N)
             prob    (fire with this probability per eligible check; 0..1)
             times   (max firings; -1 = unlimited; default 1)
             delay   (seconds slept for action=delay)

Example::

    TORCHFT_FAULTS="pg.allreduce:replica=replica_1,step=2;\
transport.recv:after_step=0,action=drop;\
manager.quorum:prob=0.05,after_step=3,times=-1,action=delay,delay=0.2"

Failure policy: with no rules registered, :func:`check` is a single
attribute test — safe on the allreduce hot path.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "KNOWN_SITES",
    "ACTIONS",
    "InjectedFault",
    "InjectedConnectionDrop",
    "FaultRule",
    "FaultRegistry",
    "FAULTS",
    "check",
    "parse_spec",
    "format_spec",
    "configure_from_env",
]

# The production injection sites (module docstring documents where each
# fires).  Rules may name other sites — e.g. a test harness's own hook —
# but a typo'd production site should be loud, so parse_spec warns on
# unknown names instead of silently never firing.
KNOWN_SITES: "Tuple[str, ...]" = (
    "lighthouse.rpc",
    "lighthouse.heartbeat",
    "lighthouse.lease",
    "lighthouse.links",
    "lighthouse.fragments",
    "manager.quorum",
    "manager.heal",
    "pg.reconfigure",
    "pg.allreduce",
    "pg.allreduce.chunk",
    "pg.allreduce.hop",
    "mesh.reshard",
    "manager.layout_commit",
    "transport.send",
    "transport.recv",
    "transport.heal.frag",
    "serving.publish",
    "serving.fetch",
    "serving.frag",
    "serving.tree_commit",
    "store.barrier",
    "store.spill",
    "store.restore",
    "local_sgd.sync",
    "train.step",
)

ACTIONS: "Tuple[str, ...]" = ("raise", "drop", "delay")


class InjectedFault(RuntimeError):
    """A chaos-injected hard failure (action=raise)."""


class InjectedConnectionDrop(ConnectionError):
    """A chaos-injected connection drop (action=drop).

    Subclasses :class:`ConnectionError` so it takes exactly the code path a
    real peer reset takes (retry loops, error latching, reconnects)."""


@dataclass
class FaultRule:
    """One scheduled fault at one site.

    Matching: the rule fires when the site matches exactly, the caller's
    replica matches ``replica`` (prefix before the ``:<uuid>`` incarnation
    suffix; ``None`` matches any), the caller's step satisfies ``step`` /
    ``after_step``, the rule is not exhausted (``times``), and a seeded
    per-rule RNG draw passes ``prob``.  A rule with a replica/step
    constraint never matches a check that did not supply that context.
    """

    site: str
    action: str = "raise"
    replica: "Optional[str]" = None
    step: "Optional[int]" = None
    after_step: "Optional[int]" = None
    prob: float = 1.0
    times: int = 1
    delay: float = 0.0
    # runtime state, not part of the spec round-trip
    fired: int = field(default=0, compare=False)

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(
                f"fault action must be one of {ACTIONS}, got {self.action!r}"
            )
        if not self.site:
            raise ValueError("fault rule needs a site")
        if not (0.0 <= self.prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")
        if self.delay < 0:
            raise ValueError(f"delay must be >= 0, got {self.delay}")

    def exhausted(self) -> bool:
        return 0 <= self.times <= self.fired


def _base_replica(replica_id: "Optional[str]") -> "Optional[str]":
    """Strip the ``:<uuid>`` incarnation suffix the Manager appends."""
    if replica_id is None:
        return None
    return replica_id.split(":", 1)[0]


class FaultRegistry:
    """Process-wide registry of fault rules with deterministic scheduling.

    Every rule owns a :class:`random.Random` seeded from the registry seed
    and the rule's index, so a fixed seed plus a deterministic sequence of
    :meth:`check` calls replays the identical schedule — the property the
    chaos soak relies on to assert "faults injected == faults scheduled".
    """

    def __init__(self, seed: "Optional[int]" = None) -> None:
        from torchft_tpu.utils import lockcheck

        self._lock = lockcheck.lock("faults.registry")
        self._seed = 0 if seed is None else int(seed)
        self._rules: "List[FaultRule]" = []
        self._rngs: "List[random.Random]" = []
        self._counts: "Dict[Tuple[str, str], int]" = {}

    # -- configuration -----------------------------------------------------

    def _rule_rng(self, index: int) -> random.Random:
        # distinct, stable stream per rule: schedule determinism survives
        # reordering of checks across *other* rules
        return random.Random((self._seed & 0xFFFFFFFF) * 1000003 + index)

    def configure(
        self, rules: "List[FaultRule]", seed: "Optional[int]" = None
    ) -> None:
        """Replace the whole schedule (and reset all accounting)."""
        with self._lock:
            if seed is not None:
                self._seed = int(seed)
            self._rules = list(rules)
            for r in self._rules:
                r.fired = 0
            self._rngs = [self._rule_rng(i) for i in range(len(self._rules))]
            self._counts = {}

    def register(self, rule: FaultRule) -> FaultRule:
        """Append one rule to the live schedule."""
        with self._lock:
            self._rules.append(rule)
            self._rngs.append(self._rule_rng(len(self._rules) - 1))
        return rule

    def clear(self) -> None:
        self.configure([])

    # -- introspection -----------------------------------------------------

    def rules(self) -> "List[FaultRule]":
        with self._lock:
            return list(self._rules)

    def counts(self) -> "Dict[Tuple[str, str], int]":
        """{(site, action): fired} since the last configure()."""
        with self._lock:
            return dict(self._counts)

    def injected(self, site: "Optional[str]" = None) -> int:
        """Total faults injected (optionally for one site)."""
        with self._lock:
            return sum(
                n
                for (s, _a), n in self._counts.items()
                if site is None or s == site
            )

    # -- the injection point -----------------------------------------------

    def check(
        self,
        site: str,
        replica: "Optional[str]" = None,
        step: "Optional[int]" = None,
    ) -> None:
        """Consult the schedule at ``site``; act on the first firing rule.

        Raises :class:`InjectedFault` (action=raise) or
        :class:`InjectedConnectionDrop` (action=drop), or sleeps
        (action=delay).  No-op (one attribute test) with no rules.
        """
        if not self._rules:
            return
        fired: "Optional[FaultRule]" = None
        base = _base_replica(replica)
        with self._lock:
            for rule, rng in zip(self._rules, self._rngs):
                if rule.site != site or rule.exhausted():
                    continue
                if rule.replica is not None and rule.replica != base:
                    continue
                if rule.step is not None and step != rule.step:
                    continue
                if rule.after_step is not None and (
                    step is None or step < rule.after_step
                ):
                    continue
                if rule.prob < 1.0 and rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                key = (site, rule.action)
                self._counts[key] = self._counts.get(key, 0) + 1
                fired = rule
                break
        if fired is None:
            return
        self._emit(fired, site, replica, step)
        if fired.action == "delay":
            time.sleep(fired.delay)
            return
        msg = (
            f"injected {fired.action} at {site}"
            f" (replica={replica}, step={step})"
        )
        if fired.action == "drop":
            raise InjectedConnectionDrop(msg)
        raise InjectedFault(msg)

    @staticmethod
    def _emit(
        rule: FaultRule, site: str, replica: "Optional[str]", step: "Optional[int]"
    ) -> None:
        # Metrics + structured event, never allowed to mask the injection
        # itself (a chaos layer that crashes on telemetry is its own chaos).
        try:
            from torchft_tpu.utils import metrics as _metrics

            _metrics.FAULTS_INJECTED.labels(site=site, action=rule.action).inc()
        except Exception:  # noqa: BLE001
            logger.exception("fault metrics emit failed")
        try:
            from torchft_tpu.utils.logging import log_event

            log_event(
                "fault",
                f"injected {rule.action} at {site}",
                site=site,
                action=rule.action,
                replica_id=replica or "",
                step=step if step is not None else -1,
                rule_times=rule.times,
                rule_fired=rule.fired,
            )
        except Exception:  # noqa: BLE001
            logger.exception("fault event emit failed")
        try:
            from torchft_tpu.utils import flightrecorder as _fr

            # fault-tagged flight record: torchft-diagnose attributes a
            # chaos-killed replica from exactly this tag
            extra = {} if step is None else {"step": step}
            _fr.record(
                "fault",
                status="fault",
                fault=f"{site}:{rule.action}",
                site=site,
                action=rule.action,
                replica_id=replica or "",
                **extra,
            )
        except Exception:  # noqa: BLE001
            logger.exception("fault flight record failed")


#: The process-wide registry every production site consults.
FAULTS = FaultRegistry()


def check(
    site: str, replica: "Optional[str]" = None, step: "Optional[int]" = None
) -> None:
    """Module-level shorthand for ``FAULTS.check(...)`` (the form the
    production call sites use)."""
    FAULTS.check(site, replica=replica, step=step)


# ---------------------------------------------------------------------------
# TORCHFT_FAULTS spec
# ---------------------------------------------------------------------------

# fixed key order so format_spec output is stable and round-trips
_SPEC_KEYS = ("action", "replica", "step", "after_step", "prob", "times", "delay")
_DEFAULTS = FaultRule(site="_defaults_")


def parse_spec(spec: str) -> "List[FaultRule]":
    """Parse a ``TORCHFT_FAULTS`` spec string (grammar in module docstring)."""
    rules: "List[FaultRule]" = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        site, _, rest = raw.partition(":")
        site = site.strip()
        if site not in KNOWN_SITES:
            logger.warning(
                "TORCHFT_FAULTS: site %r is not a known injection site %s — "
                "the rule only fires if something checks it explicitly",
                site,
                KNOWN_SITES,
            )
        kw: "Dict[str, Any]" = {}
        for item in rest.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, value = item.partition("=")
            key = key.strip()
            value = value.strip()
            if not sep or key not in _SPEC_KEYS:
                raise ValueError(
                    f"TORCHFT_FAULTS: bad entry {item!r} in rule {raw!r} "
                    f"(keys: {_SPEC_KEYS})"
                )
            if key in ("step", "after_step", "times"):
                kw[key] = int(value)
            elif key in ("prob", "delay"):
                kw[key] = float(value)
            else:
                kw[key] = value
        rules.append(FaultRule(site=site, **kw))
    return rules


def format_spec(rules: "List[FaultRule]") -> str:
    """Render rules back to the spec grammar (non-default fields only);
    ``parse_spec(format_spec(rules)) == rules``."""
    parts: "List[str]" = []
    for r in rules:
        kvs: "List[str]" = []
        for key in _SPEC_KEYS:
            value = getattr(r, key)
            if value == getattr(_DEFAULTS, key):
                continue
            if isinstance(value, float):
                kvs.append(f"{key}={value:g}")
            else:
                kvs.append(f"{key}={value}")
        parts.append(r.site + (":" + ",".join(kvs) if kvs else ""))
    return ";".join(parts)


def configure_from_env(env: "Optional[Dict[str, str]]" = None) -> bool:
    """Install the schedule from ``TORCHFT_FAULTS`` / ``TORCHFT_FAULTS_SEED``.

    Returns True if a schedule was installed.  Called once at import; a
    malformed spec raises (a chaos run with a silently-empty schedule would
    report a vacuous pass)."""
    if env is None:
        from torchft_tpu.utils.env import env_str

        spec = env_str("TORCHFT_FAULTS")
        seed_raw = env_str("TORCHFT_FAULTS_SEED")
    else:
        spec = env.get("TORCHFT_FAULTS", "")
        seed_raw = env.get("TORCHFT_FAULTS_SEED")
    if not spec.strip():
        return False
    seed = int(seed_raw) if seed_raw else 0
    FAULTS.configure(parse_spec(spec), seed=seed)
    logger.info(
        "chaos schedule installed from TORCHFT_FAULTS (%d rules, seed=%d)",
        len(FAULTS.rules()),
        seed,
    )
    return True


configure_from_env()
