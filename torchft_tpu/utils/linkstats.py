"""Passive per-link bandwidth/RTT estimation (the fleet link-state plane).

ROADMAP item 4's plan synthesizer needs *live measured* per-link
bandwidth and RTT as data (PCCL's premise; Prime shows why
assumed-uniform links are fiction on real fleets).  This registry is the
replica-local half of that plane: a process-wide table keyed by
``(peer, plane)`` fed by every REAL transfer — no active probing:

- ``reduction``  — ProcessGroupTCP message completions (bytes + wall per
  inter-host send, parallel/process_group.py);
- ``fragments``  — the fragment fetch plane (Content-Length + first-byte
  latency, checkpointing/fragments.py — serves both serving pulls and
  striped heal for free);
- ``rpc``        — coordination RPC round trips (coordination.py).

Estimators are a byte-weighted decayed-mean goodput plus a windowed
first-byte latency reservoir (p50/p99).  ``record()`` runs at the
flight-recorder cost bar (one lock + a few float ops + one deque append;
budget-tested in tests/test_linkstats.py) because it sits inside the
collective send path.

The WAN/local distinction is carried per entry (``local`` flag) and in
the key itself: a same-host peer that the declared ``TORCHFT_TOPOLOGY``
places across a boundary is keyed under a ``host#gN`` pseudo-host so a
shaped (WAN-modeled) link is never averaged into the unshaped local
fabric — intra-host pairs report unshaped-fast, WAN pairs report the
modeled link, and the two can never be confused.

Fleet aggregation: ``maybe_digest()`` emits a bounded link table at most
every ``TORCHFT_LINK_REPORT_S`` seconds; the Manager piggybacks it on
the native heartbeat (consumed-on-send, like the per-step digest) and
the lighthouse folds it into the host-pair matrix served at
``/links.json``.  The same cadence refreshes the worst-K-bounded
``torchft_link_*`` gauges (``TORCHFT_LINK_TOPK`` rows per plane; the
fleet-wide truth lives in the unlabeled aggregates — the straggler-tier
cardinality rule, docs/observability.md).

``LinkMatrix.snapshot()`` is the frozen, monotone-versioned view the
plan synthesizer will take as input (docs/architecture.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from torchft_tpu.utils.env import env_float, env_int

__all__ = [
    "PLANES",
    "LinkStat",
    "LinkMatrix",
    "LinkRegistry",
    "LINKS",
    "record",
]

#: the three transfer planes a link is measured on
PLANES = ("reduction", "fragments", "rpc")

#: decay applied to the goodput accumulators per sample — a ~32-sample
#: half-life: old shaping regimes fade, single outliers don't dominate
_DECAY = 0.98


@dataclass(frozen=True)
class LinkStat:
    """One measured link, frozen at snapshot time."""

    peer: str
    plane: str
    local: bool
    goodput_bps: float
    rtt_p50_ms: float
    rtt_p99_ms: float
    samples: int
    bytes_total: int
    age_s: float

    def to_dict(self) -> "Dict[str, Any]":
        return {
            "peer": self.peer,
            "plane": self.plane,
            "local": self.local,
            "goodput_bps": round(self.goodput_bps, 1),
            "rtt_ms": round(self.rtt_p50_ms, 3),
            "rtt_p99_ms": round(self.rtt_p99_ms, 3),
            "samples": self.samples,
            "bytes": self.bytes_total,
            "age_s": round(self.age_s, 3),
        }


@dataclass(frozen=True)
class LinkMatrix:
    """A frozen snapshot of the link table with a monotone version —
    the plan-synthesizer input contract (ROADMAP item 4): equal versions
    mean identical entries; a higher version supersedes a lower one."""

    version: int
    entries: "Tuple[LinkStat, ...]"

    def get(self, peer: str, plane: str) -> "Optional[LinkStat]":
        for e in self.entries:
            if e.peer == peer and e.plane == plane:
                return e
        return None


class _Estimator:
    """Per-(peer, plane) accumulators.  All mutation happens under the
    registry lock; no per-estimator lock (record() cost bar)."""

    __slots__ = (
        "local", "bytes_dec", "secs_dec", "fb_window",
        "samples", "bytes_total", "last_mono",
    )

    def __init__(self, local: bool, window: int) -> None:
        self.local = local
        self.bytes_dec = 0.0
        self.secs_dec = 0.0
        self.fb_window: "deque[float]" = deque(maxlen=window)
        self.samples = 0
        self.bytes_total = 0
        self.last_mono = 0.0


def _quantiles(window: "deque[float]") -> "Tuple[float, float]":
    """(p50, p99) of the first-byte window, in seconds."""
    if not window:
        return 0.0, 0.0
    s = sorted(window)
    n = len(s)
    return s[n // 2], s[min(int(n * 0.99), n - 1)]


class LinkRegistry:
    """The process-wide passive link table (module global ``LINKS``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._links: "Dict[Tuple[str, str], _Estimator]" = {}
        self._version = 0
        self._window = env_int("TORCHFT_LINK_WINDOW", 64, minimum=4)
        self._topk = env_int("TORCHFT_LINK_TOPK", 8, minimum=1)
        self._report_s = env_float("TORCHFT_LINK_REPORT_S", 2.0, minimum=0.0)
        self._last_report_mono = 0.0
        # first-K distinct peer names get their own bounded metric label;
        # everyone later folds into "other" (restart-stable: peer names
        # are hosts, not incarnations)
        self._label_peers: "Dict[str, str]" = {}

    # -- configuration ----------------------------------------------------

    def reset(self) -> None:
        """Drop every link and re-read the env knobs (tests flip them)."""
        with self._lock:
            self._links.clear()
            self._label_peers.clear()
            self._version = 0
            self._last_report_mono = 0.0
            self._window = env_int("TORCHFT_LINK_WINDOW", 64, minimum=4)
            self._topk = env_int("TORCHFT_LINK_TOPK", 8, minimum=1)
            self._report_s = env_float(
                "TORCHFT_LINK_REPORT_S", 2.0, minimum=0.0
            )

    # -- hot path ---------------------------------------------------------

    def record(
        self,
        peer: str,
        plane: str,
        nbytes: int,
        seconds: float,
        first_byte_s: "Optional[float]" = None,
        local: bool = False,
    ) -> None:
        """Fold one completed transfer in.  ``seconds`` is the whole
        message wall (first byte included); goodput uses the post-first-
        byte interval so bandwidth and latency estimate independently
        (the two decoupled legs of the wire model)."""
        now = time.monotonic()
        with self._lock:
            est = self._links.get((peer, plane))
            if est is None:
                est = self._links[(peer, plane)] = _Estimator(
                    local, self._window
                )
            xfer = seconds - (first_byte_s or 0.0)
            if nbytes > 0 and xfer > 0.0:
                est.bytes_dec = est.bytes_dec * _DECAY + nbytes
                est.secs_dec = est.secs_dec * _DECAY + xfer
            if first_byte_s is not None:
                est.fb_window.append(first_byte_s)
            est.samples += 1
            est.bytes_total += nbytes
            est.last_mono = now
            self._version += 1

    # -- bounded metric labels (worst-K tier) -----------------------------

    def peer_topk_label(self, peer: str) -> str:
        """Bounded per-peer metric label: the first ``TORCHFT_LINK_TOPK``
        distinct peers keep their name, later ones fold into ``other`` —
        at most K+1 values ever, restart-stable (peers are hosts).  The
        ``metrics-cardinality`` lint recognizes ``*topk_label`` accessors
        as this bounded tier."""
        with self._lock:
            label = self._label_peers.get(peer)
            if label is None:
                label = (
                    peer if len(self._label_peers) < self._topk else "other"
                )
                self._label_peers[peer] = label
            return label

    # -- snapshots --------------------------------------------------------

    def _stat_locked(self, key: "Tuple[str, str]", now: float) -> LinkStat:
        est = self._links[key]
        p50, p99 = _quantiles(est.fb_window)
        return LinkStat(
            peer=key[0],
            plane=key[1],
            local=est.local,
            goodput_bps=(
                est.bytes_dec / est.secs_dec if est.secs_dec > 0.0 else 0.0
            ),
            rtt_p50_ms=p50 * 1e3,
            rtt_p99_ms=p99 * 1e3,
            samples=est.samples,
            bytes_total=est.bytes_total,
            age_s=max(now - est.last_mono, 0.0),
        )

    def snapshot(self) -> LinkMatrix:
        """The frozen, monotone-versioned link matrix."""
        now = time.monotonic()
        with self._lock:
            return LinkMatrix(
                version=self._version,
                entries=tuple(
                    self._stat_locked(k, now) for k in sorted(self._links)
                ),
            )

    def maybe_digest(self, host: str) -> "Optional[Dict[str, Any]]":
        """The heartbeat-piggyback digest, rate-limited to one per
        ``TORCHFT_LINK_REPORT_S``: ``None`` when not due or empty.  Rows
        are bounded to the worst-K WAN links per plane (lowest goodput
        first — the links worth aggregating fleet-wide) plus local-pair
        evidence; the same pass refreshes the worst-K gauges."""
        now = time.monotonic()
        with self._lock:
            if not self._links:
                return None
            if (
                self._report_s > 0.0
                and now - self._last_report_mono < self._report_s
            ):
                return None
            self._last_report_mono = now
            stats = [self._stat_locked(k, now) for k in sorted(self._links)]
            topk = self._topk
        self._export_metrics(stats, topk)
        rows: "List[Dict[str, Any]]" = []
        for plane in PLANES:
            wan = sorted(
                (s for s in stats if s.plane == plane and not s.local),
                key=lambda s: (s.goodput_bps or float("inf")),
            )
            loc = [s for s in stats if s.plane == plane and s.local]
            rows.extend(s.to_dict() for s in wan[:topk])
            rows.extend(s.to_dict() for s in loc[:topk])
        if not rows:
            return None
        return {"host": host, "rows": rows}

    def _export_metrics(self, stats: "List[LinkStat]", topk: int) -> None:
        """Refresh the worst-K-bounded ``torchft_link_*`` gauges plus the
        unlabeled fleet-local aggregates (cardinality contract:
        docs/observability.md "metric cardinality")."""
        from torchft_tpu.utils import metrics as _metrics

        wan = [s for s in stats if not s.local]
        _metrics.LINK_PAIRS.set(len(stats))
        _metrics.LINK_GOODPUT_MIN.set(
            min((s.goodput_bps for s in wan if s.goodput_bps > 0), default=0.0)
        )
        worst = sorted(
            (s for s in wan if s.goodput_bps > 0),
            key=lambda s: s.goodput_bps,
        )[:topk]
        for s in worst:
            _metrics.LINK_GOODPUT.labels(
                peer=self.peer_topk_label(s.peer), plane=s.plane
            ).set(s.goodput_bps)
            _metrics.LINK_RTT_P99.labels(
                peer=self.peer_topk_label(s.peer), plane=s.plane
            ).set(s.rtt_p99_ms / 1e3)


#: the process-wide registry every transfer plane feeds
LINKS = LinkRegistry()


def record(
    peer: str,
    plane: str,
    nbytes: int,
    seconds: float,
    first_byte_s: "Optional[float]" = None,
    local: bool = False,
) -> None:
    """Module-level convenience over ``LINKS.record`` (hot-path feeds
    import the module once and call this)."""
    LINKS.record(
        peer, plane, nbytes, seconds, first_byte_s=first_byte_s, local=local
    )
