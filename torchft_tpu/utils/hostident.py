"""Which names can denote THIS machine.

One source of truth for host-locality decisions: the HA peer-list
self-exclusion (``ha/endpoints.exclude_self``) and the serving-tier
wire shaper's intra-host exemption (``serving/wire.py``) must agree on
what "local" means, or a host addressed one way would be excluded from
its own peer list while the same address is shaped as WAN traffic.
"""

from __future__ import annotations

from typing import FrozenSet

__all__ = ["local_host_identities"]


def local_host_identities() -> "FrozenSet[str]":
    """Hostnames/addresses that denote this machine: loopback and
    wildcard forms, the hostname (full + short), and the hostname's
    resolved address when resolution works."""
    import socket

    name = socket.gethostname()
    ids = {
        "localhost",
        "127.0.0.1",
        "::1",
        "0.0.0.0",
        "",
        name,
        name.split(".")[0],
    }
    try:
        ids.add(socket.gethostbyname(name))
    except OSError:
        pass
    return frozenset(ids)
