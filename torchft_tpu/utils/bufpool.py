"""Size-keyed scratch-buffer pool for host collective staging.

Large numpy allocations are mmap-backed: every fresh buffer pays a
page-fault per 4 KiB on first touch, which on the DCN host path costs
~5x the actual write (measured 144 ms vs 28 ms to fill 232 MB on the
bench host).  The quantized-collective codec stages (accumulators, packed
wire buffers, padded row-blocks) and the TCP ring's scratch chunks have
exact, repeating sizes and clear ownership windows — a pool turns their
per-fragment page-fault bill into a one-time warmup.

The reference has the same concept on device (its CUDA caching allocator
does this transparently for torch tensors); on the host side numpy has no
caching allocator, so the framework carries a small explicit one.

Contract: ``take`` returns an UNINITIALIZED array (np.empty semantics);
``give`` hands memory back — the caller must guarantee no other live
reference (views included) escapes.  Never ``give`` a buffer the caller
returned to user code.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

import numpy as np


class BufferPool:
    def __init__(self, max_bytes: "int | None" = None) -> None:
        if max_bytes is None:
            from torchft_tpu.utils.env import env_int

            max_bytes = env_int("TORCHFT_BUFPOOL_MB", 2048, minimum=0) << 20
        self.max_bytes = max_bytes
        self._free: "Dict[Tuple[int, str], List[np.ndarray]]" = {}
        self._held = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def take(self, shape, dtype=np.float32) -> np.ndarray:
        dt = np.dtype(dtype)
        size = int(np.prod(shape, dtype=np.int64)) if not np.isscalar(shape) else int(shape)
        key = (size, dt.str)
        with self._lock:
            lst = self._free.get(key)
            if lst:
                arr = lst.pop()
                self._held -= arr.nbytes
                self.hits += 1
                return arr.reshape(shape)
            self.misses += 1
        return np.empty(shape, dtype=dt)

    def give(self, arr: "np.ndarray | None") -> None:
        if arr is None or arr.nbytes == 0 or not arr.flags.c_contiguous:
            return
        # normalize views produced by take()'s reshape back to their base
        # allocation so the whole buffer is reusable
        base = arr
        while isinstance(base.base, np.ndarray) and base.base.nbytes == arr.nbytes:
            base = base.base
        # Only pool arrays that OWN their memory (malloc'd by numpy).  A
        # view over foreign memory — e.g. the Baby PG's zero-copy
        # /dev/shm-backed receive buffers, whose close/unlink finalizer
        # would be pinned for as long as the pool holds the view — must
        # fall to the GC instead.  This is enforced here, at the seam,
        # so no recycle call site has to know which PG produced a buffer.
        if base.base is not None:
            return
        key = (base.size, base.dtype.str)
        with self._lock:
            if self._held + base.nbytes > self.max_bytes:
                return  # over cap: drop on the floor, OS reclaims
            self._free.setdefault(key, []).append(base)
            self._held += base.nbytes

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._held = 0


# Process-wide default pool: collective staging buffers repeat sizes
# across fragments AND across replica ranks hosted in one process.
POOL = BufferPool()
