"""Flagship model: a llama-style decoder-only transformer, TPU-first.

The reference's examples train a toy CNN/MLP (train_ddp.py:84-102,
train_diloco.py:76-120) and its reference-scale config is Llama3-8B via
torchtitan (torchft/examples/slurm/runner.py:16-49).  This module is that
model family built natively: pure-functional JAX (params are a pytree),
bfloat16 compute with fp32 master params, RMSNorm + rotary embeddings + GQA
+ SwiGLU, layers stacked and iterated with `lax.scan` (one trace per block,
fast compiles at depth), optional `jax.checkpoint` rematerialization, and a
multi-axis parallelism story expressed as `PartitionSpec`s:

- ``dp``   data-parallel replicas *within* a slice (pure batch dim),
- ``fsdp`` fully-sharded data parallel (params sharded over it, batch too),
- ``tp``   tensor parallel (attention heads / MLP hidden),
- ``cp``   context parallel (sequence; ring or Ulysses attention),
- ``ep``   expert parallel (MoE experts; rides the batch dims elsewhere).

Pipeline parallelism is a separate composition primitive
(torchft_tpu/parallel/pipeline.py) for stacked-layer stacks.

The elastic FT replica dimension deliberately does NOT appear here: it lives
above jit in the Manager (zero-fill + divide-by-participants keeps compiled
shapes static across quorum changes — SURVEY §7, reference manager.py:416).

Weights layout keeps matmuls [*, E] x [E, F] shaped for the MXU; all
reductions accumulate in fp32 (`preferred_element_type`).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.ops.ring_attention import dense_attention, ring_attention_local
from torchft_tpu.ops.ulysses import ulysses_attention_local

logger = logging.getLogger(__name__)
_warned_replicated: set = set()

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1408
    n_layers: int = 6
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # remat granularity: "full" recomputes the whole block in the backward
    # (min memory); "dots" saves matmul outputs and recomputes only the
    # cheap elementwise ops (jax.checkpoint_policies.dots_saveable —
    # trades ~260 MB/layer of bf16 activations for skipping the
    # FLOP-heavy recompute; measured faster whenever it fits in HBM).
    remat_policy: str = "full"
    # "auto"    = TPU-first resolution per call site: flash when the
    #             sequence is lane-aligned (T % 128 == 0) and unsharded,
    #             ring on cp meshes / manual-cp contexts, dense otherwise
    #             (one log line on fallback);
    # "dense"   = single-pass attention (cp must be 1 / unsharded seq);
    # "flash"   = fused Pallas tiles (ops/flash_attention.py); needs
    #             T % 128 == 0, sequence unsharded;
    # "ring"    = ring attention, sequence sharded over `cp_axis`
    #             (K/V ppermute ring; memory stays local-T, best for
    #             extreme sequence lengths);
    # "ulysses" = all-to-all head-scatter/seq-gather attention over
    #             `cp_axis`. Needs the PER-TP-SHARD head counts divisible
    #             by cp: (n_heads/tp) % cp == 0 and (n_kv_heads/tp) % cp
    #             == 0. One dense attention per head group; best MXU
    #             utilization at moderate T.
    attn_impl: str = "auto"
    # n_experts > 0 replaces the dense FFN with a MoE layer (top-k routed,
    # experts sharded over `ep_axis`; see torchft_tpu/models/moe.py).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    dp_axis: str = "dp"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"
    cp_axis: str = "cp"
    ep_axis: str = "ep"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    """Initialize the parameter pytree. Per-layer weights are stacked on a
    leading [n_layers] dim so the forward can `lax.scan` over blocks."""
    e, f, l = cfg.d_model, cfg.d_ff, cfg.n_layers
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    pd = cfg.param_dtype
    keys = jax.random.split(rng, 8)

    def dense(key, *shape):
        fan_in = shape[-2]
        return (jax.random.normal(key, shape, pd) / np.sqrt(fan_in)).astype(pd)

    blocks = {
        "attn_norm": jnp.ones((l, e), pd),
        "wq": dense(keys[1], l, e, nh * hd),
        "wk": dense(keys[2], l, e, nkv * hd),
        "wv": dense(keys[3], l, e, nkv * hd),
        "wo": dense(keys[4], l, nh * hd, e),
        "mlp_norm": jnp.ones((l, e), pd),
    }
    if cfg.n_experts:
        from torchft_tpu.models.moe import init_moe_params

        blocks.update(init_moe_params(keys[5], _moe_cfg(cfg), n_layers=l))
    else:
        blocks.update(
            {
                "w_gate": dense(keys[5], l, e, f),
                "w_up": dense(keys[6], l, e, f),
                "w_down": dense(keys[7], l, f, e),
            }
        )
    return {
        "embed": jax.random.normal(keys[0], (cfg.vocab_size, e), pd) * 0.02,
        "blocks": blocks,
        "final_norm": jnp.ones((e,), pd),
    }


def _moe_cfg(cfg: TransformerConfig):
    from torchft_tpu.models.moe import MoEConfig

    return MoEConfig(
        d_model=cfg.d_model,
        d_ff=cfg.d_ff,
        n_experts=cfg.n_experts,
        top_k=cfg.moe_top_k,
        capacity_factor=cfg.moe_capacity_factor,
        ep_axis=cfg.ep_axis,
        fsdp_axis=cfg.fsdp_axis,
        tp_axis=cfg.tp_axis,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
    )


def _filter_spec(spec: P, mesh: "Optional[Mesh]") -> P:
    """Drop axes the mesh doesn't have (partial meshes, e.g. cp-only or
    fsdp/tp-only inner HSDP meshes)."""
    if mesh is None:
        return spec

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in mesh.axis_names)
            return kept or None
        return entry if entry in mesh.axis_names else None

    return P(*(keep(e) for e in spec))


def param_specs(cfg: TransformerConfig, mesh: "Optional[Mesh]" = None) -> Params:
    """PartitionSpecs matching init_params' tree: 2-D weights sharded
    (fsdp x tp); the stacked layer dim stays unsharded so `lax.scan` slices
    locally. With a mesh, axes absent from it are dropped."""
    fs, tp = cfg.fsdp_axis, cfg.tp_axis
    blocks = {
        "attn_norm": P(None, None),
        "wq": P(None, fs, tp),
        "wk": P(None, fs, tp),
        "wv": P(None, fs, tp),
        "wo": P(None, tp, fs),
        "mlp_norm": P(None, None),
    }
    if cfg.n_experts:
        from torchft_tpu.models.moe import moe_param_specs

        blocks.update(moe_param_specs(_moe_cfg(cfg), stacked=True))
    else:
        blocks.update(
            {
                "w_gate": P(None, fs, tp),
                "w_up": P(None, fs, tp),
                "w_down": P(None, tp, fs),
            }
        )
    specs = {
        "embed": P(tp, fs),
        "blocks": blocks,
        "final_norm": P(None),
    }
    if mesh is not None and fs not in mesh.axis_names and tp not in mesh.axis_names:
        # legitimate for e.g. a cp-only inner mesh (weights replicated by
        # design), but also the symptom of a cfg/mesh axis-name mismatch —
        # which would otherwise silently train unsharded. Warn once per
        # combination (param_specs sits in training-loop paths).
        key = (tuple(mesh.axis_names), fs, tp)
        if key not in _warned_replicated:
            _warned_replicated.add(key)
            logger.warning(
                "mesh %s has neither the fsdp (%r) nor tp (%r) axis: "
                "parameters will be fully replicated. If this is "
                "unintended, align the TransformerConfig *_axis names "
                "with the mesh.",
                mesh.axis_names, fs, tp,
            )
    return jax.tree_util.tree_map(
        lambda s: _filter_spec(s, mesh), specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def _batch_axes(cfg: TransformerConfig, mesh: "Optional[Mesh]") -> tuple:
    """Mesh axes the batch dim shards over: (dp, fsdp) plus ep when it
    exists — ep rides the batch dims so non-MoE compute is data-parallel
    over ep shards instead of replicated; inside the MoE layer the
    [E, C, d] constraint re-shards tokens expert-wise (the GShard
    ep-borrowed-from-dp layout).

    With a mesh, axes are filtered to those present and deduped, so
    partial meshes (e.g. an inner HSDP mesh with only fsdp/tp) and axis
    aliasing (dp_axis == fsdp_axis) both work.
    """
    axes = [cfg.dp_axis, cfg.fsdp_axis]
    if (mesh is not None and cfg.ep_axis in mesh.axis_names) or (
        mesh is None and cfg.n_experts
    ):
        axes.append(cfg.ep_axis)
    if mesh is not None:
        axes = [a for a in axes if a in mesh.axis_names]
    return tuple(dict.fromkeys(axes))  # dedupe, order-preserving


def _seq_axis(cfg: TransformerConfig, mesh: "Optional[Mesh]") -> "Optional[str]":
    if mesh is not None and cfg.cp_axis not in mesh.axis_names:
        return None
    return cfg.cp_axis


def batch_spec(cfg: TransformerConfig, mesh: "Optional[Mesh]" = None) -> P:
    """Tokens [B, T]: batch over (dp, fsdp[, ep]), sequence over cp."""
    return P(_batch_axes(cfg, mesh), _seq_axis(cfg, mesh))


def shard_params(params: Params, mesh: Mesh, cfg: TransformerConfig) -> Params:
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        param_specs(cfg, mesh),
    )


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _rms_norm(x: jax.Array, w: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * w.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x [B, T, H, D], positions [T] (global)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _expand_kv_for_tp(cfg: TransformerConfig, mesh: Mesh, nh: int, k, v):
    """K/V normally cross shard_map unexpanded (nkv heads of ppermute /
    all-to-all / kernel bytes); when tp doesn't divide nkv that layout
    isn't shardable, so pre-expand to nh heads."""
    tp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
        cfg.tp_axis, 1
    )
    if k.shape[2] % tp_size != 0:
        rep = nh // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


_warned_attn_fallback: set = set()


def _resolve_attn_impl(
    cfg: TransformerConfig, mesh: Any, manual_cp: bool, seq_len: int
) -> str:
    """Resolve ``attn_impl='auto'`` at trace time, TPU-first: the fused
    Pallas flash tiles whenever the shapes allow (they remove the [T, T]
    score materialization — the dominant HBM cost of dense attention),
    ring attention when the sequence is cp-sharded, dense only as the
    lane-unaligned fallback (logged once per shape)."""
    if cfg.attn_impl != "auto":
        return cfg.attn_impl
    if manual_cp:
        return "ring"
    if isinstance(mesh, Mesh):
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if sizes.get(cfg.cp_axis, 1) > 1:
            return "ring"
    # The Pallas kernel only pays on real TPU hardware; off-TPU it would
    # run in interpreter mode (orders of magnitude slower than XLA dense),
    # so "auto" means dense there — CPU debugging / virtual-mesh dryruns
    # keep their speed, and the flash path itself is covered off-TPU by
    # its interpret-mode kernel tests.
    if seq_len % 128 == 0 and jax.default_backend() == "tpu":
        return "flash"
    key = (seq_len, jax.default_backend())
    if key not in _warned_attn_fallback:
        _warned_attn_fallback.add(key)
        logger.info(
            "attn_impl='auto': %s; using dense attention",
            f"T={seq_len} is not 128-lane-aligned"
            if seq_len % 128
            else f"backend={jax.default_backend()} runs pallas interpreted",
        )
    return "dense"


def _make_block(
    cfg: TransformerConfig, mesh: "Optional[Mesh]", manual_cp: bool = False
):
    """Returns block(x, layer_params, positions) -> x for one decoder layer.

    ``manual_cp``: the block runs inside an existing manual shard_map
    context over ``cp_axis`` (e.g. the pipeline's) — attention calls the
    local ring body directly instead of opening its own shard_map, and
    ``positions=None`` makes the block derive global rotary positions from
    its cp shard index.
    """
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    act = cfg.dtype

    def attention(q, k, v):
        impl = _resolve_attn_impl(cfg, mesh, manual_cp, q.shape[1])
        if manual_cp:
            if impl == "ring":
                # the pipeline's shard_map is partial-auto, which rejects
                # pallas lowering — keep the jnp tile body there
                return ring_attention_local(
                    q, k, v, axis_name=cfg.cp_axis, causal=True,
                    use_flash=False,
                )
            if impl == "ulysses":
                # same partial-auto shard_map constraint as ring above:
                # no pallas lowering inside the pipeline's blocks
                return ulysses_attention_local(
                    q, k, v, axis_name=cfg.cp_axis, causal=True,
                    use_flash=False,
                )
            raise ValueError(
                "manual-cp blocks support ring or ulysses attention only"
            )
        if impl in ("ring", "ulysses"):
            if mesh is None:
                raise ValueError(f"{impl} attention requires a mesh")
            if cfg.cp_axis not in mesh.axis_names:
                raise ValueError(
                    f"{impl} attention requires a {cfg.cp_axis!r} "
                    f"mesh axis; this mesh has {mesh.axis_names} "
                    "(use attn_impl='dense' on cp-less meshes)"
                )
            local_fn = (
                ring_attention_local
                if impl == "ring"
                else ulysses_attention_local
            )
            k, v = _expand_kv_for_tp(cfg, mesh, nh, k, v)
            spec = _filter_spec(
                P(_batch_axes(cfg, mesh), cfg.cp_axis, cfg.tp_axis, None), mesh
            )
            fn = jax.shard_map(
                lambda q_, k_, v_: local_fn(
                    q_, k_, v_, axis_name=cfg.cp_axis, causal=True
                ),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                # the ring body may lower to pallas_call (flash tiles)
                check_vma=False,
            )
            return fn(q, k, v)
        if impl == "flash":
            from torchft_tpu.ops.flash_attention import flash_attention

            if mesh is None:
                return flash_attention(q, k, v, causal=True)
            if isinstance(mesh, str):
                raise ValueError(
                    "attn_impl='flash' does not nest in manual shard_map "
                    "contexts; use 'ring'/'ulysses' there"
                )
            # batch/head-parallel over the mesh: each shard holds the FULL
            # sequence (flash is not sequence-parallel — use ring/ulysses
            # for cp) and runs the kernel on its [B/dp.., T, H/tp, D] shard
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            if sizes.get(cfg.cp_axis, 1) > 1:
                raise ValueError(
                    "attn_impl='flash' needs the sequence unsharded; on a "
                    f"{cfg.cp_axis!r} mesh use 'ring' or 'ulysses'"
                )
            k, v = _expand_kv_for_tp(cfg, mesh, nh, k, v)
            spec = _filter_spec(
                P(_batch_axes(cfg, mesh), None, cfg.tp_axis, None), mesh
            )
            fn = jax.shard_map(
                lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=True),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                # pallas_call's out_shape carries no vma annotation; the
                # kernel is per-shard elementwise in the mesh sense
                check_vma=False,
            )
            return fn(q, k, v)
        if impl != "dense":
            raise ValueError(
                f"unknown attn_impl {impl!r}; "
                "expected 'dense', 'flash', 'ring', or 'ulysses'"
            )
        return dense_attention(q, k, v, causal=True)

    def block(x: jax.Array, p: Params, positions: "Optional[jax.Array]"):
        b, t, e = x.shape
        if positions is None:
            # manual-cp context: x is the local sequence chunk; rotary
            # embeddings need GLOBAL positions, derived from the shard index
            offset = jax.lax.axis_index(cfg.cp_axis) * t
            positions = offset + jnp.arange(t)
        h = _rms_norm(x, p["attn_norm"])
        q = (h @ p["wq"].astype(act)).reshape(b, t, nh, hd)
        k = (h @ p["wk"].astype(act)).reshape(b, t, nkv, hd)
        v = (h @ p["wv"].astype(act)).reshape(b, t, nkv, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        # GQA kv heads stay unexpanded: each attention impl broadcasts them
        # up AFTER any cross-device transfer (ring ppermute / ulysses
        # all-to-all move nkv, not nh, heads of K/V)
        attn = attention(q, k, v).reshape(b, t, nh * hd)
        x = x + attn @ p["wo"].astype(act)

        h = _rms_norm(x, p["mlp_norm"])
        if cfg.n_experts:
            from torchft_tpu.models.moe import moe_ffn

            y, aux = moe_ffn(h, p, _moe_cfg(cfg), mesh=mesh)
            return x + y, aux
        gate = jax.nn.silu(h @ p["w_gate"].astype(act))
        up = h @ p["w_up"].astype(act)
        x = x + (gate * up) @ p["w_down"].astype(act)
        return x, jnp.zeros((), jnp.float32)

    return block


def _remat(fn, cfg: TransformerConfig):
    """Apply cfg's rematerialization policy to a block function."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_saveable
        )
    if cfg.remat_policy != "full":
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r}; expected 'full' or 'dots'"
        )
    return jax.checkpoint(fn)



def forward(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: "Optional[Mesh]" = None,
    return_aux: bool = False,
) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] (fp32).

    With a mesh, activations get sharding constraints so XLA places the tp
    collectives; without one it is a plain single-device program (the
    `entry()` compile-check path). ``return_aux`` additionally returns the
    summed MoE load-balance loss (0 for dense FFN configs).
    """
    b, t = tokens.shape
    x = _embed(params, tokens, cfg, sharded=mesh is not None)
    positions = jnp.arange(t)

    if mesh is not None:
        act_spec = NamedSharding(
            mesh, P(_batch_axes(cfg, mesh), _seq_axis(cfg, mesh), None)
        )
        x = jax.lax.with_sharding_constraint(x, act_spec)

    block = _make_block(cfg, mesh)
    if cfg.remat:
        block = _remat(block, cfg)

    def scan_body(carry, layer_params):
        x, aux_sum = carry
        x, aux = block(x, layer_params, positions)
        if mesh is not None:
            x = jax.lax.with_sharding_constraint(x, act_spec)
        return (x, aux_sum + aux), None

    (x, aux_sum), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["blocks"]
    )
    logits = _head(params, x, cfg)
    if return_aux:
        return logits, aux_sum
    return logits


def _embed(
    params: Params, tokens: jax.Array, cfg: TransformerConfig, sharded: bool
) -> jax.Array:
    """Token embedding [B, T] -> [B, T, E].

    Sharded path: one-hot matmul instead of gather — runs on the MXU and
    partitions cleanly when embed is sharded (tp, fsdp); XLA's SPMD
    partitioner fully rematerializes a sharded gather.
    """
    act = cfg.dtype
    if sharded:
        return jnp.einsum(
            "btv,ve->bte",
            jax.nn.one_hot(tokens, cfg.vocab_size, dtype=act),
            params["embed"].astype(act),
        )
    return params["embed"].astype(act)[tokens]


def _head(params: Params, x: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Final norm + tied output head: [B,T,E] x [E,V] on the MXU.

    The matmul runs in the ACTIVATION dtype (bf16 on TPU) with f32
    accumulation — at V=32k this is the largest single matmul in the
    model, and running it f32 costs the MXU's 3-pass f32 emulation on the
    ~10% of model FLOPs it represents (measured +1.5 MFU points on the
    flagship from this cast alone).  Logits come out f32 from the
    accumulator."""
    x = _rms_norm(x, params["final_norm"])
    return jnp.einsum(
        "bte,ve->btv",
        x.astype(cfg.dtype),
        params["embed"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )


def forward_pipelined(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: Mesh,
    microbatches: int = 4,
    pp_axis: str = "pp",
    return_aux: bool = False,
) -> "jax.Array | tuple":
    """Pipeline-parallel forward: decoder blocks GPipe-scheduled over the
    ``pp`` mesh axis (torchft_tpu/parallel/pipeline.py), embedding/head
    outside the pipe.

    Each stage holds ``n_layers / pp`` consecutive blocks (the stacked
    layer dim is sharded over pp). Composes with the other parallelism
    axes:

    - ``attn_impl='ring'`` / ``'ulysses'`` with a ``cp`` mesh axis: the
      pipeline shard_map goes manual over (pp, cp) and each stage runs the
      local sequence-parallel body (K/V ppermute ring / head all-to-all);
    - ``n_experts > 0`` (MoE / ep): expert FFNs run inside the stage; the
      load-balance aux loss rides the pipe as a side stream of the
      activation pytree and is returned with ``return_aux=True``. Aux is
      computed per microbatch (batch statistics over each microbatch
      rather than the full batch — an equally valid estimator).
    """
    if cfg.attn_impl == "auto":
        # inside the pipe, flash never applies (the pipeline's
        # partial-auto shard_map rejects pallas lowering): auto means
        # ring when the sequence is cp-sharded, dense otherwise
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        cfg = dataclasses.replace(
            cfg,
            attn_impl="ring" if sizes.get(cfg.cp_axis, 1) > 1 else "dense",
        )
    manual_cp = cfg.attn_impl in ("ring", "ulysses")
    if cfg.attn_impl not in ("dense", "ring", "ulysses"):
        raise ValueError(
            f"forward_pipelined does not support attn_impl "
            f"{cfg.attn_impl!r}; expected 'dense', 'ring', or 'ulysses' "
            "('flash' does not compose with the pipeline's manual "
            "shard_map — use ring/ulysses for sequence parallelism "
            "inside the pipe)"
        )
    if manual_cp and cfg.cp_axis not in mesh.axis_names:
        raise ValueError(
            f"{cfg.attn_impl} attention requires a {cfg.cp_axis!r} mesh "
            f"axis; this mesh has {mesh.axis_names}"
        )
    from torchft_tpu.parallel.pipeline import pipeline_apply

    b, t = tokens.shape
    x = _embed(params, tokens, cfg, sharded=True)
    positions = None if manual_cp else jnp.arange(t)
    # MoE blocks pin their [E, C, d] expert buffers to the ep axis inside
    # the pipeline's partial-manual shard_map — via a bare-PartitionSpec
    # constraint ("manual" sentinel), since ep stays automatic in there
    moe_mesh = (
        "manual" if cfg.n_experts and cfg.ep_axis in mesh.axis_names else None
    )
    block = _make_block(cfg, moe_mesh, manual_cp=manual_cp)

    moe = bool(cfg.n_experts)

    def layer_fn(h, layer_params):
        # non-MoE: plain array activations — no dead aux stream riding the
        # pipe (it would cost a ppermute + scatter per tick for zeros)
        if not moe:
            return block(h, layer_params, positions)[0]
        y, aux = block(h["x"], layer_params, positions)
        if manual_cp:
            # aux is computed from this cp shard's local tokens: average
            # over cp for the global-batch statistic (also makes the value
            # cp-invariant, which the pipe's carry signature requires)
            aux = jax.lax.pmean(aux, cfg.cp_axis)
        return {"x": y, "aux": h["aux"] + aux}

    if cfg.remat:
        layer_fn = _remat(layer_fn, cfg)

    # pipeline_apply is partial-manual over pp (+cp for ring/ulysses):
    # batch (dp/fsdp/ep) and weight (fsdp/tp) shardings flow automatically
    # from input shardings; MoE adds a per-example aux side stream
    out = pipeline_apply(
        params["blocks"],
        {"x": x, "aux": jnp.zeros((b,), jnp.float32)} if moe else x,
        layer_fn,
        mesh,
        axis_name=pp_axis,
        microbatches=microbatches,
        seq_axis=cfg.cp_axis if manual_cp else None,
    )
    logits = _head(params, out["x"] if moe else out, cfg)
    if return_aux:
        return logits, out["aux"].mean() if moe else jnp.zeros((), jnp.float32)
    return logits


def loss_fn(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh: "Optional[Mesh]" = None,
) -> jax.Array:
    """Next-token cross-entropy, mean over all positions but the last.
    MoE configs add the weighted load-balance auxiliary loss."""
    logits, aux = forward(params, tokens, cfg, mesh, return_aux=True)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    # fused NLL: logsumexp(logits) - logit[target] == -log_softmax[target]
    # without materializing the full [B, T, V] log-probability tensor (at
    # flagship scale that tensor is ~1 GB of f32 HBM write+read per step)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    loss = (lse - picked).mean()
    if cfg.n_experts:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


# ---------------------------------------------------------------------------
# training step
# ---------------------------------------------------------------------------


def make_train_step(
    cfg: TransformerConfig,
    optimizer: Any,
    mesh: "Optional[Mesh]" = None,
    donate: bool = True,
):
    """Build a jitted (params, opt_state, tokens) -> (params, opt_state, loss)
    full training step (fwd + bwd + optax update). With a mesh, in/out
    shardings pin params to `param_specs` and the batch to `batch_spec`."""

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1) if donate else ())

    pspecs = param_specs(cfg, mesh)
    param_sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), pspecs)
    batch_sh = NamedSharding(mesh, batch_spec(cfg, mesh))
    return jax.jit(
        step,
        in_shardings=(param_sh, None, batch_sh),
        out_shardings=(param_sh, None, None),
        donate_argnums=(0, 1) if donate else (),
    )


def make_grad_step(
    cfg: TransformerConfig, mesh: "Optional[Mesh]" = None
):
    """Build a jitted (params, tokens) -> (loss, grads) step — the FT-DDP
    shape: grads come back to the host, `Manager.allreduce` averages them
    across replica groups over DCN, then `apply_updates` runs (reference
    ddp.py:47-79 comm-hook factored the same way)."""

    def step(params, tokens):
        return jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)

    if mesh is None:
        return jax.jit(step)
    pspecs = param_specs(cfg, mesh)
    param_sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), pspecs)
    batch_sh = NamedSharding(mesh, batch_spec(cfg, mesh))
    return jax.jit(
        step,
        in_shardings=(param_sh, batch_sh),
        out_shardings=(None, param_sh),
    )
