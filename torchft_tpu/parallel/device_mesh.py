"""FT-aware device mesh composition (the HSDP story).

Analog of the reference's ManagedDeviceMesh (reference:
torchft/device_mesh.py:51-340) — but designed the JAX way.  The reference
must *lie* to torch's DeviceMesh (registering a fake world-size-1 backend)
because torch parallelism APIs demand every dim be a real process group.  In
JAX, inner parallelism (FSDP/TP/SP over ICI within a slice) is a
``jax.sharding.Mesh`` + pjit shardings, and the elastic replica dimension
lives *above* jit entirely: the FT allreduce runs on host gradients between
jitted steps.  So the composition is explicit rather than spoofed:

- ``ManagedDeviceMesh.mesh`` — the static inner mesh handed to pjit; its
  membership never changes (a slice is fault-free by assumption; if a chip
  dies, the whole replica group dies and heals as a unit).
- the replicate dim is virtual: ``num_participants`` / ``replica_rank`` are
  live quorum values used for loss scaling and data sharding.

Zero-fill + divide-by-participants keeps compiled shapes static, so
membership changes never trigger a re-jit (SURVEY §7 / reference
manager.py:416-417).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from torchft_tpu.manager import Manager


class ManagedDeviceMesh:
    """An inner JAX mesh plus the elastic FT replicate dimension.

    With a :class:`~torchft_tpu.parallel.layout.LayoutController`
    attached (:meth:`attach_layout`), the replicate dimension itself
    becomes a live ``dp x shard x pp`` grid: on every committed layout
    switch the mesh re-forms its cross-group process groups (the dp row
    this group averages gradients with, and the shard column it
    re-partitions parameters across) against the quorum store under a
    per-epoch prefix — so collectives after the switch can never mix
    layout generations — and ``global_batch_slice`` partitions the batch
    over the ``dp`` dimension only (shard/pp peers of one replica train
    the same examples).

    Args:
        manager: FT manager owning the replica dimension.
        mesh: inner ``jax.sharding.Mesh`` (ICI dims: fsdp/tp/sp/...).
        replicate_dim_name: name reported for the virtual FT dim.
    """

    def __init__(
        self,
        manager: Manager,
        mesh: "jax.sharding.Mesh",
        replicate_dim_name: str = "dp_replicate",
    ) -> None:
        self._manager = manager
        self.mesh = mesh
        self.replicate_dim_name = replicate_dim_name
        self._layout_ctrl: "Optional[Any]" = None
        self._row_pg: "Optional[Any]" = None
        self._col_pg: "Optional[Any]" = None
        self._grid_rank: "Optional[int]" = None

    # -- online parallelism switching (parallel/layout.py) -----------------

    def attach_layout(
        self,
        controller: Any,
        row_pg: "Optional[Any]" = None,
        col_pg: "Optional[Any]" = None,
    ) -> Any:
        """Subscribe this mesh to layout commits.  ``row_pg`` (optional)
        is re-configured over the dp row (same shard+pp coordinates) on
        every committed switch; ``col_pg`` over the shard column (same
        dp+pp coordinates) — the process groups an HSDP-across-groups
        algorithm reduces over.  Returns the controller."""
        self._layout_ctrl = controller
        self._row_pg = row_pg
        self._col_pg = col_pg
        controller.add_listener(self._on_layout_commit)
        return controller

    def _on_layout_commit(self, layout: Any, info: "Dict[str, Any]") -> None:
        """Re-form the cross-group process groups for the new grid.  The
        store prefix embeds the layout epoch, so two generations can
        never rendezvous with each other — every replica switches at the
        same quorum round, making this a fleet-synchronous reconfigure."""
        rank = info.get("rank")
        self._grid_rank = rank
        if rank is None:
            return
        dp_rank, shard_rank, pp_rank = layout.coords(rank)
        store = info.get("store_address", "")
        replica_id = self._manager.replica_id()
        if self._row_pg is not None and store:
            self._row_pg.configure(
                f"{store}/torchft/layout/{layout.epoch}/row/"
                f"{shard_rank}_{pp_rank}/{dp_rank}",
                replica_id,
                dp_rank,
                layout.dp,
            )
        if self._col_pg is not None and store:
            self._col_pg.configure(
                f"{store}/torchft/layout/{layout.epoch}/col/"
                f"{dp_rank}_{pp_rank}/{shard_rank}",
                replica_id,
                shard_rank,
                layout.shard,
            )

    def layout(self) -> "Optional[Any]":
        """The active (dp, shard, pp) layout, or None when no controller
        is attached / nothing committed yet."""
        if self._layout_ctrl is None:
            return None
        return self._layout_ctrl.active_layout()

    def row_pg(self) -> "Optional[Any]":
        return self._row_pg

    def col_pg(self) -> "Optional[Any]":
        return self._col_pg

    # -- virtual replicate dim (live quorum values) ------------------------

    def num_participants(self) -> int:
        return self._manager.num_participants()

    def replica_rank(self) -> "Optional[int]":
        return self._manager.participating_rank()

    def is_participating(self) -> bool:
        return self._manager.is_participating()

    # -- composed topology -------------------------------------------------

    @property
    def axis_names(self) -> "Tuple[str, ...]":
        return (self.replicate_dim_name,) + tuple(self.mesh.axis_names)

    def shape(self) -> "Dict[str, int]":
        """Axis sizes; the replicate dim reports the live participant count
        (>=1 during 0-participant init, mirroring reference :169-184)."""
        sizes = {self.replicate_dim_name: max(self.num_participants(), 1)}
        sizes.update(dict(zip(self.mesh.axis_names, self.mesh.devices.shape)))
        return sizes

    def global_batch_slice(self, global_batch_size: int) -> "Tuple[int, int]":
        """This replica's contiguous [start, end) share of the global batch,
        given the live quorum (DistributedSampler analog at batch level).

        Returns the empty slice (0, 0) while not participating (healing /
        no quorum yet) — defaulting to rank 0's slice would silently train
        on another replica's data.

        Partition contract (property-tested across shrink/grow in
        tests/test_layout.py): over the participating ranks the slices
        tile [0, global_batch_size) exactly — no overlap, no gap — under
        ANY participant count, including counts larger than the batch.
        With a committed layout whose grid matches the live participant
        count, the batch partitions over the ``dp`` dimension only and
        shard/pp peers of one dp replica receive the same slice."""
        rank = self.replica_rank()
        if rank is None or not self.is_participating():
            return 0, 0
        n = max(self.num_participants(), 1)
        layout = self.layout()
        if layout is not None and layout.world == n and layout.dp != n:
            # dp-dim slicing: shard/pp peers train the same examples.
            # Guarded on the grid matching the live count — mid-switch
            # (membership changed, commit pending) the flat partition
            # below keeps the tiling exact.
            dp_rank, _, _ = layout.coords(rank)
            rank, n = dp_rank, layout.dp
        per, rem = divmod(global_batch_size, n)
        # first `rem` ranks take one extra example so every example in the
        # global batch is assigned under any elastic membership
        start = rank * per + min(rank, rem)
        end = start + per + (1 if rank < rem else 0)
        return start, end

    def __repr__(self) -> str:
        return (
            f"ManagedDeviceMesh({self.replicate_dim_name}="
            f"{max(self.num_participants(), 1)} x inner {self.mesh!r})"
        )


def ft_init_device_mesh(
    manager: Manager,
    mesh_shape: "Dict[str, int]",
    devices: "Optional[Sequence[Any]]" = None,
    replicate_dim_name: str = "dp_replicate",
) -> ManagedDeviceMesh:
    """Build the inner mesh over this replica group's devices and wrap it
    with the FT dim (reference ft_init_device_mesh, device_mesh.py:307-340).

    ``mesh_shape`` maps inner axis names to sizes, e.g.
    ``{"fsdp": 4, "tp": 2}``; the product must equal the local device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    total = int(np.prod(list(mesh_shape.values()), dtype=np.int64))
    if total != len(devices):
        raise ValueError(
            f"mesh_shape {mesh_shape} needs {total} devices, have {len(devices)}"
        )
    dev_array = np.array(devices).reshape(tuple(mesh_shape.values()))
    mesh = jax.sharding.Mesh(dev_array, tuple(mesh_shape.keys()))
    return ManagedDeviceMesh(manager, mesh, replicate_dim_name)
