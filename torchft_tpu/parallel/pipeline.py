"""Pipeline parallelism: a GPipe schedule over a ``pp`` mesh axis.

A TPU-first capability beyond the reference (which has no pipeline
schedule — SURVEY §2.3: torch pipelining appears there only as a
model-splitting tool for DiLoCo fragments). Layer-stacked parameters
``[L, ...]`` are sharded over the ``pp`` axis (each stage holds ``L/S``
consecutive layers); inside ``shard_map`` the classic GPipe tick loop runs
as a ``lax.scan``: at tick ``t`` stage ``s`` processes microbatch
``t - s``, then activations hop one stage forward via neighbor
``ppermute`` (riding ICI). Reverse-mode AD through the scan + ppermute
gives the backward schedule for free.

Shapes are fully static: every stage computes every tick (bubble ticks are
masked with ``where``), so the whole schedule jits once. Bubble overhead is
the standard ``(S-1)/(M+S-1)`` — pick ``microbatches >= 4*stages`` to
amortize.

Composes with the other axes: the per-stage ``fn`` may itself use tp/cp
collectives (its shard_map axis names remain visible), and dp/fsdp shard
the microbatch dim through ``in_specs``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Params = Any


def _stage_apply(
    fn: "Callable[[jax.Array, Params], jax.Array]",
    x: jax.Array,
    stage_params: Params,
) -> jax.Array:
    """Run this stage's local layer stack ``[L/S, ...]`` over x."""

    def body(h, layer_params):
        return fn(h, layer_params), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def pipeline_apply_local(
    params: Params,
    microbatches: jax.Array,
    fn: "Callable[[jax.Array, Params], jax.Array]",
    axis_name: str = "pp",
) -> jax.Array:
    """Per-shard GPipe body; must run inside shard_map over ``axis_name``.

    Args:
        params: this stage's layer stack, pytree with leading ``[L/S]`` dim.
        microbatches: ``[M, mb, ...]`` — full microbatch set (replicated
            across stages; only stage 0 feeds it into the pipe).
        fn: one decoder-layer step ``fn(x, layer_params) -> x``.

    Returns ``[M, mb, ...]`` outputs, identical on every stage (the last
    stage's results are broadcast back via psum).
    """
    stage = jax.lax.axis_index(axis_name)
    size = jax.lax.axis_size(axis_name)
    m = microbatches.shape[0]
    n_ticks = m + size - 1
    perm_fwd = [(i, i + 1) for i in range(size - 1)]

    def tick(carry, t):
        buf, outputs = carry
        mb_idx = t - stage
        active = (mb_idx >= 0) & (mb_idx < m)
        # stage 0 pulls the next microbatch; later stages consume the
        # activation that hopped in last tick
        feed = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(mb_idx, 0, m - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(stage == 0, feed, buf)
        y = _stage_apply(fn, x_in, params)
        # bubble ticks produce garbage; zero it so the output scatter and
        # the ppermute hand clean values downstream
        y = jnp.where(active, y, jnp.zeros_like(y))
        is_last = stage == size - 1
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(
                active & is_last,
                y,
                jax.lax.dynamic_index_in_dim(
                    outputs, jnp.clip(mb_idx, 0, m - 1), axis=0, keepdims=False
                ),
            ),
            jnp.clip(mb_idx, 0, m - 1),
            axis=0,
        )
        buf = jax.lax.ppermute(y, axis_name, perm_fwd)
        return (buf, outputs), None

    # pvary: the carry becomes device-varying after one tick (it depends on
    # the stage index), so the initial carry must carry the same varying-
    # axis type or scan rejects the carry signature (shard_map vma rule)
    _pcast = getattr(jax.lax, "pcast", None)
    if _pcast is not None:
        buf0 = _pcast(jnp.zeros_like(microbatches[0]), axis_name, to="varying")
        out0 = _pcast(jnp.zeros_like(microbatches), axis_name, to="varying")
    else:  # older jax
        buf0 = jax.lax.pvary(jnp.zeros_like(microbatches[0]), (axis_name,))
        out0 = jax.lax.pvary(jnp.zeros_like(microbatches), (axis_name,))
    (_, outputs), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(n_ticks))
    # only the last stage holds real outputs; broadcast to all stages
    return jax.lax.psum(
        jnp.where(stage == size - 1, outputs, jnp.zeros_like(outputs)), axis_name
    )


def pipeline_apply(
    params: Params,
    x: jax.Array,
    fn: "Callable[[jax.Array, Params], jax.Array]",
    mesh: Mesh,
    axis_name: str = "pp",
    microbatches: int = 4,
    batch_axes: "Optional[tuple]" = None,
    seq_axis: "Optional[str]" = None,
    seq_dim: int = 1,
) -> jax.Array:
    """GPipe-apply a stacked-layer model over the ``pp`` mesh axis.

    The shard_map is *partial-manual* (``axis_names={pp[, seq_axis]}``):
    only the pipeline axis (and, when given, the sequence-parallel axis the
    stage fn handles itself, e.g. ring attention over cp) is manual; every
    other mesh axis stays automatic, so dp/fsdp batch sharding and fsdp/tp
    weight sharding flow through from the inputs' shardings with XLA
    placing the collectives — stage weights are NOT replicated.

    Args:
        params: pytree with leading layer dim ``[L]``; ``L`` must divide by
            the pp axis size (each stage takes ``L/S`` consecutive layers).
        x: ``[B, ...]`` activations; ``B`` must divide by ``microbatches``.
        fn: one layer step ``fn(x_mb, layer_params) -> x_mb``. With
            ``seq_axis`` the fn runs in manual context over that axis too
            (it may call e.g. ring_attention_local over it) and receives
            the local sequence chunk.
        mesh: mesh containing ``axis_name``.
        microbatches: GPipe microbatch count M (bubble = (S-1)/(M+S-1)).
        batch_axes: unused (kept for call-site stability); batch sharding
            over dp/fsdp/ep is automatic in partial-manual mode.
        seq_axis: optional mesh axis the sequence dim is sharded over
            (manual: the stage fn owns its collectives).
        seq_dim: which dim of ``x`` is the sequence (default 1, [B, T, E]).

    Returns ``[B, ...]`` outputs with x's sharding.
    """
    del batch_axes  # automatic in partial-manual mode
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis_name!r} axis: {mesh.axis_names}")
    if seq_axis is not None and seq_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {seq_axis!r} axis: {mesh.axis_names}")
    stages = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    n_layers = jax.tree_util.tree_leaves(params)[0].shape[0]
    if n_layers % stages != 0:
        raise ValueError(
            f"layer count {n_layers} not divisible by pp axis size {stages}"
        )
    b = x.shape[0]
    if b % microbatches != 0:
        raise ValueError(f"batch {b} not divisible by microbatches {microbatches}")
    mb = b // microbatches
    x_mb = x.reshape((microbatches, mb) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), params
    )
    data_entries: "list" = [None] * (x.ndim + 1)
    if seq_axis is not None:
        data_entries[seq_dim + 1] = seq_axis  # +1 for the microbatch dim
    data_spec = P(*data_entries)

    manual = {axis_name} if seq_axis is None else {axis_name, seq_axis}
    out = jax.shard_map(
        functools.partial(pipeline_apply_local, fn=fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, data_spec),
        out_specs=data_spec,
        axis_names=manual,
    )(params, x_mb)
    return out.reshape(x.shape)


__all__ = ["pipeline_apply", "pipeline_apply_local"]
