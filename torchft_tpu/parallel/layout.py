"""Online parallelism switching: layout planning + live resharding.

ROADMAP item 4 (DynaTrain, PAPERS.md): when quorum membership changes,
the fleet should not just resize the elastic DP dimension — it should
re-plan the whole (dp, shard, pp) layout and re-shard parameters live,
so the job continuously fits the hardware it has instead of degrading
permanently on a shrink or wasting a grow.

Three pieces, all deterministic so every replica group computes the same
answer from the same quorum result with zero extra coordination:

- **Planner** (:func:`plan_layout`): given the live participant count and
  declared :class:`LayoutConstraints` (divisibility, min DP for the
  batch, per-group memory ceiling), pick the best feasible
  :class:`Layout` under a total ordering (max dp, then min pp, then
  least movement vs the previous layout).
- **Epoch state machine** (:class:`LayoutState`): layouts activate under
  a monotone **layout epoch** stamped into the quorum round.  Two-phase:
  *plan+stage* during the step the membership change was observed
  (transfers run on the async-quorum thread, exactly like heal), then
  *commit* at the next quorum iff every participant reports the staged
  epoch (``min == max == E`` on the wire) — so the whole fleet switches
  at the same step or not at all.  A failed stage anywhere rolls the
  whole fleet back to the old layout and **burns** the epoch (a
  rolled-back epoch is never reused — the tft-verify ``resize`` model
  proves both properties and catches the seeded violations).
- **Reshard data path** (:class:`LayoutController`): each group computes
  the slice diff between its old and new shardings and fetches only the
  missing intervals from their current owners over the HTTP
  checkpoint-streaming machinery — heal generalized from "copy
  everything from one peer" to "re-layout from many peers".  Transfers
  ride the existing retry/backoff policy (the transport's 503-poll
  fetch policy); any failure aborts cleanly to the old layout: degrade,
  never wedge.

Sharding model: the elastic units are replica groups arranged in a
``dp x shard x pp`` grid (``world = dp * shard * pp``).  ``dp`` is the
replication degree (today's only dimension); ``shard`` partitions each
registered state leaf's flat element space; ``pp`` partitions it again
(layer-major, folded into one combined shard index ``shard * pp`` for
the host data path).  The inner per-group JAX mesh is untouched — this
module moves host state between groups.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.logging import log_event

logger = logging.getLogger(__name__)

__all__ = [
    "Layout",
    "LayoutConstraints",
    "LayoutError",
    "ReshardError",
    "plan_layout",
    "feasible_layouts",
    "partition",
    "shard_interval",
    "interval_subtract",
    "interval_intersect",
    "plan_fetches",
    "LayoutState",
    "LayoutController",
    "RESHARD_STEP_KEY",
]


class LayoutError(RuntimeError):
    """No feasible layout exists for the given world + constraints."""


class ReshardError(RuntimeError):
    """A reshard transfer failed or left coverage gaps; the switch must
    roll back to the old layout."""


class Layout(NamedTuple):
    """One (dp, shard, pp) placement of ``world = dp*shard*pp`` replica
    groups, stamped with the monotone epoch it was planned under."""

    dp: int
    shard: int
    pp: int
    epoch: int

    @property
    def world(self) -> int:
        return self.dp * self.shard * self.pp

    @property
    def nshards(self) -> int:
        """Combined data-path shard count (``shard * pp``: pp stages own
        layer-major contiguous intervals of the flat element space)."""
        return self.shard * self.pp

    def coords(self, rank: int) -> "Tuple[int, int, int]":
        """``rank -> (dp_rank, shard_rank, pp_rank)``; rank is the
        group's index in the quorum's replica-id-sorted participant
        list (dp-major, then shard, then pp)."""
        if not (0 <= rank < self.world):
            raise ValueError(f"rank {rank} outside world {self.world}")
        dp_rank, rem = divmod(rank, self.shard * self.pp)
        shard_rank, pp_rank = divmod(rem, self.pp)
        return dp_rank, shard_rank, pp_rank

    def shard_index(self, rank: int) -> int:
        """Combined data-path shard index of ``rank`` in [0, nshards)."""
        _, shard_rank, pp_rank = self.coords(rank)
        return shard_rank * self.pp + pp_rank

    def key(self) -> "Tuple[int, int, int]":
        """Layout identity without the epoch stamp."""
        return (self.dp, self.shard, self.pp)


@dataclass(frozen=True)
class LayoutConstraints:
    """Declared feasibility constraints for the planner.

    Args:
        min_dp: minimum data-parallel degree (the effective-batch floor;
            a layout with fewer replicas than this is infeasible).
        layers: model layer count — ``pp`` must divide it.
        global_batch_size: if > 0, ``dp`` may not exceed it (a replica
            with an empty batch slice contributes nothing).
        param_bytes: total model state bytes (the sharded surface).
        shard_memory_bytes: per-group memory ceiling; if > 0 a layout is
            feasible only when ``ceil(param_bytes / nshards) <= ceiling``
            — the knob that FORCES shard growth on a shrink.
        max_pp: maximum pipeline depth to consider (1 = pp disabled).
    """

    min_dp: int = 1
    layers: int = 1
    global_batch_size: int = 0
    param_bytes: int = 0
    shard_memory_bytes: int = 0
    max_pp: int = 1

    def __post_init__(self) -> None:
        if self.min_dp < 1:
            raise ValueError(f"min_dp must be >= 1, got {self.min_dp}")
        if self.layers < 1:
            raise ValueError(f"layers must be >= 1, got {self.layers}")
        if self.max_pp < 1:
            raise ValueError(f"max_pp must be >= 1, got {self.max_pp}")


def _divisors(n: int) -> "List[int]":
    return [d for d in range(1, n + 1) if n % d == 0]


def feasible_layouts(
    world: int, constraints: LayoutConstraints
) -> "List[Tuple[int, int, int]]":
    """All (dp, shard, pp) triples with ``dp*shard*pp == world`` that
    satisfy the constraints, unordered."""
    if world < 1:
        return []
    out: "List[Tuple[int, int, int]]" = []
    for dp in _divisors(world):
        if dp < constraints.min_dp:
            continue
        if 0 < constraints.global_batch_size < dp:
            continue
        inner = world // dp
        for pp in _divisors(inner):
            if pp > constraints.max_pp or constraints.layers % pp != 0:
                continue
            shard = inner // pp
            nshards = shard * pp
            if constraints.shard_memory_bytes > 0 and constraints.param_bytes > 0:
                per = -(-constraints.param_bytes // nshards)  # ceil div
                if per > constraints.shard_memory_bytes:
                    continue
            out.append((dp, shard, pp))
    return out


def plan_layout(
    world: int,
    constraints: LayoutConstraints,
    prev: "Optional[Layout]" = None,
    epoch: int = 0,
) -> Layout:
    """Pick the best feasible layout for ``world`` groups, deterministically.

    Total ordering (so every replica picks the same plan from the same
    quorum): maximize ``dp`` (throughput), then minimize ``pp`` (bubble),
    then minimize shard-count movement vs ``prev`` (reshard bytes), then
    the smallest shard count.  Raises :class:`LayoutError` when nothing
    is feasible (e.g. the memory ceiling cannot be met at this world) —
    the caller keeps the old layout and degrades.
    """
    options = feasible_layouts(world, constraints)
    if not options:
        raise LayoutError(
            f"no feasible (dp, shard, pp) layout for world={world} under "
            f"{constraints}"
        )
    prev_nshards = prev.nshards if prev is not None else 1

    def score(opt: "Tuple[int, int, int]") -> "Tuple[int, int, int, int]":
        dp, shard, pp = opt
        return (-dp, pp, abs(shard * pp - prev_nshards), shard * pp)

    best = min(options, key=score)
    return Layout(dp=best[0], shard=best[1], pp=best[2], epoch=epoch)


# ---------------------------------------------------------------------------
# interval math (the slice-diff engine; all [start, end) half-open)
# ---------------------------------------------------------------------------

Interval = Tuple[int, int]


def partition(n: int, k: int) -> "List[Interval]":
    """Split [0, n) into k contiguous intervals, first ``n % k`` one
    element longer — the same math as ``global_batch_slice`` so every
    element is owned under any k."""
    per, rem = divmod(n, k)
    out: "List[Interval]" = []
    start = 0
    for i in range(k):
        end = start + per + (1 if i < rem else 0)
        out.append((start, end))
        start = end
    return out


def shard_interval(n: int, shard_rank: int, nshards: int) -> Interval:
    """This shard's contiguous [start, end) of an ``n``-element leaf."""
    return partition(n, nshards)[shard_rank]


def interval_intersect(a: Interval, b: Interval) -> "Optional[Interval]":
    lo, hi = max(a[0], b[0]), min(a[1], b[1])
    return (lo, hi) if lo < hi else None


def interval_subtract(a: Interval, holes: "List[Interval]") -> "List[Interval]":
    """``a`` minus the union of ``holes`` as a sorted interval list."""
    out: "List[Interval]" = []
    cursor = a[0]
    for h in sorted(holes):
        cut = interval_intersect(a, h)
        if cut is None:
            continue
        if cut[0] > cursor:
            out.append((cursor, cut[0]))
        cursor = max(cursor, cut[1])
    if cursor < a[1]:
        out.append((cursor, a[1]))
    return out


def plan_fetches(
    need: Interval,
    have: "List[Interval]",
    owners: "List[Tuple[int, Interval]]",
) -> "Dict[int, List[Interval]]":
    """The slice diff: which intervals of ``need`` must be fetched from
    which owner.

    ``have`` is data already held locally (skipped); ``owners`` is an
    ORDERED list of (owner_rank, owned_interval) — when several owners
    cover the same missing piece the first in the list serves it, so
    both sides compute the identical assignment by using the same
    ordering.  Returns {owner_rank: [intervals]}, covering exactly
    ``need`` minus ``have`` (a remainder means no owner covers a piece —
    the caller must treat that as a failed reshard).
    """
    missing = interval_subtract(need, list(have))
    out: "Dict[int, List[Interval]]" = {}
    for owner_rank, owned in owners:
        still: "List[Interval]" = []
        for piece in missing:
            got = interval_intersect(piece, owned)
            if got is None:
                still.append(piece)
                continue
            out.setdefault(owner_rank, []).append(got)
            still.extend(interval_subtract(piece, [got]))
        missing = sorted(still)
        if not missing:
            break
    if missing:
        raise ReshardError(
            f"no owner covers interval(s) {missing} of {need} — "
            f"cannot complete the reshard"
        )
    return out


# ---------------------------------------------------------------------------
# epoch state machine
# ---------------------------------------------------------------------------


class LayoutState:
    """Monotone layout-epoch bookkeeping for one replica group.

    ``active`` is the layout this group runs; ``staged`` a fully
    transferred candidate awaiting the fleet-wide commit round.
    Committing enforces monotonicity (a commit at an epoch <= the active
    one, or at a burned epoch, raises — the runtime mirror of the
    tft-verify ``resize`` invariants)."""

    def __init__(self) -> None:
        self.active: "Optional[Layout]" = None
        self.staged: "Optional[Layout]" = None
        self.max_seen_epoch = 0
        self._burned: "set[int]" = set()

    @property
    def active_epoch(self) -> int:
        return self.active.epoch if self.active is not None else 0

    def observe_epoch(self, epoch: int) -> None:
        self.max_seen_epoch = max(self.max_seen_epoch, epoch)

    def next_epoch(self) -> int:
        """The epoch a fresh plan must use: past everything seen on the
        wire, everything burned, and the active epoch."""
        worst = max(
            [self.max_seen_epoch, self.active_epoch]
            + ([max(self._burned)] if self._burned else [])
        )
        return worst + 1

    def stage(self, layout: Layout) -> None:
        if layout.epoch <= self.active_epoch or layout.epoch in self._burned:
            raise LayoutError(
                f"cannot stage epoch {layout.epoch} (active "
                f"{self.active_epoch}, burned {sorted(self._burned)})"
            )
        self.staged = layout
        self.observe_epoch(layout.epoch)

    def commit(self, epoch: int) -> Layout:
        if self.staged is None or self.staged.epoch != epoch:
            raise LayoutError(f"no staged layout at epoch {epoch}")
        if epoch <= self.active_epoch:
            raise LayoutError(
                f"layout epoch must advance: active {self.active_epoch}, "
                f"commit {epoch}"
            )
        if epoch in self._burned:
            raise LayoutError(f"epoch {epoch} was rolled back and is burned")
        self.active, self.staged = self.staged, None
        return self.active

    def rollback(self, epoch: int) -> None:
        """Discard the staged layout and burn its epoch forever."""
        self._burned.add(epoch)
        if self.staged is not None and self.staged.epoch == epoch:
            self.staged = None

    def is_burned(self, epoch: int) -> bool:
        return epoch in self._burned


# ---------------------------------------------------------------------------
# the controller: plan at quorum, stage transfers, commit or roll back
# ---------------------------------------------------------------------------

#: Reshard payloads stage on the group's checkpoint transport under a
#: NEGATIVE step key derived from the epoch, so they can never collide
#: with heal staging (real steps are >= 0) and survive the per-step
#: ``disallow_checkpoint`` retirement of heal slots.
def RESHARD_STEP_KEY(epoch: int) -> int:
    return -(epoch + 1)


@dataclass
class _ShardedState:
    """One registered layout-sharded state surface."""

    sizes: "Dict[str, int]"  # leaf name -> full flat element count
    get_fn: "Callable[[], Dict[str, np.ndarray]]"
    set_fn: "Callable[[Dict[str, np.ndarray]], None]"


@dataclass
class _Staged:
    layout: Layout
    shard_index: int
    # key -> leaf -> (start, flat array) covering the NEW owned interval
    data: "Dict[str, Dict[str, np.ndarray]]"
    starts: "Dict[str, Dict[str, int]]"
    planned_world: int
    fetched_bytes: int = 0


class LayoutController:
    """Drives online parallelism switching for one Manager.

    Attach with :meth:`torchft_tpu.manager.Manager.attach_layout`; the
    Manager calls :meth:`wire_epoch` / :meth:`wire_data` when joining a
    quorum, :meth:`maybe_commit` + :meth:`maybe_stage` on its
    async-quorum thread, and :meth:`on_step_commit` from the
    ``should_commit`` barrier (a failed step discards the stage, so only
    barrier-committed stages reach the fleet-wide commit round).
    """

    def __init__(self, constraints: LayoutConstraints) -> None:
        self.constraints = constraints
        self.state = LayoutState()
        self._manager: "Optional[Any]" = None
        self._sharded: "Dict[str, _ShardedState]" = {}
        # this group's current data-path shard index / count (what the
        # wire manifest advertises as owned intervals)
        self._shard_index = 0
        self._nshards = 1
        self._staged: "Optional[_Staged]" = None
        self._step_committed = False
        self._transport_warned = False
        self._listeners: "List[Callable[[Layout, Dict[str, Any]], None]]" = []
        self.last_switch: "Dict[str, Any]" = {}

    # -- registration ------------------------------------------------------

    def bind(self, manager: Any) -> None:
        """Called by ``Manager.attach_layout``: keeps the manager handle
        for transport-slot retirement, and registers the heal surface —
        while the state is UNSHARDED (nshards == 1) the owned data rides
        ordinary heal transfers, so a mid-run joiner in a fleet that has
        never switched receives real parameters instead of its init
        values (once sharded, epochs > 0 make a joiner's report stale
        and the reshard path fetches its shard instead)."""
        self._manager = manager
        manager.register_state_dict_fn(
            "__layout_sharded__", self._load_heal_state, self._heal_state
        )

    def _heal_state(self) -> "Dict[str, Any]":
        active = self.state.active
        out: "Dict[str, Any]" = {
            "layout": list(active.key()) + [active.epoch] if active else None,
            "shard_index": self._shard_index,
            "nshards": self._nshards,
            "data": None,
        }
        if self._nshards == 1 and self._sharded:
            out["data"] = {
                key: {
                    leaf: np.asarray(arr)
                    for leaf, arr in spec.get_fn().items()
                }
                for key, spec in self._sharded.items()
            }
        return out

    def _load_heal_state(self, sd: "Dict[str, Any]") -> None:
        if not isinstance(sd, dict):
            return
        lay = sd.get("layout")
        if lay:
            self.state.observe_epoch(int(lay[3]))
        data = sd.get("data")
        if data is None or int(sd.get("nshards", 1)) != 1:
            # source holds a shard, not the full state: its slice cannot
            # heal us — the next switch's reshard path will (our stale
            # epoch report triggers it)
            return
        for key, spec in self._sharded.items():
            leaves = data.get(key)
            if leaves is None:
                continue
            sizes_ok = all(
                leaf in leaves
                and np.asarray(leaves[leaf]).size == size
                for leaf, size in spec.sizes.items()
            )
            if not sizes_ok:
                logger.warning(
                    "heal payload for sharded state %r has mismatched "
                    "sizes; skipping (reshard will repair)", key
                )
                continue
            spec.set_fn(
                {leaf: np.array(leaves[leaf]) for leaf in spec.sizes}
            )
        if lay:
            dp, shard, pp, epoch = (int(x) for x in lay)
            if epoch >= self.state.active_epoch:
                self.state.active = Layout(dp, shard, pp, epoch)
                self._shard_index, self._nshards = 0, 1

    def _retire_slot(self, epoch: int) -> None:
        transport = getattr(self._manager, "_checkpoint_transport", None)
        if transport is not None and hasattr(transport, "retire_checkpoint"):
            try:
                transport.retire_checkpoint(RESHARD_STEP_KEY(epoch))
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                logger.debug("reshard slot retirement failed", exc_info=True)

    def register_sharded_state(
        self,
        key: str,
        sizes: "Dict[str, int]",
        get_fn: "Callable[[], Dict[str, np.ndarray]]",
        set_fn: "Callable[[Dict[str, np.ndarray]], None]",
    ) -> None:
        """Register a layout-sharded state surface: ``sizes`` maps leaf
        names to their FULL flat element counts; ``get_fn`` returns the
        currently owned flat slices (full leaves while unsharded);
        ``set_fn`` installs the re-owned slices after a commit."""
        self._sharded[key] = _ShardedState(dict(sizes), get_fn, set_fn)

    def update_sharded(
        self,
        key: str,
        fn: "Callable[[str, np.ndarray, int], None]",
    ) -> None:
        """Apply an in-place update to the owned slices of ``key`` —
        ``fn(leaf_name, flat_array, global_start)`` mutates the array.

        This is the REQUIRED mutation path while a switch may be in
        flight: a staged reshard buffer is a copy taken at the plan
        round, so the controller double-writes every update into it
        (classic migration double-write) — updates applied directly to
        the ``get_fn`` arrays between stage and commit would be lost
        when the staged buffer is installed.  Call between steps (after
        ``should_commit``), not concurrently with ``start_quorum``."""
        spec = self._sharded[key]
        held = spec.get_fn()
        for leaf, size in spec.sizes.items():
            start, _end = shard_interval(size, self._shard_index, self._nshards)
            fn(leaf, np.asarray(held[leaf]).reshape(-1), start)
        if self._staged is not None:
            data = self._staged.data.get(key, {})
            starts = self._staged.starts.get(key, {})
            for leaf, arr in data.items():
                fn(leaf, arr, starts[leaf])

    def add_listener(
        self, fn: "Callable[[Layout, Dict[str, Any]], None]"
    ) -> None:
        """``fn(layout, info)`` runs after every commit (info carries
        ``store_address``, ``rank``, ``epoch``, ``prev`` — enough for a
        ManagedDeviceMesh to re-form its row/column process groups)."""
        self._listeners.append(fn)

    # -- wire surface ------------------------------------------------------

    def wire_epoch(self) -> int:
        """The epoch this group reports at quorum: the staged epoch once
        its stage survived the should_commit barrier, else the active
        epoch — unanimity of reports is the fleet's commit signal."""
        if self._staged is not None and self._step_committed:
            return self._staged.layout.epoch
        return self.state.active_epoch

    def wire_data(self) -> str:
        """Opaque manifest carried in the quorum member ``data`` field:
        this group's current data-path shard coordinates, from which any
        peer derives its owned intervals."""
        return json.dumps(
            {"shard": self._shard_index, "nshards": self._nshards}
        )

    def active_layout(self) -> "Optional[Layout]":
        return self.state.active

    def shard_coords(self) -> "Tuple[int, int]":
        """(shard_index, nshards) of the data this group currently owns."""
        return self._shard_index, self._nshards

    def owned_interval(self, leaf_size: int) -> Interval:
        return shard_interval(leaf_size, self._shard_index, self._nshards)

    # -- the two-phase protocol -------------------------------------------

    def maybe_commit(self, quorum: Any) -> str:
        """Commit round: if our stage survived the barrier and EVERY
        participant reports the same staged epoch at the planned world,
        activate; on any disagreement discard the stage and burn the
        epoch.  Returns "committed" / "rolled_back" / ""."""
        staged = self._staged
        if staged is None:
            self.state.observe_epoch(getattr(quorum, "max_layout_epoch", 0))
            return ""
        epoch = staged.layout.epoch
        unanimous = (
            self._step_committed
            and quorum.min_layout_epoch == quorum.max_layout_epoch == epoch
            and quorum.replica_world_size == staged.planned_world
        )
        if not unanimous:
            self._rollback(
                epoch,
                reason=(
                    f"epochs [{quorum.min_layout_epoch}, "
                    f"{quorum.max_layout_epoch}] world "
                    f"{quorum.replica_world_size} (planned "
                    f"{staged.planned_world}, step_committed "
                    f"{self._step_committed})"
                ),
            )
            self.state.observe_epoch(getattr(quorum, "max_layout_epoch", 0))
            return "rolled_back"
        # activate: install the re-owned slices, flip the shard coords,
        # notify listeners — at this quorum round on every group at once
        prev = self.state.active
        layout = self.state.commit(epoch)
        for key, spec in self._sharded.items():
            spec.set_fn(staged.data.get(key, {}))
        self._shard_index = staged.shard_index
        self._nshards = layout.nshards
        self._staged = None
        self._step_committed = False
        self._retire_slot(epoch)
        info = {
            "epoch": epoch,
            "prev": prev,
            "rank": quorum.replica_rank,
            "store_address": quorum.store_address,
            "fetched_bytes": staged.fetched_bytes,
        }
        self.last_switch = {
            "result": "committed",
            "layout": layout.key(),
            **{k: v for k, v in info.items() if k != "prev"},
        }
        for fn in self._listeners:
            try:
                fn(layout, info)
            except Exception:  # noqa: BLE001 - listeners must not fail a step
                logger.exception("layout listener failed")
        return "committed"

    def abort_staged(self, reason: str) -> None:
        """Discard any staged switch (burning its epoch); no-op when
        nothing is staged.  The Manager calls this when either phase of
        the switch protocol raises — a half-processed commit round must
        not commit one round late on this group alone."""
        if self._staged is not None:
            self._rollback(self._staged.layout.epoch, reason)

    def _rollback(self, epoch: int, reason: str) -> None:
        self.state.rollback(epoch)
        self._staged = None
        self._step_committed = False
        self._retire_slot(epoch)
        self.last_switch = {"result": "rolled_back", "epoch": epoch,
                           "reason": reason}
        logger.warning("layout epoch %d rolled back: %s", epoch, reason)

    def maybe_stage(self, manager: Any, quorum: Any) -> bool:
        """Plan phase: when the live world no longer matches the active
        layout (or a participant reports a stale epoch — a fresh joiner
        needing its shard), plan the next layout and run the reshard
        transfers into a staged buffer.  Any failure burns the epoch
        locally; the commit round then rolls the fleet back.  Returns
        True when a stage was attempted."""
        world = quorum.replica_world_size
        participants = list(getattr(quorum, "participants", []) or [])
        if world < 1 or len(participants) != world:
            return False
        self.state.observe_epoch(getattr(quorum, "max_layout_epoch", 0))
        if self.state.active is None:
            # implicit seed layout: today's behavior — pure DP, one shard
            self.state.active = Layout(dp=world, shard=1, pp=1, epoch=0)
            self._shard_index, self._nshards = 0, 1
        # mixed epoch reports (in EITHER direction) mean some group's
        # sharded data is not at the fleet's current generation — a fresh
        # joiner needing its shard, or this group having rolled back a
        # commit the rest completed; both resolve through a fresh switch
        reported = {int(p.get("layout_epoch", 0)) for p in participants}
        mixed = reported != {self.state.active_epoch}
        # the seed (pure-DP) layout may itself violate the declared
        # constraints (e.g. the memory ceiling demands shard > 1): an
        # infeasible active layout triggers a switch even at stable world
        active_infeasible = (
            self.state.active.key()
            not in feasible_layouts(world, self.constraints)
        )
        if (
            world == self.state.active.world
            and not mixed
            and not active_infeasible
            and self._staged is None
        ):
            return False
        if self._staged is not None:
            # a stage is already in flight toward its commit round
            return False
        epoch = self.state.next_epoch()
        try:
            layout = plan_layout(
                world, self.constraints, prev=self.state.active, epoch=epoch
            )
        except LayoutError as e:
            logger.warning("layout planning infeasible at world=%d: %s", world, e)
            return False
        if (
            layout.nshards == 1
            and self.state.active.nshards == 1
            and not mixed
        ):
            # pure-DP fleets resize with zero data movement: the layout's
            # only live dimension is dp == world, so adopt in place
            # without spending an epoch or a commit round
            self.state.active = Layout(
                dp=world, shard=1, pp=1, epoch=self.state.active_epoch
            )
            return False
        if not getattr(manager._checkpoint_transport, "supports_reshard", False):
            # a transport without the slice-diff serving surface (e.g.
            # the collective PGTransport) cannot move shards between
            # arbitrary peers: stay on the old layout — pure-DP elastic
            # resizing above still applies — instead of burning an epoch
            # per round on stages that can never complete
            if not self._transport_warned:
                self._transport_warned = True
                logger.warning(
                    "checkpoint transport %s cannot serve reshard slice "
                    "fetches (no supports_reshard); online parallelism "
                    "switching stays disabled on this group",
                    type(manager._checkpoint_transport).__name__,
                )
            return False
        t0 = time.perf_counter()
        try:
            self._stage_and_fetch(manager, quorum, layout)
        except Exception as e:  # noqa: BLE001 - degrade, never wedge
            self._rollback(epoch, reason=f"stage failed: {e}")
            log_event(
                "layout",
                "reshard stage failed; rolling back to old layout",
                replica_id=getattr(manager, "_replica_id", ""),
                step=getattr(quorum, "max_step", 0),
                epoch=epoch,
                error=str(e),
            )
            return True
        dt = time.perf_counter() - t0
        assert self._staged is not None
        log_event(
            "layout",
            "reshard staged",
            replica_id=getattr(manager, "_replica_id", ""),
            step=getattr(quorum, "max_step", 0),
            epoch=epoch,
            layout=str(layout.key()),
            fetched_bytes=self._staged.fetched_bytes,
            stage_s=round(dt, 4),
        )
        return True

    def on_step_commit(self, committed: bool) -> None:
        """should_commit barrier outcome for the step that overlapped the
        stage: every local rank of the group observes the same vote, so
        either the whole group carries the staged epoch into the commit
        round or the whole group discards it (burning the epoch)."""
        if self._staged is None:
            return
        if committed:
            self._step_committed = True
        else:
            self._rollback(
                self._staged.layout.epoch, reason="overlapping step aborted"
            )

    # -- the data path -----------------------------------------------------

    @staticmethod
    def _owner_manifests(
        participants: "List[Dict[str, Any]]",
    ) -> "List[Tuple[int, Dict[str, int]]]":
        """(rank, {shard, nshards}) of every participant holding VALID
        sharded data — those reporting the fleet's max layout epoch."""
        max_epoch = max(int(p.get("layout_epoch", 0)) for p in participants)
        owners: "List[Tuple[int, Dict[str, int]]]" = []
        for rank, p in enumerate(participants):
            if int(p.get("layout_epoch", 0)) != max_epoch:
                continue
            try:
                manifest = json.loads(p.get("data") or "{}")
            except ValueError:
                manifest = {}
            owners.append(
                (rank, {"shard": int(manifest.get("shard", 0)),
                        "nshards": max(int(manifest.get("nshards", 1)), 1)})
            )
        return owners

    def _dst_plan(
        self,
        owners: "List[Tuple[int, Dict[str, int]]]",
        layout: Layout,
        n_participants: int,
        dst_rank: int,
    ) -> "Dict[Tuple[str, str], Dict[int, List[Interval]]]":
        """The slice diff for ONE destination: per (state key, leaf),
        which intervals it must fetch from which source rank.  Pure
        function of the quorum + the plan, so the destination and every
        source compute the identical assignment independently."""
        dst_index = layout.shard_index(dst_rank)
        owner_map = dict(owners)
        plan: "Dict[Tuple[str, str], Dict[int, List[Interval]]]" = {}
        # owner preference rotates with the destination so dp replicas of
        # one shard spread the serving load instead of all hammering rank 0
        ordered = sorted(
            owners, key=lambda o: ((o[0] - dst_rank) % max(n_participants, 1))
        )
        for key, spec in self._sharded.items():
            for leaf, size in spec.sizes.items():
                need = shard_interval(size, dst_index, layout.nshards)
                have: "List[Interval]" = []
                if dst_rank in owner_map:
                    m = owner_map[dst_rank]
                    have = [shard_interval(size, m["shard"], m["nshards"])]
                src_map = plan_fetches(
                    need,
                    have,
                    [
                        (r, shard_interval(size, m["shard"], m["nshards"]))
                        for r, m in ordered
                    ],
                )
                plan[(key, leaf)] = src_map
        return plan

    def _stage_and_fetch(self, manager: Any, quorum: Any, layout: Layout) -> None:
        """Stage outgoing slices on our checkpoint transport, then fetch
        our missing slices from their current owners."""
        participants = [dict(p) for p in quorum.participants]
        my_rank = quorum.replica_rank
        new_index = layout.shard_index(my_rank)
        epoch = layout.epoch
        # chaos site, once per stage attempt (and again before each
        # remote fetch below): a bootstrap shard-up moves no bytes, so
        # without this entry check it would be untargetable
        _faults.check(
            "mesh.reshard",
            replica=getattr(manager, "_replica_id", None),
            step=epoch,
        )
        owners = self._owner_manifests(participants)
        if not owners:
            raise ReshardError("no participant holds valid sharded state")
        owner_map = dict(owners)
        i_am_valid = my_rank in owner_map

        # stage: for every other destination, the slices the shared plan
        # routes through us; they poll-fetch via our checkpoint transport
        if i_am_valid:
            staged_doc: "Dict[str, Any]" = {}
            my_manifest = owner_map[my_rank]
            held_cache = {k: s.get_fn() for k, s in self._sharded.items()}
            for dst_rank in range(len(participants)):
                if dst_rank == my_rank:
                    continue
                plan = self._dst_plan(owners, layout, len(participants), dst_rank)
                out: "Dict[str, Any]" = {}
                for (key, leaf), src_map in plan.items():
                    size = self._sharded[key].sizes[leaf]
                    my_start, _my_end = shard_interval(
                        size, my_manifest["shard"], my_manifest["nshards"]
                    )
                    arr = np.asarray(held_cache[key][leaf]).reshape(-1)
                    for (s, e) in src_map.get(my_rank, []):
                        out[f"{key}/{leaf}/{s}:{e}"] = arr[
                            s - my_start : e - my_start
                        ]
                if out:
                    staged_doc[f"for:{dst_rank}"] = out
            if staged_doc:
                manager._checkpoint_transport.send_checkpoint(
                    dst_ranks=[],
                    step=RESHARD_STEP_KEY(epoch),
                    state_dict=staged_doc,
                    timeout=manager._timeout,
                )

        # fetch: assemble our new shard from local overlap + remote slices
        my_plan = self._dst_plan(owners, layout, len(participants), my_rank)
        src_ranks = sorted(
            {
                r
                for src_map in my_plan.values()
                for r, ivs in src_map.items()
                if ivs and r != my_rank
            }
        )
        remote: "Dict[int, Dict[str, np.ndarray]]" = {}
        fetched_bytes = 0
        for src_rank in src_ranks:
            _faults.check(
                "mesh.reshard",
                replica=getattr(manager, "_replica_id", None),
                step=epoch,
            )
            doc = self._fetch_part(
                manager,
                participants[src_rank].get("address", ""),
                epoch,
                my_rank,
                src_rank,
            )
            remote[src_rank] = doc
            fetched_bytes += sum(np.asarray(v).nbytes for v in doc.values())

        new_data: "Dict[str, Dict[str, np.ndarray]]" = {}
        new_starts: "Dict[str, Dict[str, int]]" = {}
        for key, spec in self._sharded.items():
            new_data[key] = {}
            new_starts[key] = {}
            held = spec.get_fn()
            for leaf, size in spec.sizes.items():
                start, end = shard_interval(size, new_index, layout.nshards)
                local = np.asarray(held[leaf]).reshape(-1)
                buf = np.empty(end - start, dtype=local.dtype)
                covered: "List[Interval]" = []
                if i_am_valid:
                    m = owner_map[my_rank]
                    old = shard_interval(size, m["shard"], m["nshards"])
                    keep = interval_intersect((start, end), old)
                    if keep is not None:
                        buf[keep[0] - start : keep[1] - start] = local[
                            keep[0] - old[0] : keep[1] - old[0]
                        ]
                        covered.append(keep)
                for src_rank, ivs in my_plan[(key, leaf)].items():
                    if src_rank == my_rank:
                        continue
                    doc = remote.get(src_rank, {})
                    for (s, e) in ivs:
                        piece = doc.get(f"{key}/{leaf}/{s}:{e}")
                        if piece is None:
                            continue
                        buf[s - start : e - start] = np.asarray(piece).reshape(-1)
                        covered.append((s, e))
                gaps = interval_subtract((start, end), covered)
                if gaps:
                    raise ReshardError(
                        f"coverage gaps {gaps} for {key}/{leaf} "
                        f"interval [{start}, {end})"
                    )
                new_data[key][leaf] = buf
                new_starts[key][leaf] = start

        self.state.stage(layout)
        self._staged = _Staged(
            layout=layout,
            shard_index=new_index,
            data=new_data,
            starts=new_starts,
            planned_world=len(participants),
            fetched_bytes=fetched_bytes,
        )
        self._step_committed = False
        _metrics.RESHARD_BYTES.labels(
            replica_id=manager._metric_replica_id
        ).inc(fetched_bytes)
        _flightrec.record(
            "mesh.reshard",
            epoch=epoch,
            layout=str(layout.key()),
            bytes=fetched_bytes,
            replica_id=getattr(manager, "_replica_id", ""),
        )

    def _fetch_part(
        self, manager: Any, addr: str, epoch: int, my_rank: int, src_rank: int
    ) -> "Dict[str, np.ndarray]":
        """Fetch the slices source ``src_rank`` staged for us, over its
        checkpoint transport (HTTP streaming + the 503-poll retry
        policy).  The source's transport address comes from its manager's
        ``checkpoint_metadata`` RPC — the same discovery heal uses."""
        from torchft_tpu.coordination import ManagerClient

        client = ManagerClient(addr, connect_timeout=manager._connect_timeout)
        try:
            metadata = client._checkpoint_metadata(
                manager._group_rank, timeout=manager._timeout
            )
        finally:
            client.close()
        doc = manager._checkpoint_transport.recv_checkpoint(
            src_rank=src_rank,
            metadata=metadata,
            step=RESHARD_STEP_KEY(epoch),
            timeout=manager._timeout,
            resource=f"part_{my_rank}",
        )
        return doc or {}
