"""Reconfigurable process groups: the fault-tolerant collective layer.

TPU-native rebuild of the reference's reconfigurable ProcessGroup hierarchy
(reference: torchft/process_group.py:133-2023).  The key fault-tolerance
properties reproduced here (reference §5 semantics):

- **reconfigure**: ``configure(store_addr, replica_id, rank, world_size)``
  tears down and re-forms the group with new membership (keyed by the
  per-quorum store prefix) without restarting the process.
- **abortable with deadline**: every op takes the group timeout; ``abort()``
  cancels in-flight ops by closing sockets, never killing the process.
- **error latching**: after a failure every op fails fast (or is swallowed by
  ``ErrorSwallowingProcessGroupWrapper``) until the next configure.
- **host-mediated DCN path**: collectives run over TCP on host buffers
  (numpy), the Gloo analog.  On TPU the *inner* dimensions (FSDP/TP over ICI)
  are XLA collectives inside jit and are fault-free by assumption; this layer
  owns only the elastic replica dimension, so membership changes never
  trigger re-jit (zero-fill participation keeps compiled shapes static).

Subprocess isolation: ``ProcessGroupBabyTCP`` runs the real PG in a spawned
worker process (reference "Baby" variants, torchft/process_group.py:
1358-2023).  On TPU there is no NCCL-context crash mode to contain, but the
isolation still buys a *hard* abort — killing the worker cancels a wedged
collective no matter what state its sockets are in — and shields the
trainer (and its XLA runtime) from any failure mode of the collective
stack.  Design divergence from the reference, by intent: no fake
world-size-1 backend registration (a torch-DeviceMesh-specific trick; the
JAX mesh composition lives in torchft_tpu/parallel/device_mesh.py).
"""

from __future__ import annotations

import logging
import pickle
import queue
import socket
import struct
import threading
import time
import uuid
import concurrent.futures as concurrent_futures
from abc import ABC, abstractmethod
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.coordination import StoreClient
from torchft_tpu.parallel.work import Work, completed_work, failed_work
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import linkstats as _linkstats
from torchft_tpu.utils import lockcheck as _lockcheck
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.bufpool import POOL as _pool
from torchft_tpu.utils.env import env_float

logger = logging.getLogger(__name__)

REDUCE_SUM = "sum"
REDUCE_AVG = "avg"
REDUCE_MAX = "max"
REDUCE_MIN = "min"

# in-place reduction ufuncs for ring steps (AVG divides at the end)
_REDUCE_UFUNCS: Dict[str, Any] = {
    REDUCE_SUM: np.add,
    REDUCE_AVG: np.add,
    REDUCE_MAX: np.maximum,
    REDUCE_MIN: np.minimum,
}


def _is_float_dtype(dtype: np.dtype) -> bool:
    """True for numpy floats AND ml_dtypes extension floats (bfloat16,
    float8_*) — np.issubdtype misses the latter (they register as kind 'V';
    same pitfall as manager._is_floating, manager.py:67)."""
    return np.issubdtype(dtype, np.floating) or dtype.name.startswith(
        ("bfloat", "float8")
    )


def _accumulation_dtype(dtype: np.dtype) -> np.dtype:
    """Accumulation dtype for ring partial sums.

    Floats accumulate in f32 (f64 stays f64): the replica dimension is
    small, the ring reduces each chunk in a fixed order on exactly one rank
    before allgather, so results are bitwise identical across ranks at any
    precision — and f32 halves the wire bytes vs f64 promotion. Half-width
    floats (f16 and the ml_dtypes TPU types bf16/fp8) widen to f32 for
    precision; integers widen to 64-bit to avoid silent overflow.
    """
    if _is_float_dtype(dtype):
        return np.dtype(np.float64) if dtype.itemsize >= 8 else np.dtype(np.float32)
    if np.issubdtype(dtype, np.signedinteger):
        return np.dtype(np.int64)
    if np.issubdtype(dtype, np.unsignedinteger):
        return np.dtype(np.uint64)
    return dtype


def _as_numpy(x: Any) -> np.ndarray:
    """Host view of an array (device->host copy for jax arrays)."""
    return np.asarray(x)


def _check_recv_buffer(out: np.ndarray, shape: Any, dtype: str) -> None:
    """Validate a caller-supplied in-place recv buffer against the wire
    header: shape, dtype, and contiguity must all match (a silent
    value-cast or reshape would mask a buffer-setup bug).  Shared by the
    direct wire reader and the Baby PG's in-place emulation."""
    if (
        str(out.dtype) != dtype
        or tuple(out.shape) != tuple(shape)
        or not out.flags.c_contiguous
    ):
        raise RuntimeError(
            f"in-place recv buffer mismatch: {out.shape}/{out.dtype} vs "
            f"wire {tuple(shape)}/{dtype}"
        )


def _routable_local_ip(store_addr: str) -> str:
    """Local IP of the interface that routes to the store host.

    Hostnames are not guaranteed resolvable across hosts/containers; the
    interface used to reach the rendezvous store is by construction routable
    from every peer that also reaches the store.
    """
    host, _, port = store_addr.rpartition(":")
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect((host or "127.0.0.1", int(port or 1)))
            return probe.getsockname()[0]
        finally:
            probe.close()
    except OSError:
        return socket.gethostname()


class ProcessGroup(ABC):
    """Abstract reconfigurable process group over host buffers.

    API parity with the reference base ProcessGroup
    (reference: torchft/process_group.py:133-386), adapted to numpy/pytree
    data instead of torch tensors.
    """

    def __init__(self, timeout: float = 60.0) -> None:
        self._timeout = timeout

    # -- lifecycle ---------------------------------------------------------

    @abstractmethod
    def configure(
        self, store_addr: str, replica_id: str, rank: int, world_size: int
    ) -> None:
        """(Re)initialize membership. store_addr is ``host:port/prefix``."""

    @abstractmethod
    def abort(self) -> None:
        """Cancel in-flight ops and latch an aborted error."""

    @abstractmethod
    def errored(self) -> Optional[Exception]:
        """Latched failure, or None if healthy."""

    def shutdown(self) -> None:
        self.abort()

    def set_timeout(self, timeout: float) -> None:
        self._timeout = timeout

    # -- topology ----------------------------------------------------------

    @abstractmethod
    def rank(self) -> int: ...

    @abstractmethod
    def size(self) -> int: ...

    # -- collectives -------------------------------------------------------

    @abstractmethod
    def allreduce(self, arrays: "List[Any]", op: str = REDUCE_SUM) -> Work: ...

    @abstractmethod
    def allgather(self, array: Any) -> Work:
        """Resolves to a list of ``size()`` arrays, indexed by rank."""

    @abstractmethod
    def broadcast(self, array: Any, root: int = 0) -> Work: ...

    @abstractmethod
    def reduce_scatter(self, array: Any, op: str = REDUCE_SUM) -> Work:
        """Reduce then scatter row-chunks; resolves to this rank's chunk.

        ``array.shape[0]`` must be divisible by ``size()``.
        """

    @abstractmethod
    def alltoall(self, arrays: "List[Any]") -> Work:
        """Exchange: sends arrays[i] to rank i; resolves to received list."""

    def sendrecv(self, array: Any, dst: int, src: int, tag: int = 0) -> Work:
        """Simultaneous send-to-``dst`` + receive-from-``src`` as ONE op;
        resolves to the received array.  The deadlock-free pairwise
        exchange primitive multi-hop reduction plans are built from
        (ops/topology.py): both directions drain concurrently even when
        payloads exceed socket buffers, which two serialized send/recv
        ops on the single worker cannot guarantee.  Backends without a
        native implementation reject it."""
        return failed_work(
            RuntimeError(f"{type(self).__name__} does not support sendrecv")
        )

    @abstractmethod
    def send(self, array: Any, dst: int, tag: int = 0) -> Work: ...

    @abstractmethod
    def recv(self, src: int, tag: int = 0, out: "Optional[np.ndarray]" = None) -> Work:
        """Resolves to the received array (shape/dtype carried on the wire).
        ``out``: backends that can, receive in place into this buffer."""

    def barrier(self) -> Work:
        return self.allreduce([np.zeros(1, dtype=np.float32)])


class ProcessGroupDummy(ProcessGroup):
    """World-size-1 no-op group (reference: torchft/process_group.py:960-1081).

    Used to bootstrap wrappers before the first quorum and in tests.
    """

    def __init__(self, rank: int = 0, world: int = 1, timeout: float = 60.0) -> None:
        super().__init__(timeout)
        assert world == 1, "ProcessGroupDummy only supports world_size 1"
        self._rank = rank
        self._world = world
        self._errored: Optional[Exception] = None
        self.configure_count = 0

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        self.configure_count += 1
        self._errored = None

    def abort(self) -> None:
        self._errored = RuntimeError("aborted")

    def errored(self) -> Optional[Exception]:
        return self._errored

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._world

    def allreduce(self, arrays: "List[Any]", op: str = REDUCE_SUM) -> Work:
        return completed_work([_as_numpy(a).copy() for a in arrays])

    def allgather(self, array: Any) -> Work:
        return completed_work([_as_numpy(array).copy()])

    def broadcast(self, array: Any, root: int = 0) -> Work:
        return completed_work(_as_numpy(array).copy())

    def reduce_scatter(self, array: Any, op: str = REDUCE_SUM) -> Work:
        return completed_work(_as_numpy(array).copy())

    def alltoall(self, arrays: "List[Any]") -> Work:
        return completed_work([_as_numpy(a).copy() for a in arrays])

    def send(self, array: Any, dst: int, tag: int = 0) -> Work:
        return failed_work(RuntimeError("send not supported on world-size-1 group"))

    def recv(self, src: int, tag: int = 0, out: "Optional[np.ndarray]" = None) -> Work:
        return failed_work(RuntimeError("recv not supported on world-size-1 group"))


# ---------------------------------------------------------------------------
# TCP backend (host-mediated DCN collectives — the Gloo analog)
# ---------------------------------------------------------------------------

_HELLO_MAGIC = 0x7F7A11AA


class _PeerConn:
    """A connected, rank-identified socket to one peer."""

    def __init__(self, sock: socket.socket, rank: int) -> None:
        self.sock = sock
        self.rank = rank
        sock.setblocking(True)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class _TokenBucket:
    """Egress token bucket shared by a PG's sender threads.

    ``consume(n)`` debits ``n`` bytes and sleeps off any debt, so the
    long-run egress rate converges to ``rate`` bytes/s while short bursts
    up to ``burst`` pass unthrottled (one socket-buffer's worth — shaping
    below that granularity would only measure syscall overhead).  The
    sleep happens OUTSIDE the lock: concurrent senders each serve their
    own debt, and because debits are serialized under the lock the debt
    each sender sleeps for is its own marginal contribution.
    """

    def __init__(self, rate_bytes_per_s: float, burst: int = 4 << 20) -> None:
        self.rate = float(rate_bytes_per_s)
        self.burst = float(burst)
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = _lockcheck.lock("pg.token_bucket")
        # Own ledger (bytes debited / seconds slept serving debt): tests
        # assert pacing on these instead of wall-clock deltas, which CI
        # scheduler noise can invert.
        self.consumed_bytes = 0
        self.slept_s = 0.0

    def consume(self, nbytes: int) -> float:
        """Debit ``nbytes``; returns the seconds slept serving the debt
        (the shaper-wait the per-peer wait accounting attributes)."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate
            )
            self._t = now
            self._tokens -= nbytes
            self.consumed_bytes += int(nbytes)
            debt = -self._tokens
        if debt > 0:
            wait = debt / self.rate
            time.sleep(wait)
            with self._lock:
                self.slept_s += wait
            return wait
        return 0.0


class _PGAborted(RuntimeError):
    pass


class NotParticipatingError(RuntimeError):
    """Raised by ``ManagedProcessGroup.rank()`` when the replica has no rank
    in the current quorum (it is healing or excluded).  Contrast with the
    reference, whose managed PG always has a local rank (torchft/
    process_group.py:1233-1266) because healing replicas still hold one."""


class ProcessGroupTCP(ProcessGroup):
    """Fault-tolerant collectives over a full TCP mesh of host processes.

    The cross-replica-group (DCN) collective backend: rendezvous through the
    quorum primary's store under a per-quorum prefix (set by the Manager,
    reference: torchft/manager.py:659-690), full-mesh connect, then ring
    algorithms on host buffers.  Bandwidth-optimal ring allreduce /
    reduce-scatter; direct sends for broadcast/gather at the small world
    sizes of the replica dimension.

    All ops run in submission order on a single worker thread; both
    endpoints of each socket submit the same collective sequence so streams
    stay in sync (the standard collective contract).
    """

    def __init__(
        self,
        timeout: float = 60.0,
        bandwidth_gbps: "Optional[float]" = None,
        rtt_ms: "Optional[float]" = None,
    ) -> None:
        super().__init__(timeout)
        self._rank = -1
        self._world = 0
        self._peers: Dict[int, _PeerConn] = {}
        self._listener: Optional[socket.socket] = None
        self._errored: Optional[Exception] = None
        self._aborted = False
        self._generation = 0
        # Egress bandwidth shaping (token bucket across all sender
        # threads).  Two uses: benchmarking the quantized wire under a
        # *measured* DCN bandwidth instead of loopback's effectively
        # infinite one, and capping a training job's DCN footprint on
        # shared links.  None = unshaped; TORCHFT_WIRE_GBPS supplies a
        # default (decimal GB/s, e.g. "0.5").
        if bandwidth_gbps is None:
            env = env_float("TORCHFT_WIRE_GBPS", 0.0)
            bandwidth_gbps = env if env > 0 else None
        self._bucket: "Optional[_TokenBucket]" = (
            _TokenBucket(bandwidth_gbps * 1e9) if bandwidth_gbps else None
        )
        # WAN latency model (TORCHFT_WIRE_RTT_MS): per-MESSAGE first-byte
        # delay on the shaped path, charged only on sends that cross a
        # host/slice boundary of the TORCHFT_TOPOLOGY descriptor (flat /
        # unset topology = every peer is across a boundary, the
        # multi-region flat-ring premise).  Deliberately decoupled from
        # the token bucket: the bucket paces PAYLOAD CHUNKS (bandwidth
        # debt accumulates per byte), while latency is paid once per
        # message no matter how many pacing chunks it splits into — so a
        # K-chunk message costs rtt + bytes/rate, never K*rtt
        # (tests/test_topology.py pins the composition).  The token
        # bucket is boundary-scoped the same way: with a declared
        # topology, BOTH shaping legs model the WAN boundary and
        # intra-host messages ride the (loopback/ICI-fast) local fabric
        # unshaped; with flat/unset topology every peer is across the
        # boundary, so existing shaped setups behave byte-identically.
        if rtt_ms is None:
            rtt_ms = env_float("TORCHFT_WIRE_RTT_MS", 0.0)
        self._rtt_s = max(rtt_ms, 0.0) / 1e3
        # ranks whose messages cross a topology boundary (computed per
        # configure from TORCHFT_TOPOLOGY; empty while unconfigured)
        self._inter_peers: "frozenset[int]" = frozenset()
        # link-state plane identities (utils/linkstats.py): per peer
        # rank, the peer host learned at configure and the derived
        # (link label, is_local) pair — a same-host peer across a
        # declared topology boundary gets a ``host#gN`` pseudo-host so
        # the shaped link is never averaged into the local fabric
        self._peer_hosts: "Dict[int, str]" = {}
        self._link_labels: "Dict[int, Tuple[str, bool]]" = {}
        # In-flight op handle in the process-wide flight recorder
        # (utils/flightrecorder.py; subsumes the old ad-hoc ``_flight``
        # dict).  The FlightOp serializes its own updates (worker + sender
        # threads write); _flight_swap_lock guards the TAKE of the handle
        # so the worker's success path and a concurrent abort() cannot
        # both finish the same op (the loser would mislabel a completed
        # collective as aborted).
        self._flight_op: "Optional[_flightrec.FlightOp]" = None
        self._flight_swap_lock = _lockcheck.lock("pg.tcp.flight_swap")
        self._replica_id = ""
        self._lock = _lockcheck.lock("pg.tcp.state")
        self._worker: Optional[threading.Thread] = None
        self._sender: "Optional[concurrent_futures.ThreadPoolExecutor]" = None
        self._queue: "queue.Queue[Optional[Tuple[int, Callable[[], Any], Future]]]" = (
            queue.Queue()
        )

    def set_bandwidth(self, gbps: "Optional[float]") -> None:
        """(Re)shape egress to ``gbps`` decimal GB/s; None removes the cap.
        Takes effect from the next send — in-flight chunks finish at the
        old rate."""
        self._bucket = _TokenBucket(gbps * 1e9) if gbps else None

    def set_rtt(self, rtt_ms: "Optional[float]") -> None:
        """(Re)set the modeled per-message boundary latency; None/0
        removes it.  Takes effect from the next send; boundary membership
        re-derives at the next configure."""
        self._rtt_s = max(rtt_ms or 0.0, 0.0) / 1e3

    def _boundary_peers(self, rank: int, world: int) -> "frozenset[int]":
        """Peers across a TORCHFT_TOPOLOGY host/slice boundary — the set
        BOTH wire-model legs (RTT and token bucket) charge on.
        Flat/unset topology: every peer (a flat ring spanning regions
        pays the boundary on every hop — and pre-topology shaped setups
        keep their exact behavior).  Computed unconditionally per
        configure: ``set_bandwidth``/``set_rtt`` may arm shaping AFTER
        membership forms."""
        if world <= 1:
            return frozenset()
        from torchft_tpu.ops.topology import resolve_topology

        topo = resolve_topology(world)
        if topo is None:
            return frozenset(r for r in range(world) if r != rank)
        return frozenset(
            r for r in range(world) if r != rank and topo.inter(rank, r)
        )

    def _link_peer_labels(
        self, world: int
    ) -> "Dict[int, Tuple[str, bool]]":
        """(link label, is_local) per connected peer for the passive
        link-state plane.  Cross-host peers key by their real host; a
        same-host peer across the declared topology boundary keys by the
        ``host#gN`` pseudo-host (its topology group) so WAN-modeled and
        local-fabric traffic never share an estimator — intra-host pairs
        report unshaped-fast, boundary pairs report the modeled link."""
        from torchft_tpu.ops.topology import resolve_topology
        from torchft_tpu.utils.hostident import local_host_identities

        topo = resolve_topology(world) if world > 1 else None
        local_ids = local_host_identities()
        labels: "Dict[int, Tuple[str, bool]]" = {}
        for r, host in self._peer_hosts.items():
            wan = r in self._inter_peers
            if wan and topo is not None and host in local_ids:
                label = f"{host}#g{topo.group_index(r)}"
            else:
                label = host
            labels[r] = (label, not wan)
        return labels

    # -- lifecycle ---------------------------------------------------------

    def configure(
        self, store_addr: str, replica_id: str, rank: int, world_size: int
    ) -> None:
        # chaos site: a reconfigure failure here surfaces to the Manager's
        # configure try-block, which latches it and re-forms next quorum
        _faults.check("pg.reconfigure", replica=replica_id)
        self._replica_id = replica_id
        t_cfg_ns = time.time_ns()
        self._teardown()
        deadline = time.monotonic() + self._timeout

        with self._lock:
            self._errored = None
            self._aborted = False
            self._generation += 1
            gen = self._generation
        self._rank = rank
        self._world = world_size
        self._inter_peers = self._boundary_peers(rank, world_size)

        if world_size == 1:
            self._peers = {}
            self._peer_hosts = {}
            self._link_labels = {}
            self._start_worker(gen)
            _metrics.PG_RECONFIGURES.labels(transport="tcp").inc()
            _flightrec.record(
                "pg.configure", start_ns=t_cfg_ns, replica_id=replica_id,
                rank=rank, world=world_size,
            )
            return

        addr, _, prefix = store_addr.partition("/")
        store = StoreClient(addr, connect_timeout=self._timeout)
        try:
            try:
                listener = socket.socket(socket.AF_INET6, socket.SOCK_STREAM)
                listener.bind(("", 0))
            except OSError:
                # Host without IPv6 (ipv6.disable=1 containers).
                listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                listener.bind(("", 0))
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.listen(world_size)
            self._listener = listener
            # Advertise the interface address peers can actually route to:
            # the local IP of a connection toward the store host (hostnames
            # may not resolve across container boundaries).
            host = _routable_local_ip(addr)
            port = listener.getsockname()[1]
            store.set(f"{prefix}/rank_{rank}", f"{host}:{port}")

            peers: Dict[int, _PeerConn] = {}
            peer_hosts: Dict[int, str] = {}
            # Deterministic connect direction avoids duplicate links: lower
            # ranks dial higher ranks; higher ranks accept.
            for peer in range(rank + 1, world_size):
                peer_addr = store.get(
                    f"{prefix}/rank_{peer}",
                    timeout=max(deadline - time.monotonic(), 0.001),
                )
                phost, _, pport = peer_addr.rpartition(":")
                sock = socket.create_connection(
                    (phost, int(pport)),
                    timeout=max(deadline - time.monotonic(), 0.001),
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.sendall(struct.pack(">II", _HELLO_MAGIC, rank))
                peers[peer] = _PeerConn(sock, peer)
                peer_hosts[peer] = phost
            for _ in range(rank):
                listener.settimeout(max(deadline - time.monotonic(), 0.001))
                sock, _ = listener.accept()
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                magic, peer_rank = struct.unpack(">II", self._read_exact_sock(sock, 8, deadline))
                if magic != _HELLO_MAGIC:
                    raise RuntimeError("bad hello from peer")
                peers[peer_rank] = _PeerConn(sock, peer_rank)
                try:
                    peer_hosts[peer_rank] = sock.getpeername()[0]
                except OSError:
                    peer_hosts[peer_rank] = "unknown"
            self._peers = peers
            self._peer_hosts = peer_hosts
            self._link_labels = self._link_peer_labels(world_size)
            self._start_worker(gen)
            _metrics.PG_RECONFIGURES.labels(transport="tcp").inc()
            _flightrec.record(
                "pg.configure", start_ns=t_cfg_ns, replica_id=replica_id,
                rank=rank, world=world_size,
            )
        except Exception as e:
            _flightrec.record(
                "pg.configure", status="error", start_ns=t_cfg_ns,
                replica_id=replica_id, rank=rank, world=world_size,
                error=repr(e),
            )
            self._teardown()
            raise
        finally:
            store.close()

    def _start_worker(self, gen: int) -> None:
        # Fresh queue per generation so stale ops/poison pills from a prior
        # configure can never reach the new worker. Swapped under the lock so
        # _submit can never enqueue onto a retired queue.
        with self._lock:
            self._queue = queue.Queue()
            self._sender = concurrent_futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="pg_tcp_sender"
            )
            self._worker = threading.Thread(
                target=self._worker_loop,
                args=(gen, self._queue),
                name="pg_tcp_worker",
                daemon=True,
            )
            self._worker.start()

    def _teardown(self) -> None:
        with self._lock:
            self._generation += 1  # invalidate the running worker
            peers = list(self._peers.values())
            self._peers = {}
            listener = self._listener
            self._listener = None
            old_queue = self._queue
            old_queue.put(None)  # wake the worker so it can exit
        for p in peers:
            p.close()
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        worker = self._worker
        if worker is not None and worker is not threading.current_thread():
            worker.join(timeout=5.0)
        with self._lock:
            # After this, _submit fails fast instead of enqueueing into limbo.
            self._worker = None
            sender, self._sender = self._sender, None
        if sender is not None:
            # don't wait: a sendall stuck on a dead peer unwedges itself when
            # the socket close (above) fails it
            sender.shutdown(wait=False)
        # Fail any ops still sitting in the retired queue so no Work handle
        # is left unresolved (a hang is worse than an error in FT code).
        while True:
            try:
                item = old_queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item[2].set_exception(_PGAborted("process group torn down"))

    def abort(self) -> None:
        self._dump_flight("process group aborted", dump=False)
        _flightrec.record(
            "pg.abort", status="abort", replica_id=self._replica_id,
            rank=self._rank, world=self._world,
        )
        # one dump per abort, whether or not an op was in flight: the ring
        # around the abort IS the postmortem evidence
        _flightrec.dump("process group aborted", trigger="pg_abort")
        _metrics.PG_ABORTS.labels(transport="tcp").inc()
        with self._lock:
            self._aborted = True
            if self._errored is None:
                self._errored = _PGAborted("process group aborted")
        self._teardown()

    def errored(self) -> Optional[Exception]:
        return self._errored

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._world

    # -- op submission -----------------------------------------------------

    def _submit(self, fn: "Callable[[], Any]", op: str = "op") -> Work:
        fut: Future = Future()
        with self._lock:
            if self._errored is not None:
                return failed_work(self._errored)
            if self._worker is None:
                return failed_work(
                    _PGAborted("process group not configured/running")
                )
            # Enqueue under the lock: the queue object is swapped by
            # _teardown/_start_worker under the same lock, so this item can
            # never land on a retired queue with no worker to fail it.
            self._queue.put((self._generation, fn, fut, op))
        return Work(fut)

    def _worker_loop(self, gen: int, q: "queue.Queue") -> None:
        superseded = False
        while True:
            item = q.get()
            if item is None:
                return
            item_gen, fn, fut, op = item
            with self._lock:
                superseded = self._generation != gen
                errored = self._errored
            if superseded or item_gen != gen or errored is not None:
                # Keep draining so every queued Work resolves — abandoned
                # futures would hang their waiters forever.
                fut.set_exception(
                    errored or _PGAborted("process group reconfigured")
                )
                continue
            self._flight_op = _flightrec.start(
                op,
                kind="collective",
                generation=item_gen,
                rank=self._rank,
                world=self._world,
                replica_id=self._replica_id,
            )
            try:
                result = fn()
                with self._flight_swap_lock:
                    flight_op, self._flight_op = self._flight_op, None
                if flight_op is not None:
                    flight_op.finish("ok")
                fut.set_result(result)
            except Exception as e:  # noqa: BLE001 - latch every op failure
                # Flight-recorder dump BEFORE latching: when a wedged
                # collective dies (deadline, peer reset), the op-level state
                # — what was in flight, with whom, how far it got — is the
                # evidence the postmortem needs (reference dumps the NCCL
                # flight recorder on abort for the same reason,
                # torchft/process_group.py:89-108,830-838).
                self._dump_flight(f"collective failed: {e!r}", error=repr(e))
                with self._lock:
                    if self._errored is None:
                        self._errored = e
                fut.set_exception(e)

    # -- flight recorder ---------------------------------------------------

    def _flight_io(self, **kw: Any) -> None:
        """Merge current transfer state (direction, peer, tag, bytes) into
        the in-flight op record (worker or sender thread)."""
        op = self._flight_op
        if op is not None:
            op.update(**kw)

    def _flight_progress(self, nbytes: int) -> None:
        op = self._flight_op
        if op is not None:
            op.add_bytes(nbytes)

    def _dump_flight(self, reason: str, dump: bool = True, **extra: Any) -> None:
        """Finish the in-flight op as failed: the completed record lands in
        the process flight ring, a legacy ``abort`` event goes to the
        structured pipeline (JSONL sink when TORCHFT_EVENTS_FILE is set),
        and — unless the caller dumps separately — the whole ring is
        dumped to TORCHFT_FLIGHT_FILE."""
        with self._flight_swap_lock:
            flight_op, self._flight_op = self._flight_op, None
        if flight_op is None:
            return
        # Best-effort: the recorder must never mask the collective error.
        try:
            rec = flight_op.finish("error", reason=reason, **extra)
            from torchft_tpu.utils.logging import log_event

            f = {
                k: v
                for k, v in rec.items()
                if k not in ("status", "start_ns", "end_ns", "kind")
            }
            deadline = f.pop("deadline_mono", None)
            if deadline is not None:
                f["deadline_remaining_s"] = round(
                    deadline - time.monotonic(), 3
                )
            f["in_flight_s"] = round(
                (rec["end_ns"] - rec["start_ns"]) / 1e9, 3
            )
            log_event("abort", reason, **f)
            if dump:
                _flightrec.dump(reason, trigger="pg_abort")
        except Exception:  # noqa: BLE001 - recorder must never mask the error
            logger.exception("flight-recorder dump failed")

    # -- wire helpers ------------------------------------------------------

    @staticmethod
    def _read_exact_sock(sock: socket.socket, n: int, deadline: float) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            sock.settimeout(max(deadline - time.monotonic(), 0.001))
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed connection")
            buf.extend(chunk)
        return bytes(buf)

    def _peer(self, rank: int) -> _PeerConn:
        peer = self._peers.get(rank)
        if peer is None:
            raise _PGAborted(f"no connection to rank {rank}")
        return peer

    def _read_into_sock(
        self, sock: socket.socket, view: memoryview, deadline: float
    ) -> None:
        """recv_into a buffer — zero intermediate copies for payloads."""
        off, n = 0, len(view)
        while off < n:
            sock.settimeout(max(deadline - time.monotonic(), 0.001))
            got = sock.recv_into(view[off:], n - off)
            if got == 0:
                raise ConnectionError("peer closed connection")
            off += got
            self._flight_progress(got)

    def _send_msg(self, dst: int, tag: int, array: np.ndarray, deadline: float) -> None:
        peer = self._peer(dst)
        array = np.ascontiguousarray(array)
        header = pickle.dumps(
            {"tag": tag, "shape": array.shape, "dtype": str(array.dtype)}
        )
        self._flight_io(
            send_peer=dst, send_tag=tag, send_bytes=array.nbytes,
            deadline_mono=deadline,
        )
        wan = dst in self._inter_peers
        t0 = time.perf_counter()
        shaper_wait = 0.0
        if wan and self._rtt_s > 0.0:
            # First-byte latency of the WAN model: once per MESSAGE,
            # before any byte moves, independent of the bandwidth debt
            # the pacing loop below accrues (K pacing chunks still pay
            # 1x RTT).  Charged in the sender so a blocked receiver
            # observes the first byte RTT late, like a real WAN socket.
            time.sleep(self._rtt_s)
            shaper_wait += self._rtt_s
        # boundary-scoped shaping: only messages crossing the declared
        # topology boundary ride the modeled WAN link (flat/unset
        # topology: every peer — see __init__)
        bucket = self._bucket if wan else None
        if bucket is not None:
            shaper_wait += bucket.consume(8 + len(header))
        peer.sock.settimeout(max(deadline - time.monotonic(), 0.001))
        peer.sock.sendall(struct.pack(">II", len(header), array.nbytes) + header)
        if array.nbytes:
            # uint8 view, not memoryview.cast("B"): ml_dtypes arrays
            # (bfloat16/fp8 — the TPU training dtypes) have no
            # buffer-protocol format char and raise in cast(). The payload
            # still goes to the kernel straight from the array's buffer.
            view = memoryview(array.reshape(-1).view(np.uint8))
            if bucket is None:
                peer.sock.sendall(view)
            else:
                # shaped path: pace in 1 MB chunks so the bucket's sleeps
                # interleave with the peer's compute at sub-fragment
                # granularity (a single consume() of a GB payload would
                # model a link with GB-deep switch buffers)
                chunk_len = 1 << 20
                for off in range(0, len(view), chunk_len):
                    chunk = view[off : off + chunk_len]
                    shaper_wait += bucket.consume(len(chunk))
                    peer.sock.settimeout(
                        max(deadline - time.monotonic(), 0.001)
                    )
                    peer.sock.sendall(chunk)
        # Passive link-state measurement (utils/linkstats.py): every
        # completed send is one sample — bytes + wall on the reduction
        # plane, first-byte = the modeled RTT leg.  Shaper waits are
        # additionally attributed per peer host (worst-K label tier).
        label, is_local = self._link_labels.get(dst, ("unknown", not wan))
        _linkstats.record(
            label,
            "reduction",
            8 + len(header) + array.nbytes,
            time.perf_counter() - t0,
            first_byte_s=self._rtt_s if (wan and self._rtt_s > 0.0) else 0.0,
            local=is_local,
        )
        if shaper_wait > 0.0:
            _metrics.PG_WIRE_WAIT.labels(
                peer=_linkstats.LINKS.peer_topk_label(label)
            ).inc(shaper_wait)

    def _recv_msg(
        self,
        src: int,
        tag: int,
        deadline: float,
        out: "Optional[np.ndarray]" = None,
    ) -> np.ndarray:
        """Receive one tagged array; ``out`` receives in place (zero-alloc
        fast path for ring steps — reference pg_transport in-place recv
        analog, torchft/checkpointing/pg_transport.py:230-300)."""
        peer = self._peer(src)
        # record the blocked-on peer BEFORE the header read: a wedged recv
        # (peer never sends) hangs right here, and that is exactly the state
        # the flight recorder must capture
        self._flight_io(recv_peer=src, recv_tag=tag, deadline_mono=deadline)
        hlen, nbytes = struct.unpack(
            ">II", self._read_exact_sock(peer.sock, 8, deadline)
        )
        header = pickle.loads(self._read_exact_sock(peer.sock, hlen, deadline))
        if header["tag"] != tag:
            raise RuntimeError(
                f"collective tag mismatch: expected {tag}, got {header['tag']}"
            )
        if out is None:
            # Pool-backed receive: repeated collective shapes (ring chunks,
            # the quantized pipeline's per-chunk wire buffers) re-take the
            # SAME pages their consumers gave back, so steady-state receive
            # allocation — and its mmap page-fault bill — is zero.  Buffers
            # that escape to callers simply never return to the pool (take
            # falls back to np.empty on a miss), same contract as before.
            out = _pool.take(header["shape"], np.dtype(header["dtype"]))
            if out.nbytes != nbytes:
                raise RuntimeError(
                    f"collective payload size mismatch: header says {nbytes},"
                    f" shape/dtype imply {out.nbytes}"
                )
        else:
            _check_recv_buffer(out, header["shape"], header["dtype"])
            if out.nbytes != nbytes:
                raise RuntimeError(
                    f"collective payload size mismatch: header says {nbytes},"
                    f" shape/dtype imply {out.nbytes}"
                )
        self._flight_io(recv_bytes=nbytes)
        if nbytes:
            # uint8 view for ml_dtypes compat (see _send_msg)
            self._read_into_sock(
                peer.sock, memoryview(out.reshape(-1).view(np.uint8)), deadline
            )
        return out

    def _exchange(
        self,
        send_dst: int,
        send_tag: int,
        send_array: np.ndarray,
        recv_src: int,
        recv_tag: int,
        deadline: float,
        recv_out: "Optional[np.ndarray]" = None,
    ) -> np.ndarray:
        """Simultaneous send+recv without deadlocking on full TCP buffers.

        Ring steps send and receive concurrently; pushing the send to the
        persistent sender thread keeps both directions draining even when
        payloads exceed socket buffer sizes.
        """
        sender = self._sender
        if sender is None:
            raise _PGAborted("process group not configured/running")
        send_fut = sender.submit(
            self._send_msg, send_dst, send_tag, send_array, deadline
        )
        send_err: "Optional[BaseException]" = None
        try:
            received = self._recv_msg(recv_src, recv_tag, deadline, out=recv_out)
        finally:
            # always reap the send: the socket stream must never be left
            # mid-write when the next step starts (a recv error still
            # propagates; it takes precedence over any send error)
            try:
                send_fut.result(
                    timeout=max(deadline - time.monotonic(), 0.001) + 1.0
                )
            except concurrent_futures.TimeoutError:
                send_err = TimeoutError(
                    "collective send did not complete by deadline"
                )
            except BaseException as e:  # noqa: BLE001 - re-raised below
                send_err = e
        if send_err is not None:
            raise send_err
        return received

    # -- collectives -------------------------------------------------------

    def allreduce(self, arrays: "List[Any]", op: str = REDUCE_SUM) -> Work:
        deadline_budget = self._timeout

        def run() -> List[np.ndarray]:
            # device→host materialization happens HERE, on the PG worker:
            # for jax-array inputs `_as_numpy` blocks on device compute +
            # transfer, and doing that on the caller thread would stall it
            # for the whole sync instead of letting the submit return
            # immediately (the DiLoCo overlap pattern: outer-grad allreduce
            # rides behind the next fragment's inner steps).
            deadline = time.monotonic() + deadline_budget
            np_arrays = [_as_numpy(a) for a in arrays]
            return self._allreduce_coalesced(np_arrays, op, deadline)

        work = self._submit(run, op="allreduce")
        # Wire accounting on the UNQUANTIZED path too (parity with the
        # quantized collectives' measured wire_bytes, so bench/diagnose
        # compare f32 vs int8 traffic honestly): per-rank ring egress from
        # the same bucket plan the reduce will use, computed synchronously
        # from shapes/dtypes — device arrays stay unmaterialized.
        def _leaf(a: Any) -> "Tuple[np.dtype, int]":
            if not hasattr(a, "dtype") or not hasattr(a, "size"):
                a = np.asarray(a)
            return _accumulation_dtype(np.dtype(a.dtype)), int(a.size)

        try:
            work.wire_bytes = self._ring_wire_bytes(
                [_leaf(a) for a in arrays], self._world
            )
            work.unquantized_wire_bytes = work.wire_bytes
        except Exception:  # noqa: BLE001 - accounting must not fail the op
            logger.debug("allreduce wire accounting failed", exc_info=True)
        return work

    # Pack small same-acc-dtype leaves into buckets up to this many bytes.
    # Below the cap, coalescing wins (one ring amortizes per-message
    # latency: measured 10x at 28 tiny leaves); above it, the extra
    # concat/split memcpy costs more than the saved round trips, so big
    # leaves ring solo (zero-copy path).
    BUCKET_BYTES = 4 * 1024 * 1024

    @classmethod
    def _plan_buckets(
        cls, leaves: "List[Tuple[np.dtype, int]]"
    ) -> "List[Tuple[np.dtype, List[int], int]]":
        """Greedy same-accumulation-dtype buckets under ``BUCKET_BYTES``.

        ``leaves``: per-leaf (acc dtype, element count).  Returns
        ``(acc, leaf indices, total elements)`` per bucket, order-
        preserving — the one plan both the reduce and the wire-byte
        accounting derive from.
        """
        buckets: "List[Tuple[np.dtype, List[int], int]]" = []
        bucket_bytes: "List[int]" = []
        open_bucket: "Dict[np.dtype, int]" = {}  # acc dtype -> bucket index
        for i, (acc, size) in enumerate(leaves):
            nbytes = size * acc.itemsize
            if nbytes >= cls.BUCKET_BYTES:
                buckets.append((acc, [i], size))
                bucket_bytes.append(nbytes)
                continue
            bi = open_bucket.get(acc)
            if bi is not None and bucket_bytes[bi] + nbytes <= cls.BUCKET_BYTES:
                buckets[bi][1].append(i)
                buckets[bi] = (acc, buckets[bi][1], buckets[bi][2] + size)
                bucket_bytes[bi] += nbytes
            else:
                buckets.append((acc, [i], size))
                bucket_bytes.append(nbytes)
                open_bucket[acc] = len(buckets) - 1
        return buckets

    @classmethod
    def _ring_wire_bytes(
        cls, leaves: "List[Tuple[np.dtype, int]]", world: int
    ) -> int:
        """Per-rank ring-allreduce egress for these leaves: each bucket
        rings once, sending 2*(w-1) chunk-sized messages (reduce-scatter
        half + allgather half) of its accumulation dtype."""
        if world <= 1:
            return 0
        total = 0
        for acc, _idxs, elems in cls._plan_buckets(leaves):
            chunk = -(-elems // world)
            total += 2 * (world - 1) * chunk * acc.itemsize
        return total

    def _allreduce_coalesced(
        self, arrays: "List[np.ndarray]", op: str, deadline: float
    ) -> "List[np.ndarray]":
        """Bucketized allreduce of a gradient pytree's leaves.

        A gradient pytree is many small leaves; ringing each one costs
        2*(w-1) latency-bound exchanges per leaf. Same-accumulation-dtype
        leaves pack greedily into <= BUCKET_BYTES buckets that ring once
        (the reference's bucketized-allreduce idea,
        TORCHFT_USE_BUCKETIZATION, local_sgd.py:29); oversized leaves ring
        solo on the zero-copy path. Order-preserving.
        """
        if len(arrays) <= 1 or self._world == 1:
            # world==1: _allreduce_one is a pure copy; skip bucketing work
            # entirely (the post-failure shrunken-group hot path)
            return [self._allreduce_one(a, op, deadline) for a in arrays]
        buckets = self._plan_buckets(
            [(_accumulation_dtype(a.dtype), a.size) for a in arrays]
        )
        results: "List[Optional[np.ndarray]]" = [None] * len(arrays)
        for acc_dtype, idxs, _ in buckets:
            if len(idxs) == 1:
                i = idxs[0]
                results[i] = self._allreduce_one(arrays[i], op, deadline)
                continue
            # cast leaves individually: mixed input dtypes sharing one acc
            # dtype (f16+f32, bf16) may not have a numpy promotion rule
            flat = np.concatenate(
                [
                    np.ascontiguousarray(arrays[i])
                    .ravel()
                    .astype(acc_dtype, copy=False)
                    for i in idxs
                ]
            )
            reduced = self._allreduce_one(flat, op, deadline)
            off = 0
            for i in idxs:
                n = arrays[i].size
                results[i] = (
                    reduced[off : off + n]
                    .astype(arrays[i].dtype, copy=False)
                    .reshape(arrays[i].shape)
                )
                off += n
        return results  # type: ignore[return-value]

    def _allreduce_one(self, array: np.ndarray, op: str, deadline: float) -> np.ndarray:
        w, r = self._world, self._rank
        if w == 1:
            return array.copy()
        acc_dtype = _accumulation_dtype(array.dtype)
        inplace_reduce = _REDUCE_UFUNCS[op]
        n = array.size
        chunk = -(-n // w)
        # single private buffer; chunks are views of it, so ring steps
        # receive in place and reduce in place — the only full-size copies
        # are the pad-in and (if dtype widened) the cast back out
        # buf escapes to the caller as the result view — not poolable;
        # scratch is private to this call and its size repeats every ring
        # (page-fault amortization, utils/bufpool.py)
        buf = np.empty(chunk * w, dtype=acc_dtype)
        buf[:n] = array.ravel()
        if chunk * w > n:
            buf[n:] = 0
        chunks = [buf[i * chunk : (i + 1) * chunk] for i in range(w)]
        scratch = _pool.take(chunk, acc_dtype)

        nxt, prv = (r + 1) % w, (r - 1) % w
        # ring reduce-scatter: after w-1 steps, chunk (r+1)%w is fully reduced
        for step in range(w - 1):
            send_idx = (r - step) % w
            recv_idx = (r - step - 1) % w
            self._exchange(
                nxt, 100 + step, chunks[send_idx], prv, 100 + step, deadline,
                recv_out=scratch,
            )
            inplace_reduce(chunks[recv_idx], scratch, out=chunks[recv_idx])
        # ring allgather of the reduced chunks, received straight into place
        for step in range(w - 1):
            send_idx = (r - step + 1) % w
            recv_idx = (r - step) % w
            self._exchange(
                nxt, 200 + step, chunks[send_idx], prv, 200 + step, deadline,
                recv_out=chunks[recv_idx],
            )
        _pool.give(scratch)
        result = buf[:n]
        if op == REDUCE_AVG:
            if np.issubdtype(acc_dtype, np.floating):
                result /= w
            else:
                result = result / w
        return np.asarray(result, dtype=array.dtype).reshape(array.shape)

    def allgather(self, array: Any) -> Work:
        np_array = _as_numpy(array)
        deadline_budget = self._timeout

        def run() -> List[np.ndarray]:
            deadline = time.monotonic() + deadline_budget
            w, r = self._world, self._rank
            if w == 1:
                return [np_array.copy()]
            pieces: List[Optional[np.ndarray]] = [None] * w
            pieces[r] = np.ascontiguousarray(np_array)
            nxt, prv = (r + 1) % w, (r - 1) % w
            for step in range(w - 1):
                send_idx = (r - step) % w
                recv_idx = (r - step - 1) % w
                pieces[recv_idx] = self._exchange(
                    nxt, 300 + step, pieces[send_idx], prv, 300 + step, deadline
                )
            # received pieces are already private allocations from
            # _recv_msg; only the own piece aliases the caller's array and
            # needs a defensive copy
            pieces[r] = pieces[r].copy()  # type: ignore[union-attr]
            return pieces  # type: ignore[return-value]

        return self._submit(run, op="allgather")

    def broadcast(self, array: Any, root: int = 0) -> Work:
        np_array = _as_numpy(array)
        deadline_budget = self._timeout

        def run() -> np.ndarray:
            deadline = time.monotonic() + deadline_budget
            w, r = self._world, self._rank
            if w == 1:
                return np_array.copy()
            if r == root:
                for peer in range(w):
                    if peer != r:
                        self._send_msg(peer, 400, np_array, deadline)
                return np_array.copy()
            return self._recv_msg(root, 400, deadline)

        return self._submit(run, op="broadcast")

    def reduce_scatter(self, array: Any, op: str = REDUCE_SUM) -> Work:
        np_array = _as_numpy(array)
        deadline_budget = self._timeout

        def run() -> np.ndarray:
            deadline = time.monotonic() + deadline_budget
            w, r = self._world, self._rank
            if w == 1:
                return np_array.copy()
            if np_array.shape[0] % w != 0:
                raise ValueError(
                    f"reduce_scatter dim0 {np_array.shape[0]} not divisible by {w}"
                )
            inplace_reduce = _REDUCE_UFUNCS[op]
            rows = np_array.shape[0] // w
            acc_dtype = _accumulation_dtype(np_array.dtype)
            buf = np.empty(np_array.shape, dtype=acc_dtype)
            buf[...] = np_array
            chunks = [buf[i * rows : (i + 1) * rows] for i in range(w)]
            scratch = np.empty(chunks[0].shape, dtype=acc_dtype)
            nxt, prv = (r + 1) % w, (r - 1) % w
            # Ring schedule shifted by one vs allreduce so each rank ends
            # holding its *own* fully-reduced chunk r.
            for step in range(w - 1):
                send_idx = (r - step - 1) % w
                recv_idx = (r - step - 2) % w
                self._exchange(
                    nxt, 500 + step, chunks[send_idx], prv, 500 + step, deadline,
                    recv_out=scratch,
                )
                inplace_reduce(chunks[recv_idx], scratch, out=chunks[recv_idx])
            result = chunks[r]
            if op == REDUCE_AVG:
                if np.issubdtype(acc_dtype, np.floating):
                    result /= w
                else:
                    result = result / w
            # copy: returning a view of chunks[r] would pin the w-times
            # larger accumulation buffer for as long as the caller holds
            # the result
            return np.array(result, dtype=np_array.dtype)

        return self._submit(run, op="reduce_scatter")

    def alltoall(self, arrays: "List[Any]") -> Work:
        np_arrays = [_as_numpy(a) for a in arrays]
        deadline_budget = self._timeout

        def run() -> List[np.ndarray]:
            deadline = time.monotonic() + deadline_budget
            w, r = self._world, self._rank
            if len(np_arrays) != w:
                raise ValueError(f"alltoall needs {w} arrays, got {len(np_arrays)}")
            out: List[Optional[np.ndarray]] = [None] * w
            out[r] = np_arrays[r].copy()
            for offset in range(1, w):
                dst = (r + offset) % w
                src = (r - offset) % w
                out[src] = self._exchange(
                    dst, 600 + offset, np_arrays[dst], src, 600 + offset, deadline
                )
            return out  # type: ignore[return-value]

        return self._submit(run, op="alltoall")

    def sendrecv(self, array: Any, dst: int, src: int, tag: int = 0) -> Work:
        np_array = _as_numpy(array)
        deadline_budget = self._timeout

        def run() -> np.ndarray:
            deadline = time.monotonic() + deadline_budget
            if dst == self._rank and src == self._rank:
                return np.ascontiguousarray(np_array).copy()
            # the same concurrent send+recv primitive the ring steps use:
            # the send drains on the sender thread while this worker
            # blocks on the receive, so paired exchanges never deadlock
            # on full TCP buffers
            return self._exchange(
                dst, 2000 + tag, np_array, src, 2000 + tag, deadline
            )

        return self._submit(run, op="sendrecv")

    def send(self, array: Any, dst: int, tag: int = 0) -> Work:
        np_array = _as_numpy(array)
        deadline_budget = self._timeout

        def run() -> None:
            deadline = time.monotonic() + deadline_budget
            self._send_msg(dst, 1000 + tag, np_array, deadline)

        return self._submit(run, op="send")

    def recv(self, src: int, tag: int = 0, out: "Optional[np.ndarray]" = None) -> Work:
        """``out``: receive straight into this buffer (shape/dtype must
        match the wire) — the zero-alloc path for healing into live state."""
        deadline_budget = self._timeout

        def run() -> np.ndarray:
            deadline = time.monotonic() + deadline_budget
            return self._recv_msg(src, 1000 + tag, deadline, out=out)

        return self._submit(run, op="recv")


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------


class ProcessGroupWrapper(ProcessGroup):
    """Forwards every op to an inner PG; base for behavior-modifying wrappers."""

    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__(pg._timeout)
        self._pg = pg

    @property
    def parent(self) -> ProcessGroup:
        return self._pg

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        self._pg.configure(store_addr, replica_id, rank, world_size)

    def abort(self) -> None:
        self._pg.abort()

    def errored(self) -> Optional[Exception]:
        return self._pg.errored()

    def set_timeout(self, timeout: float) -> None:
        self._pg.set_timeout(timeout)

    def rank(self) -> int:
        return self._pg.rank()

    def size(self) -> int:
        return self._pg.size()

    def allreduce(self, arrays: "List[Any]", op: str = REDUCE_SUM) -> Work:
        return self._wrap(
            self._pg.allreduce(arrays, op),
            lambda: [_as_numpy(a) for a in arrays],
        )

    def allgather(self, array: Any) -> Work:
        return self._wrap(self._pg.allgather(array), lambda: [_as_numpy(array)])

    def broadcast(self, array: Any, root: int = 0) -> Work:
        return self._wrap(self._pg.broadcast(array, root), lambda: _as_numpy(array))

    def reduce_scatter(self, array: Any, op: str = REDUCE_SUM) -> Work:
        # Fallback keeps the success-path *shape*: this rank's row chunk.
        def fallback() -> np.ndarray:
            np_array = _as_numpy(array)
            w = max(self._pg.size(), 1)
            rows = np_array.shape[0] // w if np_array.shape[0] >= w else 1
            r = max(self._pg.rank(), 0)
            return np_array[r * rows : (r + 1) * rows]

        return self._wrap(self._pg.reduce_scatter(array, op), fallback)

    def alltoall(self, arrays: "List[Any]") -> Work:
        return self._wrap(
            self._pg.alltoall(arrays), lambda: [_as_numpy(a) for a in arrays]
        )

    def sendrecv(self, array: Any, dst: int, src: int, tag: int = 0) -> Work:
        # fallback shaped like the success path: plan exchanges are
        # same-shape both directions, so the sent array stands in
        return self._wrap(
            self._pg.sendrecv(array, dst, src, tag),
            lambda: _as_numpy(array),
        )

    def send(self, array: Any, dst: int, tag: int = 0) -> Work:
        return self._wrap(self._pg.send(array, dst, tag), lambda: None)

    def recv(self, src: int, tag: int = 0, out: "Optional[np.ndarray]" = None) -> Work:
        return self._wrap(self._pg.recv(src, tag, out=out), lambda: None)

    def _wrap(self, work: Work, fallback: "Callable[[], Any]") -> Work:
        """Hook: ``fallback()`` builds a success-path-shaped substitute result."""
        return work


class ErrorSwallowingProcessGroupWrapper(ProcessGroupWrapper):
    """After the first error, ops become no-ops returning their inputs.

    Reference: torchft/process_group.py:1123-1179 — lets the training loop
    continue through a failed step; Manager.should_commit observes the error
    and triggers reconfigure.
    """

    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__(pg)
        self._swallowed: Optional[Exception] = None

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        self._swallowed = None
        super().configure(store_addr, replica_id, rank, world_size)

    def errored(self) -> Optional[Exception]:
        return self._swallowed or super().errored()

    def report_error(self, exc: Exception) -> None:
        self._swallowed = exc

    def _wrap(self, work: Work, fallback: "Callable[[], Any]") -> Work:
        if self._swallowed is not None:
            return completed_work(fallback())

        out: Future = Future()

        def _done(f: "Future[Any]") -> None:
            exc = f.exception()
            if exc is not None:
                if self._swallowed is None:
                    self._swallowed = (
                        exc if isinstance(exc, Exception) else RuntimeError(str(exc))
                    )
                # Resolve with a result shaped like the success path so the
                # training loop proceeds; Manager observes errored() later.
                out.set_result(fallback())
            else:
                out.set_result(f.result())

        work.get_future().add_done_callback(_done)
        return Work(out)


class FakeProcessGroupWrapper(ProcessGroupWrapper):
    """Test-only fault injection: fail the *future* of upcoming ops.

    Reference: torchft/process_group.py:1182-1230 — lets integration tests
    inject an allreduce failure at a chosen step without touching sockets.
    """

    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__(pg)
        self._next_op_error: Optional[Exception] = None
        self._next_configure_error: Optional[Exception] = None

    def report_future_error(self, exc: Exception) -> None:
        self._next_op_error = exc

    def report_configure_error(self, exc: Exception) -> None:
        self._next_configure_error = exc

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        if self._next_configure_error is not None:
            exc, self._next_configure_error = self._next_configure_error, None
            raise exc
        super().configure(store_addr, replica_id, rank, world_size)

    def _wrap(self, work: Work, fallback: "Callable[[], Any]") -> Work:
        if self._next_op_error is not None:
            exc, self._next_op_error = self._next_op_error, None
            return failed_work(exc)
        return work


class ManagedProcessGroup(ProcessGroup):
    """A ProcessGroup whose allreduce routes through a ``Manager``.

    Reference: torchft/process_group.py:1233-1266 — lets code written
    against the plain ProcessGroup API (e.g. a gradient-averaging hook or a
    mesh dimension) transparently get quorum-aware, error-swallowing,
    participant-count-scaled allreduce.  ``size()`` reports the *live*
    participant count so loss/gradient scaling stays correct as replicas
    fail and join; all other collectives and lifecycle calls are invalid on
    this wrapper — the Manager owns quorum and reconfiguration.
    """

    def __init__(self, manager: Any) -> None:
        super().__init__()
        self._manager = manager

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        raise RuntimeError(
            "ManagedProcessGroup is configured by its Manager, not directly"
        )

    def abort(self) -> None:
        raise RuntimeError("ManagedProcessGroup cannot be aborted directly")

    def shutdown(self) -> None:
        """No-op: the Manager owns the underlying PG's lifecycle."""

    def errored(self) -> Optional[Exception]:
        return self._manager.errored()

    def rank(self) -> int:
        """Replica rank within the live quorum.

        Raises ``NotParticipatingError`` while this replica is healing /
        excluded from the current quorum.  Returning a fake 0 here would let
        a healing replica silently consume rank-0's data shard; callers that
        can tolerate non-participation should use
        ``Manager.participating_rank()`` (returns ``None``) or
        ``ManagedDeviceMesh.global_batch_slice`` (returns the empty slice).
        """
        r = self._manager.participating_rank()
        if r is None:
            raise NotParticipatingError(
                "replica is not participating in the current quorum "
                "(healing or excluded); no rank is defined this step"
            )
        return r

    def size(self) -> int:
        return self._manager.num_participants()

    def allreduce(self, arrays: "List[Any]", op: str = REDUCE_SUM) -> Work:
        # Manager.allreduce takes a pytree; a list of arrays is one.
        return self._manager.allreduce(list(arrays), reduce_op=op)

    def allgather(self, array: Any) -> Work:
        return failed_work(RuntimeError("ManagedProcessGroup only supports allreduce"))

    def broadcast(self, array: Any, root: int = 0) -> Work:
        return failed_work(RuntimeError("ManagedProcessGroup only supports allreduce"))

    def reduce_scatter(self, array: Any, op: str = REDUCE_SUM) -> Work:
        return failed_work(RuntimeError("ManagedProcessGroup only supports allreduce"))

    def alltoall(self, arrays: "List[Any]") -> Work:
        return failed_work(RuntimeError("ManagedProcessGroup only supports allreduce"))

    def send(self, array: Any, dst: int, tag: int = 0) -> Work:
        return failed_work(RuntimeError("ManagedProcessGroup only supports allreduce"))

    def recv(self, src: int, tag: int = 0, out: "Optional[np.ndarray]" = None) -> Work:
        return failed_work(RuntimeError("ManagedProcessGroup only supports allreduce"))


# ---------------------------------------------------------------------------
# Subprocess-isolated ("Baby") process groups
# ---------------------------------------------------------------------------

# Arrays >= this cross the parent<->worker boundary as POSIX shared-memory
# segments instead of pickled pipe bytes: the pipe path costs two full
# serializations plus 2x the payload in 64 KiB pipe writes per direction
# (reference streams tensors with backpressure instead of pickling,
# torchft/process_group.py:1602-1645).
_SHM_MIN_BYTES = 1 << 20


class _ShmRef:
    """Pickle-tiny stand-in for an array staged in shared memory."""

    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name: str, shape: "Tuple[int, ...]", dtype: str) -> None:
        self.name = name
        self.shape = shape
        self.dtype = dtype

    def __getstate__(self):
        return (self.name, self.shape, self.dtype)

    def __setstate__(self, state):
        self.name, self.shape, self.dtype = state


def _shm_untrack(shm: Any) -> None:
    """Drop the resource-tracker claim on a segment.

    Parent and spawned workers share ONE tracker process whose cache is a
    set of names, and this Python registers on attach as well as create —
    so cross-process register/unregister pairs can't be balanced per
    process.  Protocol instead: every create/attach untracks immediately
    (the set stays empty of our names) and :func:`_shm_unlink_balanced`
    re-registers just before the final unlink so unlink's internal
    unregister finds the entry.  Tradeoff: the tracker won't clean our
    segments if a process dies mid-op — the Baby design's parent survives
    and does (``_release_shms`` / the view finalizers)."""
    from multiprocessing import resource_tracker

    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:  # noqa: BLE001 - tracker API is version-dependent
        pass


def _shm_unlink_balanced(shm: Any) -> None:
    """Unlink with tracker bookkeeping balanced (see :func:`_shm_untrack`);
    safe when another handle already unlinked the name."""
    from multiprocessing import resource_tracker

    try:
        resource_tracker.register(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:  # noqa: BLE001
        pass
    try:
        shm.unlink()  # internal unregister consumes the registration
    except FileNotFoundError:
        _shm_untrack(shm)


def _finalize_shm_view(shm: Any) -> None:
    shm.close()
    _shm_unlink_balanced(shm)


class _ShmIn:
    """A resolved input segment inside the worker: kept open for the op's
    lifetime and reusable as the (already warm) result buffer."""

    __slots__ = ("ref", "shm", "view", "used")

    def __init__(self, ref: "_ShmRef", shm: Any, view: np.ndarray) -> None:
        self.ref = ref
        self.shm = shm
        self.view = view
        self.used = False


def _shm_stage_value(value: Any, created: "List[Any]") -> Any:
    """Replace large arrays in ``value`` (an array or list of arrays) with
    ``_ShmRef``s backed by fresh segments appended to ``created``."""
    from multiprocessing import shared_memory

    def stage(a: Any) -> Any:
        if not isinstance(a, np.ndarray) or a.nbytes < _SHM_MIN_BYTES:
            return a
        a = np.ascontiguousarray(a)
        shm = shared_memory.SharedMemory(create=True, size=a.nbytes)
        _shm_untrack(shm)
        dst = np.ndarray(a.shape, dtype=a.dtype, buffer=shm.buf)
        dst[...] = a
        created.append(shm)
        return _ShmRef(shm.name, a.shape, str(a.dtype))

    if isinstance(value, list):
        return [stage(a) for a in value]
    return stage(value)


def _shm_resolve_value(value: Any, opened: "List[_ShmIn]") -> Any:
    """Inverse of :func:`_shm_stage_value`: materialize ``_ShmRef``s as
    zero-copy views; the backing segments are appended to ``opened`` and
    must outlive the views."""
    from multiprocessing import shared_memory

    def resolve(a: Any) -> Any:
        if not isinstance(a, _ShmRef):
            return a
        shm = shared_memory.SharedMemory(name=a.name)
        _shm_untrack(shm)  # the parent owns (and unlinks) input segments
        view = np.ndarray(a.shape, dtype=np.dtype(a.dtype), buffer=shm.buf)
        opened.append(_ShmIn(a, shm, view))
        return view

    if isinstance(value, list):
        return [resolve(a) for a in value]
    return resolve(value)


def _shm_stage_result(value: Any, inputs: "List[_ShmIn]") -> Any:
    """Worker-side result staging: write each large result array into a
    matching (shape+dtype) input segment — already-warm pages, no fresh
    allocation — falling back to a fresh segment.  Small values pickle."""
    from multiprocessing import shared_memory

    def stage(a: Any) -> Any:
        if not isinstance(a, np.ndarray) or a.nbytes < _SHM_MIN_BYTES:
            return a
        for inp in inputs:
            if (
                not inp.used
                and inp.view.shape == a.shape
                and inp.view.dtype == a.dtype
            ):
                inp.used = True
                if inp.view is not a and not np.shares_memory(inp.view, a):
                    inp.view[...] = a
                return inp.ref
        shm = shared_memory.SharedMemory(create=True, size=a.nbytes)
        _shm_untrack(shm)  # ownership passes to the parent (it unlinks)
        np.ndarray(a.shape, dtype=a.dtype, buffer=shm.buf)[...] = a
        shm.close()
        return _ShmRef(shm.name, a.shape, str(a.dtype))

    if isinstance(value, list):
        return [stage(a) for a in value]
    return stage(value)


def _shm_discard_value(value: Any) -> None:
    """Reclaim result segments whose message will never be consumed (reader
    superseded by reconfigure, future already failed): worker-created
    segments are untracked, so dropping their refs without unlinking would
    pin the payload in /dev/shm forever."""
    from multiprocessing import shared_memory

    refs = value if isinstance(value, list) else [value]
    for a in refs:
        if not isinstance(a, _ShmRef):
            continue
        try:
            shm = shared_memory.SharedMemory(name=a.name)
        except FileNotFoundError:
            continue  # an input-reused segment the parent already unlinked
        _shm_untrack(shm)
        shm.close()
        _shm_unlink_balanced(shm)


def _shm_wrap_value(value: Any) -> Any:
    """Parent-side result decode: materialize each ``_ShmRef`` as a ZERO-
    COPY view of its segment; a GC finalizer on the array closes (and, for
    worker-created segments, unlinks) the mapping.  Must run before the
    parent unlinks the op's input segments (attach needs the name; the
    mapping survives the unlink)."""
    import weakref

    from multiprocessing import shared_memory

    def wrap(a: Any) -> Any:
        if not isinstance(a, _ShmRef):
            return a
        shm = shared_memory.SharedMemory(name=a.name)
        _shm_untrack(shm)
        arr = np.ndarray(a.shape, dtype=np.dtype(a.dtype), buffer=shm.buf)
        weakref.finalize(arr, _finalize_shm_view, shm)
        return arr

    if isinstance(value, list):
        return [wrap(a) for a in value]
    return wrap(value)


def _baby_worker(
    pg_cls: type,
    pipe_conn: Any,
    store_addr: str,
    replica_id: str,
    rank: int,
    world_size: int,
    timeout: float,
) -> None:
    """Worker-process loop: run the real PG, execute ops from the pipe.

    Protocol (reference worker loop, torchft/process_group.py:1470-1600):
    parent sends ``(op_id, func_name, args, kwargs)``; worker runs the op,
    *waits* the resulting Work, and replies ``(op_id, value)`` on success or
    ``(op_id, exception)`` on failure. ``(op_id, "__shutdown__", ...)``
    exits the loop. Collectives execute on a small thread pool so an
    in-flight op cannot block the command loop (and ops on distinct tags can
    overlap), matching the parent's async Work API.
    """
    import concurrent.futures as cf

    pg = pg_cls()
    pg.set_timeout(timeout)
    try:
        pg.configure(store_addr, replica_id, rank, world_size)
    except Exception as e:  # noqa: BLE001 - shipped to parent
        try:
            # bare exception: _MonitoredPipe re-raises it in the parent's
            # configure with the real root cause intact
            pipe_conn.send(e)
        except (BrokenPipeError, OSError):
            pass
        return
    pipe_conn.send((-1, "configured"))

    send_lock = _lockcheck.lock("pg.baby.pipe_send")
    pool = cf.ThreadPoolExecutor(max_workers=4, thread_name_prefix="baby_op")

    def _send(op_id: int, value: Any) -> None:
        with send_lock:
            try:
                pipe_conn.send((op_id, value))
            except (BrokenPipeError, OSError):
                pass

    def _finish(op_id: int, work: Any, opened: "List[_ShmIn]") -> None:
        try:
            try:
                value = (
                    work.wait(timeout=timeout) if isinstance(work, Work) else work
                )
            except Exception as e:  # noqa: BLE001 - shipped to parent
                _send(op_id, e)
                return
            # stage results into the warm input segments where shapes
            # match (allreduce/broadcast/alltoall), fresh segments
            # otherwise; the parent owns every segment from here
            value = _shm_stage_result(value, opened)
            _send(op_id, value)
        finally:
            for inp in opened:
                inp.shm.close()

    try:
        while True:
            try:
                msg = pipe_conn.recv()
            except (EOFError, OSError):
                break
            op_id, func, args, kwargs = msg
            if func == "__shutdown__":
                break
            # enqueue on THIS thread so ops hit the inner PG in pipe order
            # (pipelined collectives must match across ranks); only the
            # wait() moves to the pool so an in-flight op can't block the
            # command loop.
            opened: "List[_ShmIn]" = []
            try:
                args = [_shm_resolve_value(a, opened) for a in args]
                work = getattr(pg, func)(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - shipped to parent
                for inp in opened:
                    inp.shm.close()
                _send(op_id, e)
                continue
            pool.submit(_finish, op_id, work, opened)
    finally:
        pool.shutdown(wait=False)
        try:
            pg.shutdown()
        except Exception:  # noqa: BLE001 - worker teardown is best-effort
            pass


class ProcessGroupBaby(ProcessGroup):
    """Runs the real PG in a spawned subprocess for crash isolation.

    Reference: torchft/process_group.py:1358-1828.  ``configure`` kills any
    existing worker and spawns a fresh one (subprocess restart *is* the
    reconfigure); every collective is shipped over a command pipe and
    returns a Work backed by a future that a reader thread resolves.
    ``abort()`` kills the worker — the hard-cancel that a wedged socket
    stack cannot block.

    Workers start via the ``spawn`` method, so (as with any spawning
    library) the using script must be importable without side effects —
    guard its entry point with ``if __name__ == "__main__":``.
    """

    PG_CLASS: type = None  # set by subclasses

    def __init__(self, timeout: float = 60.0, max_active_work: int = 16) -> None:
        """``max_active_work``: backpressure cap on in-flight ops — each op
        can hold staged shared-memory payloads, so an unbounded submitter
        would pin unbounded host memory (reference num_active_work,
        torchft/process_group.py:1602-1645).  0 disables the cap."""
        super().__init__(timeout)
        self._proc: Optional[Any] = None
        self._pipe: Optional[Any] = None
        self._rank = -1
        self._world = -1
        self._errored_exc: Optional[Exception] = None
        self._next_op_id = 0
        self._baby_replica_id = ""
        self._gen = 0  # bumped per configure; guards against stale readers
        self._pending: Dict[int, Future] = {}
        self._pending_shm: "Dict[int, List[Any]]" = {}
        self._max_active_work = max_active_work
        self._lock = _lockcheck.lock("pg.baby.state")
        self._cond = threading.Condition(self._lock)
        self._reader: Optional[threading.Thread] = None

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int) -> None:
        import multiprocessing as mp

        _faults.check("pg.reconfigure", replica=replica_id)
        self._kill_worker()
        self._errored_exc = None
        self._baby_replica_id = replica_id
        self._rank = rank
        self._world = world_size

        ctx = mp.get_context("spawn")
        parent_conn, child_conn = ctx.Pipe()
        self._proc = ctx.Process(
            target=_baby_worker,
            args=(
                type(self).PG_CLASS,
                child_conn,
                store_addr,
                replica_id,
                rank,
                world_size,
                self._timeout,
            ),
            daemon=True,
        )
        self._proc.start()
        child_conn.close()

        from torchft_tpu.multiprocessing import _MonitoredPipe

        pipe = _MonitoredPipe(parent_conn)
        with self._lock:
            self._pipe = pipe
            self._gen += 1
            gen = self._gen
        # first message acks configure; a worker-side configure failure
        # arrives as a bare exception that _MonitoredPipe re-raises here
        ack = self._recv_ack(pipe)
        if ack != (-1, "configured"):
            self._kill_worker()
            raise RuntimeError(f"unexpected configure ack from worker: {ack!r}")

        self._reader = threading.Thread(
            target=self._read_loop,
            args=(pipe, gen),
            name="baby_pg_reader",
            daemon=True,
        )
        self._reader.start()
        _metrics.PG_RECONFIGURES.labels(transport="baby").inc()

    def _recv_ack(self, pipe: Any) -> Any:
        try:
            return pipe.recv(timeout=self._timeout)
        except Exception:
            self._kill_worker()
            raise

    def _read_loop(self, pipe: Any, gen: int) -> None:
        while True:
            try:
                op_id, value = pipe.recv(timeout=None)
            except Exception as e:  # noqa: BLE001 - includes EOF/reset/transport
                # EOFError (clean close) or ConnectionResetError (SIGKILL)
                # both mean the worker died; transported exceptions arrive
                # without an op id and are equally fatal to all pending ops.
                # The generation check inside _fail_all makes a stale reader
                # (whose PG was already reconfigured) a no-op.
                if isinstance(e, (EOFError, OSError)):
                    self._fail_all(RuntimeError(f"baby PG worker exited: {e!r}"), gen)
                else:
                    self._fail_all(
                        e if isinstance(e, Exception) else RuntimeError(str(e)), gen
                    )
                return
            with self._lock:
                if gen != self._gen:
                    # reconfigured under us; results no longer ours — but
                    # any worker-created result segments still need reaping
                    _shm_discard_value(value)
                    return
                fut = self._pending.pop(op_id, None)
                in_shms = self._pending_shm.pop(op_id, [])
                if fut is not None and isinstance(value, Exception):
                    self._errored_exc = self._errored_exc or value
                self._cond.notify_all()
            if fut is None or isinstance(value, Exception):
                self._release_shms(in_shms)
                if not isinstance(value, Exception):
                    _shm_discard_value(value)
                if fut is not None:
                    fut.set_exception(value)
                continue
            # decode BEFORE unlinking inputs: results may live in reused
            # input segments (attach needs the name; mappings survive)
            try:
                result = _shm_wrap_value(value)
            except Exception as e:  # noqa: BLE001 - decode failure
                self._release_shms(in_shms)
                fut.set_exception(e)
                continue
            self._release_shms(in_shms)
            fut.set_result(result)

    @staticmethod
    def _release_shms(shms: "List[Any]") -> None:
        for shm in shms:
            shm.close()
            _shm_unlink_balanced(shm)

    def _fail_all(self, exc: Exception, gen: "Optional[int]" = None) -> None:
        with self._lock:
            if gen is not None and gen != self._gen:
                return  # stale reader of a pre-reconfigure worker
            self._errored_exc = self._errored_exc or exc
            pending, self._pending = self._pending, {}
            pending_shm, self._pending_shm = self._pending_shm, {}
            self._cond.notify_all()
        for shms in pending_shm.values():
            self._release_shms(shms)
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    def _kill_worker(self) -> None:
        # claim pipe+proc under the lock: abort() and configure() can race
        # here, and nulling before close makes the reader thread see a stale
        # pipe (deliberate teardown), not a worker death. Bumping the
        # generation here (not just in configure) immediately invalidates
        # the old reader so it cannot latch an error after a reconfigure
        # clears the latched state.
        with self._lock:
            pipe, self._pipe = self._pipe, None
            proc, self._proc = self._proc, None
            self._gen += 1
        if pipe is not None:
            try:
                pipe.close()
            except OSError:
                pass
        if proc is not None:
            try:
                proc.terminate()
                proc.join(timeout=5)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5)
            except ValueError:
                pass  # process never started (configure failed mid-spawn)
        self._fail_all(_PGAborted("process group aborted"))

    def _submit(self, func: str, *args: Any, **kwargs: Any) -> Work:
        with self._lock:
            # backpressure: bound in-flight ops (each may pin staged shm)
            if self._max_active_work > 0:
                deadline = time.monotonic() + self._timeout
                while (
                    len(self._pending) >= self._max_active_work
                    and self._errored_exc is None
                    and self._pipe is not None
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(timeout=remaining):
                        return failed_work(
                            TimeoutError(
                                f"{len(self._pending)} ops in flight >= "
                                f"max_active_work={self._max_active_work} "
                                f"for {self._timeout}s"
                            )
                        )
            if self._errored_exc is not None:
                return failed_work(self._errored_exc)
            if self._pipe is None:
                return failed_work(RuntimeError("process group not configured"))
            op_id = self._next_op_id
            self._next_op_id += 1
            fut: Future = Future()
            self._pending[op_id] = fut
            pipe = self._pipe  # local ref: abort() may null the attribute
        # stage large payloads outside the lock (memcpy can be tens of ms);
        # the segments stay alive until the op resolves
        created: "List[Any]" = []
        try:
            args = tuple(_shm_stage_value(a, created) for a in args)
        except Exception as e:  # noqa: BLE001 - staging failure fails the op
            self._release_shms(created)
            with self._lock:
                self._pending.pop(op_id, None)
                self._cond.notify_all()
            return failed_work(e)
        with self._lock:
            if op_id in self._pending:
                self._pending_shm[op_id] = created
            else:
                # failed/aborted while staging; nothing will clean these
                self._release_shms(created)
                created = []
        try:
            pipe.send((op_id, func, args, kwargs))
        except (BrokenPipeError, OSError) as e:
            with self._lock:
                self._pending.pop(op_id, None)
                shms = self._pending_shm.pop(op_id, [])
                self._cond.notify_all()
            self._release_shms(shms)
            self._errored_exc = self._errored_exc or e
            return failed_work(e)
        return Work(fut).with_timeout(self._timeout)

    # -- ProcessGroup API --------------------------------------------------

    def abort(self) -> None:
        _metrics.PG_ABORTS.labels(transport="baby").inc()
        _flightrec.record(
            "pg.abort", status="abort", transport="baby",
            replica_id=self._baby_replica_id, rank=self._rank,
            world=self._world,
        )
        _flightrec.dump("baby process group aborted", trigger="pg_abort")
        self._kill_worker()  # latches _PGAborted via _fail_all

    def errored(self) -> Optional[Exception]:
        return self._errored_exc

    def shutdown(self) -> None:
        if self._pipe is not None:
            try:
                self._pipe.send((-1, "__shutdown__", (), {}))
            except (BrokenPipeError, OSError):
                pass
        self._kill_worker()

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._world

    def allreduce(self, arrays: "List[Any]", op: str = REDUCE_SUM) -> Work:
        return self._submit("allreduce", [_as_numpy(a) for a in arrays], op)

    def allgather(self, array: Any) -> Work:
        return self._submit("allgather", _as_numpy(array))

    def broadcast(self, array: Any, root: int = 0) -> Work:
        return self._submit("broadcast", _as_numpy(array), root)

    def reduce_scatter(self, array: Any, op: str = REDUCE_SUM) -> Work:
        return self._submit("reduce_scatter", _as_numpy(array), op)

    def alltoall(self, arrays: "List[Any]") -> Work:
        return self._submit("alltoall", [_as_numpy(a) for a in arrays])

    def sendrecv(self, array: Any, dst: int, src: int, tag: int = 0) -> Work:
        return self._submit("sendrecv", _as_numpy(array), dst, src, tag)

    def send(self, array: Any, dst: int, tag: int = 0) -> Work:
        return self._submit("send", _as_numpy(array), dst, tag)

    def recv(self, src: int, tag: int = 0, out: "Optional[np.ndarray]" = None) -> Work:
        work = self._submit("recv", src, tag)
        if out is None:
            return work
        # the worker can't share the caller's buffer; emulate in-place by
        # copying the (possibly shm-backed) result into it — with the same
        # validation the direct backend's wire reader applies
        def into(arr: np.ndarray) -> np.ndarray:
            _check_recv_buffer(out, arr.shape, str(arr.dtype))
            out[...] = arr
            return out

        return work.then(into)


class ProcessGroupBabyTCP(ProcessGroupBaby):
    """Subprocess-isolated ProcessGroupTCP (reference ProcessGroupBabyGloo
    analog, torchft/process_group.py:1883-1923)."""

    PG_CLASS = ProcessGroupTCP
