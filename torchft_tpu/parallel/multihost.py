"""Multi-host (multi-process) wiring for one replica group.

A real TPU slice beyond v5e-8 spans several hosts (a v5e-16 is 4 hosts);
one replica *group* is then N processes forming ONE jax multi-controller
runtime: ``jax.distributed.initialize`` builds the global device mesh,
XLA's SPMD partitioner runs the inner parallelism (dp/fsdp/tp/...) over
ICI with every process feeding its addressable shards, and the
fault-tolerance layer sits above it — one ``Manager`` per process with
``group_rank = process index``, sharing the group's store for the
manager-address handoff (the reference does the same with TCPStore:
torchft/manager.py:277-325; multi-process worker wiring:
torchft/fsdp_test.py:96-120).

Division of labor (this framework's core design):
- intra-group, inter-host: XLA collectives over ICI/DCN via the jit mesh —
  static, compiled, membership never changes mid-job;
- inter-group: the elastic ``ProcessGroupTCP`` ring driven by the Manager —
  reconfigured per quorum, groups join/leave freely.

Testable without TPUs: the CPU backend supports multi-process meshes (Gloo
collectives); see examples/train_multihost.py and
tests/test_multihost_integ.py.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


def initialize_multihost(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    platform: "Optional[str]" = None,
    cpu_devices_per_process: "Optional[int]" = None,
) -> None:
    """Join this process to the replica group's jax runtime.

    Must run before any other jax device use.  ``platform``/
    ``cpu_devices_per_process`` force the CPU backend with N virtual
    devices — the no-TPU test configuration (config.update is required
    here: plugin platforms registered via sitecustomize win over the
    ``JAX_PLATFORMS`` env var).
    """
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    if cpu_devices_per_process is not None:
        try:
            jax.config.update("jax_num_cpu_devices", cpu_devices_per_process)
        except AttributeError:
            # jax < 0.5 has no jax_num_cpu_devices; the XLA flag is the
            # pre-0.5 spelling and must land before backend init (we are
            # before jax.distributed.initialize, so it does)
            import os

            from torchft_tpu.utils.env import env_str

            flags = env_str("XLA_FLAGS")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count="
                    f"{cpu_devices_per_process}"
                ).strip()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def host_sharded_array(
    global_shape: "tuple",
    sharding: Any,
    fill: "Callable[[Any], np.ndarray]",
) -> Any:
    """Build a global array from per-process local shards.

    ``fill(index)`` returns the numpy data for one addressable shard
    (``index`` is the global-slice tuple for that shard).  Thin veneer
    over ``jax.make_array_from_callback`` — named here so trainers read
    as 'each host contributes its slice of the global batch'.
    """
    import jax

    return jax.make_array_from_callback(global_shape, sharding, fill)
