"""Standalone Lighthouse CLI (reference: src/bin/lighthouse.rs:11-24 and the
``lighthouse_main`` entry in src/lib.rs:329-344).

Run one per job; point every replica group's Manager at it:

    python -m torchft_tpu.lighthouse --bind :29510 --min-replicas 2

Serves the quorum RPC protocol and the HTML dashboard (with per-replica
kill buttons and ``/status.json``) on the same port.

Coordination-plane HA: run N peers, each with the SAME full ``--peers``
list (every peer drops its own entry by bind port), and point clients at
the list — ``TORCHFT_LIGHTHOUSE=h1:p,h2:p,h3:p``::

    python -m torchft_tpu.lighthouse --bind :29510 \
        --peers hostA:29510,hostB:29510,hostC:29510

The peers elect a leader by majority lease acknowledgement; followers
answer leader-only RPCs with a ``NOT_LEADER`` redirect every client
follows transparently (docs/architecture.md "Coordination-plane HA").
"""

from __future__ import annotations

import argparse
import signal
import threading

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.ha.endpoints import exclude_self, parse_endpoints


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--bind", default=":29510", help="host:port (port 0 = ephemeral)")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--join-timeout-ms", type=int, default=60000,
                   help="straggler wait before forming a smaller quorum "
                        "(reference CLI default 60s)")
    p.add_argument("--quorum-tick-ms", type=int, default=100)
    p.add_argument("--heartbeat-timeout-ms", type=int, default=5000)
    p.add_argument("--peers", default="",
                   help="coordination-plane HA: the FULL lighthouse peer "
                        "list (host1:p,host2:p,...); this peer's own entry "
                        "is dropped by bind port")
    p.add_argument("--lease-timeout-ms", type=int, default=None,
                   help="leadership lease duration (default "
                        "$TORCHFT_LIGHTHOUSE_LEASE_MS or 1000)")
    args = p.parse_args(argv)

    bind_host, _, bind_port = args.bind.rpartition(":")
    peers = exclude_self(
        parse_endpoints(args.peers),
        int(bind_port or 0),
        # the bind host is one more way this peer can be named in the list
        local_hosts={bind_host} if bind_host else None,
    )
    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        peers=peers,
        lease_timeout_ms=args.lease_timeout_ms,
    )
    ha = f" [HA: {len(peers)} peer(s), follower until elected]" if peers else ""
    print(f"lighthouse serving at {server.address()} "
          f"(dashboard: http://{server.address()}/){ha}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        server.shutdown()


if __name__ == "__main__":
    main()
