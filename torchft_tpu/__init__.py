"""torchft_tpu — TPU-native per-step fault tolerance for replicated JAX training.

A ground-up rebuild of the capabilities of torchft (zhengchenyu/torchft) for
TPU: a C++ coordination core (Lighthouse quorum server + per-replica-group
Manager), a reconfigurable dynamic-membership collective layer over DCN,
live peer-to-peer checkpoint healing of pytree state, and training-loop
adapters (FT-DDP, LocalSGD, DiLoCo) — designed JAX-first: inner parallelism
(FSDP/TP/SP within a slice) is pjit sharding over ICI and stays static; the
elastic replica dimension lives above jit so membership changes never re-jit.

Public API surface mirrors reference torchft/__init__.py:7-34.
"""

__version__ = "0.1.0"
