"""torchft_tpu — TPU-native per-step fault tolerance for replicated JAX training.

A ground-up rebuild of the capabilities of torchft (zhengchenyu/torchft) for
TPU: a C++ coordination core (Lighthouse quorum server + per-replica-group
Manager), a reconfigurable dynamic-membership collective layer over DCN,
live peer-to-peer checkpoint healing of pytree state, and training-loop
adapters (FT-DDP, LocalSGD, DiLoCo) — designed JAX-first: inner parallelism
(FSDP/TP/SP within a slice) is pjit sharding over ICI and stays static; the
elastic replica dimension lives above jit so membership changes never re-jit.

Public API surface mirrors reference torchft/__init__.py:7-34: the Manager,
the Optimizer wrapper, FT-DDP, the elastic data sampler, and the concrete
ProcessGroup backends are importable from the package root.
"""

from torchft_tpu.data import DistributedSampler, StatefulDistributedSampler
from torchft_tpu.ddp import DistributedDataParallel, PureDistributedDataParallel
from torchft_tpu.local_sgd import DiLoCo, LocalSGD
from torchft_tpu.manager import Manager, WorldSizeMode
from torchft_tpu.optim import OptimizerWrapper
from torchft_tpu.parallel.process_group import (
    ErrorSwallowingProcessGroupWrapper,
    ManagedProcessGroup,
    NotParticipatingError,
    ProcessGroup,
    ProcessGroupBabyTCP,
    ProcessGroupDummy,
    ProcessGroupTCP,
)

# Reference name: torchft.Optimizer (torchft/optim.py re-exported at root).
Optimizer = OptimizerWrapper

# Telemetry from env, at import (reference wires its OTEL pipeline at
# import, torchft/__init__.py:20-22 + otel.py:42-86): OTLP logs + metrics
# + traces gated on TORCHFT_USE_OTEL; the Prometheus scrape server gated
# on TORCHFT_METRICS_PORT.
from torchft_tpu.utils.metrics import (
    maybe_export_from_env as _metrics_export_install,
    maybe_serve_from_env as _metrics_serve_install,
)
from torchft_tpu.utils.otel import maybe_install_from_env as _otel_install
from torchft_tpu.utils.tracing import maybe_install_from_env as _traces_install

_otel_install()
_metrics_export_install()
_traces_install()
_metrics_serve_install()
del _otel_install, _metrics_export_install, _traces_install, _metrics_serve_install

__all__ = [
    "DiLoCo",
    "DistributedDataParallel",
    "DistributedSampler",
    "ErrorSwallowingProcessGroupWrapper",
    "LocalSGD",
    "ManagedProcessGroup",
    "Manager",
    "NotParticipatingError",
    "Optimizer",
    "OptimizerWrapper",
    "ProcessGroup",
    "ProcessGroupBabyTCP",
    "ProcessGroupDummy",
    "ProcessGroupTCP",
    "PureDistributedDataParallel",
    "StatefulDistributedSampler",
    "WorldSizeMode",
]

__version__ = "0.1.0"
