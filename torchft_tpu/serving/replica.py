"""ServingReplica: one node of the weight-distribution fan-out tree.

Registers the ``server`` serving role with the lighthouse, adopts the
synthesized plan whenever the plan epoch moves (the PR 10 epoch-commit
idiom: epochs are monotone and name exactly one tree, so adoption is a
local, wedge-free act — a replica mid-switch simply serves the versions
it already holds while it re-parents), pulls new weight versions from
its tree parent (the root pulls from the publisher), and re-stages them
in its own HTTP checkpoint transport for its children and for inference
clients.  A dead parent is routed around: the pull fails over to the
publisher/root source, so a killed interior node degrades depth, never
availability.

The pull is a **cut-through fragment stream** (ISSUE 14, default;
``TORCHFT_SERVING_STREAM=0`` restores the whole-payload
store-and-forward path): the relay fetches the ``frag_manifest`` doc
first, then streams fragments one at a time and restages each the
moment its publisher-computed sha256 verifies — a child at depth *d*
overlaps its pull of fragment *i* with this node's pull of fragment
*i+1*, so publish→leaf propagation scales like T_payload + depth×T_frag
instead of depth×T_payload.  Three properties ride along:

- **delta relay pulls** — holding version *v−1*, only digest-changed
  fragments cross the wire; unchanged ones are copied from the local
  staging slot (steady-state relay bytes scale with the update delta,
  not the model);
- **zero-decode passthrough** — fragments are opaque verified bytes
  (bufpool-backed), re-served verbatim: no ``deserialize``/
  ``reassemble``/re-serialize on the relay hot path
  (``torchft_serving_relay_decode_seconds{mode="stream"}`` is
  manifest-only, ~0);
- **torn-version safety** — a streaming version serves ONLY its staged
  fragments (children 503-poll the rest); whole-document reads 503
  until the stream finishes, and the replica advertises the version
  only after the last fragment verified.  A mid-stream parent death
  resumes from the fragments already staged (digests pin content, so
  mixing sources is safe) — and a digest mismatch is treated as a dead
  source, never staged.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from torchft_tpu.checkpointing import provenance as _prov
from torchft_tpu.checkpointing import serialization as ser
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.ops.codec_pool import merged_seconds
from torchft_tpu.serving import fetcher as _fetcher
from torchft_tpu.serving import payload as _payload
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.bufpool import POOL
from torchft_tpu.utils.env import env_bool, env_float, env_int

logger = logging.getLogger(__name__)

__all__ = ["ServingReplica"]


class ServingReplica:
    """A relay/leaf serving replica.

    Args:
        lighthouse_addr: the lighthouse coordinating the serving tier.
        replica_id: stable id (default ``serve_<uuid8>``); ordering over
            ids determines the synthesized tree position.
        capacity: max children this node accepts (0 = the lighthouse's
            configured fanout).
        max_versions: staged versions retained (default
            ``TORCHFT_SERVING_VERSIONS``).
        poll_interval: heartbeat + version-poll cadence in seconds
            (default ``TORCHFT_SERVING_POLL_S``).
        fetch_timeout: per-pull deadline (default
            ``TORCHFT_SERVING_FETCH_TIMEOUT_S``).
        stream: cut-through fragment streaming (default
            ``TORCHFT_SERVING_STREAM``, on); off = whole-payload
            store-and-forward (the pre-ISSUE-14 path, kept for the
            depth-axis bench comparison).
    """

    def __init__(
        self,
        lighthouse_addr: str,
        replica_id: "Optional[str]" = None,
        capacity: int = 0,
        max_versions: "Optional[int]" = None,
        poll_interval: "Optional[float]" = None,
        fetch_timeout: "Optional[float]" = None,
        stream: "Optional[bool]" = None,
    ) -> None:
        from torchft_tpu.coordination import LighthouseClient

        self._replica_id = replica_id or f"serve_{uuid.uuid4().hex[:8]}"
        self._capacity = int(capacity)
        self._client = LighthouseClient(lighthouse_addr)
        self._transport = HTTPTransport(
            max_staged=(
                max_versions
                if max_versions is not None
                else env_int("TORCHFT_SERVING_VERSIONS", 4, minimum=1)
            ),
        )
        self._poll = (
            poll_interval
            if poll_interval is not None
            else env_float("TORCHFT_SERVING_POLL_S", 0.2, minimum=0.01)
        )
        self._fetch_timeout = (
            fetch_timeout
            if fetch_timeout is not None
            else env_float("TORCHFT_SERVING_FETCH_TIMEOUT_S", 30.0, minimum=0.1)
        )
        # Per-source failover bound: a dead source costs at most this
        # before the pull moves on (the LAST candidate gets the full
        # remaining deadline, so a slow-but-alive fleet still completes).
        self._failover_s = env_float("TORCHFT_SERVING_FAILOVER_S", 2.0,
                                     minimum=0.05)
        self._stream = (
            stream
            if stream is not None
            else env_bool("TORCHFT_SERVING_STREAM", True)
        )
        self._frag_fetcher = _fetcher.FragmentFetcher(role="relay")
        self._lock = threading.Lock()
        self._version = 0
        # Staleness ledger: publish wall-stamp (publisher's clock, ms)
        # of the held version, read from the fetched manifest's
        # created_ns and re-advertised unmodified on every heartbeat —
        # the lighthouse compares stamps from the SAME clock, so
        # per-node staleness in /serving.json is skew-free.
        self._version_ms = 0
        # delta base: manifest of the newest COMPLETELY staged version
        # (digest diff against it decides which fragments need wire)
        self._held_manifest: "Optional[Dict[str, Any]]" = None
        self._plan_epoch = -1
        self._parent = ""       # adopted parent address ("" = unplaced)
        self._root_source = ""  # publisher address (failover of last resort)
        self._peers: "List[str]" = []  # other serving-node addresses
        self._depth = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"tft_serving_{self._replica_id}",
            daemon=True,
        )
        self._thread.start()

    # -- introspection -----------------------------------------------------

    def address(self) -> str:
        """HTTP base address children/clients fetch from."""
        return self._transport.metadata()

    def replica_id(self) -> str:
        return self._replica_id

    def version(self) -> int:
        """Newest weight version staged COMPLETE and servable on this
        node (a mid-stream version is never advertised)."""
        with self._lock:
            return self._version

    def plan_epoch(self) -> int:
        with self._lock:
            return self._plan_epoch

    def depth(self) -> int:
        with self._lock:
            return self._depth

    # -- the serving loop --------------------------------------------------

    def _run(self) -> None:
        # Pacing loop (not a retry loop): one heartbeat + pull check per
        # poll interval; every failure inside is logged and re-attempted
        # on the next beat — a serving replica must outlive any
        # lighthouse restart or parent death.
        while not self._stop.is_set():
            try:
                self._beat_once()
            except Exception as e:  # noqa: BLE001 - keep serving
                logger.warning(
                    "serving replica %s beat failed: %s", self._replica_id, e
                )
            self._stop.wait(self._poll)

    def _beat_once(self) -> None:
        with self._lock:
            held_v, held_ms = self._version, self._version_ms
        # provenance piggyback: consumed-on-send; a failed beat hands
        # the digest back so no vector change is lost (the PR 16 links
        # contract)
        digest = _prov.PROV.maybe_digest(socket.gethostname())
        try:
            reply = self._client.serving_heartbeat(
                self._replica_id,
                self.address(),
                role="server",
                version=held_v,
                capacity=self._capacity,
                version_ms=held_ms,
                fragments=digest,
            )
        except Exception:
            _prov.PROV.restore_digest(digest)
            raise
        if reply["plan_epoch"] != self.plan_epoch():
            self._adopt_plan()
        target = int(reply["latest_version"])
        if target > self.version():
            self._pull(target)

    def _adopt_plan(self) -> None:
        plan = self._client.serving_plan()
        epoch = int(plan["epoch"])
        # Chaos site: a raise here leaves the OLD tree adopted — the
        # replica keeps serving what it holds (degrade, never wedge) and
        # re-tries adoption on the next heartbeat.
        _faults.check(
            "serving.tree_commit", replica=self._replica_id, step=epoch
        )
        # TORCHFT_PLAN_VERIFY: the lighthouse's BFS tree is a synthesized
        # plan — validate it at the commit point before adopting.
        from torchft_tpu.analysis import plan_verify as _pv

        if _pv.enabled():
            from torchft_tpu.analysis import plan_ir as _pir

            _pv.check_live(_pir.serving_ir(plan))
        t0_ns = time.time_ns()
        me = None
        peers: "List[str]" = []
        for node in plan["nodes"]:
            if node["replica_id"] == self._replica_id:
                me = node
            elif node["address"]:
                peers.append(node["address"])
        with self._lock:
            self._plan_epoch = epoch
            self._root_source = plan["root_source"]
            self._peers = peers
            if me is not None:
                self._parent = me["parent"] or plan["root_source"]
                self._depth = int(me["depth"])
        _metrics.SERVING_PLAN_EPOCH.labels(role="server").set(epoch)
        _metrics.SERVING_TREE_DEPTH.set(int(plan["depth"]))
        _flightrec.record(
            "serving.tree_commit", start_ns=t0_ns, step=epoch,
            parent=self._parent, depth=self._depth,
        )
        tracer = _tracing.get_tracer()
        ctx = _tracing.get_current()
        if tracer is not None and ctx is not None and ctx.sampled:
            tracer.export_span(
                name="serving.tree_commit",
                trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id,
                start_ns=t0_ns,
                end_ns=time.time_ns(),
                attributes={"epoch": epoch, "depth": self._depth},
            )

    # -- pull path ---------------------------------------------------------

    def _sources(self) -> "List[str]":
        """Failover order: parent -> root source -> two peers (bounded
        walk: a stale target is cheaper to re-resolve on the next beat
        than to chase across the whole fleet); self deduped out."""
        with self._lock:
            sources = [s for s in (self._parent, self._root_source) if s]
            peers = list(self._peers)
        own = self.address()
        seen = {own}
        ordered: "List[str]" = []
        for s in sources + peers:
            if s not in seen:
                seen.add(s)
                ordered.append(s)
        return ordered[:4]

    def _source_budget(
        self, deadline: float, i: int, total: int
    ) -> float:
        """Split the remaining deadline over the sources left, capping
        every non-final source at the failover bound so a dead parent
        costs seconds, not the whole deadline."""
        remaining = max(deadline - time.monotonic(), 0.1)
        budget = max(remaining / max(total - i, 1), 0.5)
        if i < total - 1:
            budget = min(budget, self._failover_s)
        return min(budget, remaining)

    def _pull(self, target: int) -> None:
        """Pull version ``target``; fail over to the root source, then
        any peer, when the parent is dead, lags, or serves bytes whose
        digest does not verify."""
        _faults.check("serving.fetch", replica=self._replica_id, step=target)
        ordered = self._sources()
        if not ordered:
            return
        t0 = time.perf_counter()
        with _flightrec.track(
            "serving.fetch", step=target, role="relay",
        ) as op:
            if self._stream:
                self._pull_streamed(target, ordered, op)
            else:
                self._pull_flat(target, ordered, op)
        with self._lock:
            if target > self._version:
                self._version = target
                m = self._held_manifest or {}
                self._version_ms = int(m.get("created_ns", 0) // 1_000_000)
            held_ms = self._version_ms
        dt = time.perf_counter() - t0
        _metrics.SERVING_FETCH_SECONDS.labels(role="relay").observe(dt)
        _metrics.SERVING_VERSION.labels(role="server").set(self.version())
        # server-role staleness: publish->this-node availability lag.
        # Publisher clock vs this host's clock — subject to cross-host
        # skew (the skew-free per-node ledger is the lighthouse's, in
        # /serving.json); on a well-synced fleet this IS publish->leaf.
        if held_ms > 0:
            _metrics.SERVING_STALENESS.labels(role="server").observe(
                max(time.time() - held_ms / 1e3, 0.0)
            )

    def _pull_flat(
        self, target: int, ordered: "List[str]", op: Any
    ) -> None:
        """Whole-payload store-and-forward (the pre-streaming path):
        fetch ``full``, decode the stream, restage — children cannot see
        any byte of ``target`` until this node holds all of them."""
        deadline = time.monotonic() + self._fetch_timeout
        last: "Optional[Exception]" = None
        for i, src in enumerate(ordered):
            budget = self._source_budget(deadline, i, len(ordered))
            try:
                # streamed straight off the socket (no raw intermediate
                # copy); the decode interleaves with the reads, exactly
                # what the store-and-forward baseline always paid
                t_dec = time.perf_counter()
                skeleton, leaves, n = _fetcher.fetch_serialized(
                    src, target, "full", timeout=budget, role="relay"
                )
                doc = ser.reassemble(skeleton, leaves, n)
                _metrics.SERVING_RELAY_DECODE.labels(
                    mode="flat"
                ).observe(time.perf_counter() - t_dec)
                break
            except Exception as e:  # noqa: BLE001 - failover path
                last = e
                if i < len(ordered) - 1:
                    # count only pulls that actually MOVE to another
                    # source; a terminal failure is not a failover
                    _metrics.SERVING_FAILOVERS.labels(role="relay").inc()
                logger.warning(
                    "serving relay %s: pull v%d from %s failed (%s); "
                    "failing over",
                    self._replica_id, target, src, e,
                )
        else:
            op.update(status="error")
            raise ConnectionError(
                f"serving relay {self._replica_id}: no source served "
                f"v{target} within {self._fetch_timeout}s"
            ) from last
        self._transport.send_checkpoint(
            [], target, doc, timeout=self._fetch_timeout
        )
        manifest = doc.get(f"frag:{_payload.MANIFEST_FRAG}") or {}
        m_ms = int(manifest.get("created_ns", 0) // 1_000_000)
        m_digests = manifest.get("digests") or {}
        for name in manifest.get("fragments") or ():
            fid = _prov.frag_id("weights", name)
            raw = _payload.fragment_wire(doc.get(f"frag:{name}"))
            _prov.note_hop(
                fid, target, src, "serving", verdict="ok",
                nbytes=raw.nbytes if raw is not None else 0,
            )
            _prov.note_hold(
                fid, target, m_digests.get(name, ""),
                version_ms=m_ms, role="relay",
            )
        with self._lock:
            self._held_manifest = doc.get(f"frag:{_payload.MANIFEST_FRAG}")

    def _begin_staging(
        self, target: int, manifest: "Dict[str, Any]"
    ) -> "Tuple[List[str], int]":
        """Open (or RESUME) the streamed staging slot for ``target``;
        reuse unchanged fragments from the held version's local staging
        (the delta relay pull — zero wire for fragments whose digest
        did not move).  Returns ``(names still needing wire, reused)``.
        """
        names = list(manifest["fragments"])
        with self._lock:
            held_v, held_m = self._version, self._held_manifest
        existing = self._transport.streamed_parts(target)
        if existing is None:
            self._transport.begin_streamed_checkpoint(
                target,
                {f"frag:{_payload.MANIFEST_FRAG}": manifest},
                timeout=self._fetch_timeout,
            )
            existing = {f"frag:{_payload.MANIFEST_FRAG}"}
        changed = set(_payload.changed_fragments(manifest, held_m))
        todo: "List[str]" = []
        reused = 0
        for name in names:
            key = f"frag:{name}"
            if key in existing:
                continue  # staged by an earlier interrupted pull
            if name not in changed:
                buf = self._transport.copy_staged_part(held_v, key)
                if buf is not None:
                    self._transport.stage_streamed_part(
                        target, key, buf, pooled=True
                    )
                    reused += 1
                    continue
                # held version fell out of the staging window: pay wire
            todo.append(name)
        return todo, reused

    def _pull_streamed(
        self, target: int, ordered: "List[str]", op: Any
    ) -> None:
        deadline = time.monotonic() + self._fetch_timeout
        manifest: "Optional[Dict[str, Any]]" = None
        todo: "List[str]" = []
        reused = 0
        total = 0
        wire_spans: "List[Tuple[float, float]]" = []
        proc_busy = 0.0
        t_stream0 = time.perf_counter()
        last: "Optional[Exception]" = None
        for i, src in enumerate(ordered):
            budget = self._source_budget(deadline, i, len(ordered))
            src_deadline = time.monotonic() + budget
            try:
                if manifest is None:
                    mbuf = self._frag_fetcher.fetch_raw(
                        src, target, f"frag_{_payload.MANIFEST_FRAG}",
                        timeout=budget,
                    )
                    try:
                        t_dec = time.perf_counter()
                        manifest = _payload.decode_manifest(mbuf)
                        _metrics.SERVING_RELAY_DECODE.labels(
                            mode="stream"
                        ).observe(time.perf_counter() - t_dec)
                    finally:
                        POOL.give(mbuf)
                    if int(manifest["version"]) != target:
                        v_got = manifest["version"]
                        manifest = None
                        raise ConnectionError(
                            f"wanted v{target}, {src} served v{v_got}"
                        )
                    todo, reused = self._begin_staging(target, manifest)
                    total = len(manifest["fragments"])
                    t_stream0 = time.perf_counter()
                # Cut-through: stage each fragment the moment its digest
                # verifies — children polling frag_<name> get it while
                # this node is still pulling the next one.  Fragments
                # already staged (earlier source died mid-stream) are
                # skipped; digests pin content, so resuming from another
                # source is bitwise-safe.
                parts = self._transport.streamed_parts(target) or set()
                pend = [
                    f"frag_{n}" for n in todo if f"frag:{n}" not in parts
                ]
                for res, buf, span in self._frag_fetcher.fetch_stream(
                    src, target, pend, deadline=src_deadline
                ):
                    name = res[len("frag_"):]
                    wire_spans.append(span)
                    t_proc = time.perf_counter()
                    fid = _prov.frag_id("weights", name)
                    try:
                        try:
                            _payload.verify_fragment(name, buf, manifest)
                        except ValueError:
                            # provenance: THIS hop is where the poison
                            # entered — diagnose --fragment names it
                            _prov.note_hop(
                                fid, target, src, "serving",
                                verdict="mismatch", nbytes=buf.nbytes,
                            )
                            raise
                        _prov.note_hop(
                            fid, target, src, "serving",
                            verdict="ok", nbytes=buf.nbytes,
                        )
                        self._transport.stage_streamed_part(
                            target, f"frag:{name}", buf, pooled=True
                        )
                        _prov.note_hold(
                            fid, target,
                            (manifest.get("digests") or {}).get(name, ""),
                            version_ms=int(
                                manifest.get("created_ns", 0) // 1_000_000
                            ),
                            role="relay",
                        )
                    except BaseException:
                        # poisoned or unstageable bytes never serve
                        POOL.give(buf)
                        raise
                    proc_busy += time.perf_counter() - t_proc
                break
            except Exception as e:  # noqa: BLE001 - failover path
                last = e
                if i < len(ordered) - 1:
                    _metrics.SERVING_FAILOVERS.labels(role="relay").inc()
                logger.warning(
                    "serving relay %s: streamed pull v%d from %s failed "
                    "(%s); failing over",
                    self._replica_id, target, src, e,
                )
        else:
            # terminal: keep the partial slot — the next beat RESUMES
            # from the staged fragments (or the window evicts it when
            # the fleet moves on)
            op.update(status="error")
            raise ConnectionError(
                f"serving relay {self._replica_id}: no source served "
                f"v{target} within {self._fetch_timeout}s"
            ) from last
        self._transport.finish_streamed_checkpoint(target)
        with self._lock:
            self._held_manifest = manifest
        wall = time.perf_counter() - t_stream0
        # union of fetch intervals, NOT a sum: K-parallel in-flight
        # fetches would otherwise exceed wall on their own and pin the
        # gauge at 1.0 regardless of actual overlap
        wire_busy = merged_seconds(wire_spans)
        if wire_busy > 0.0 and proc_busy > 0.0 and wall > 0.0:
            occ = (wire_busy + proc_busy - wall) / min(wire_busy, proc_busy)
            _metrics.SERVING_CUT_OCCUPANCY.set(min(max(occ, 0.0), 1.0))
        op.update(
            fragments=total, reused=reused,
            wire_s=round(wire_busy, 4),
        )

    # -- lifecycle ---------------------------------------------------------

    def retire_below(self, version: int) -> None:
        """Drop staged versions older than ``version`` (the bounded
        staging window does this on its own; explicit for tests)."""
        for v in self._transport.staged_steps():
            if v < version:
                self._transport.retire_checkpoint(v)

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._frag_fetcher.close()
        self._client.close()
        self._transport.shutdown()
