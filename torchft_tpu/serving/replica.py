"""ServingReplica: one node of the weight-distribution fan-out tree.

Registers the ``server`` serving role with the lighthouse, adopts the
synthesized plan whenever the plan epoch moves (the PR 10 epoch-commit
idiom: epochs are monotone and name exactly one tree, so adoption is a
local, wedge-free act — a replica mid-switch simply serves the versions
it already holds while it re-parents), pulls new weight versions from
its tree parent (the root pulls from the publisher), and re-stages them
in its own HTTP checkpoint transport for its children and for inference
clients.  A dead parent is routed around: the pull fails over to the
publisher/root source, so a killed interior node degrades depth, never
availability.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.serving import wire as _wire
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.env import env_float, env_int

logger = logging.getLogger(__name__)

__all__ = ["ServingReplica"]


class ServingReplica:
    """A relay/leaf serving replica.

    Args:
        lighthouse_addr: the lighthouse coordinating the serving tier.
        replica_id: stable id (default ``serve_<uuid8>``); ordering over
            ids determines the synthesized tree position.
        capacity: max children this node accepts (0 = the lighthouse's
            configured fanout).
        max_versions: staged versions retained (default
            ``TORCHFT_SERVING_VERSIONS``).
        poll_interval: heartbeat + version-poll cadence in seconds
            (default ``TORCHFT_SERVING_POLL_S``).
        fetch_timeout: per-pull deadline (default
            ``TORCHFT_SERVING_FETCH_TIMEOUT_S``).
    """

    def __init__(
        self,
        lighthouse_addr: str,
        replica_id: "Optional[str]" = None,
        capacity: int = 0,
        max_versions: "Optional[int]" = None,
        poll_interval: "Optional[float]" = None,
        fetch_timeout: "Optional[float]" = None,
    ) -> None:
        from torchft_tpu.coordination import LighthouseClient

        self._replica_id = replica_id or f"serve_{uuid.uuid4().hex[:8]}"
        self._capacity = int(capacity)
        self._client = LighthouseClient(lighthouse_addr)
        self._transport = HTTPTransport(
            max_staged=(
                max_versions
                if max_versions is not None
                else env_int("TORCHFT_SERVING_VERSIONS", 4, minimum=1)
            ),
        )
        self._poll = (
            poll_interval
            if poll_interval is not None
            else env_float("TORCHFT_SERVING_POLL_S", 0.2, minimum=0.01)
        )
        self._fetch_timeout = (
            fetch_timeout
            if fetch_timeout is not None
            else env_float("TORCHFT_SERVING_FETCH_TIMEOUT_S", 30.0, minimum=0.1)
        )
        # Per-source failover bound: a dead source costs at most this
        # before the pull moves on (the LAST candidate gets the full
        # remaining deadline, so a slow-but-alive fleet still completes).
        self._failover_s = env_float("TORCHFT_SERVING_FAILOVER_S", 2.0,
                                     minimum=0.05)
        self._lock = threading.Lock()
        self._version = 0
        self._plan_epoch = -1
        self._parent = ""       # adopted parent address ("" = unplaced)
        self._root_source = ""  # publisher address (failover of last resort)
        self._peers: "List[str]" = []  # other serving-node addresses
        self._depth = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"tft_serving_{self._replica_id}",
            daemon=True,
        )
        self._thread.start()

    # -- introspection -----------------------------------------------------

    def address(self) -> str:
        """HTTP base address children/clients fetch from."""
        return self._transport.metadata()

    def replica_id(self) -> str:
        return self._replica_id

    def version(self) -> int:
        """Newest weight version staged and servable on this node."""
        with self._lock:
            return self._version

    def plan_epoch(self) -> int:
        with self._lock:
            return self._plan_epoch

    def depth(self) -> int:
        with self._lock:
            return self._depth

    # -- the serving loop --------------------------------------------------

    def _run(self) -> None:
        # Pacing loop (not a retry loop): one heartbeat + pull check per
        # poll interval; every failure inside is logged and re-attempted
        # on the next beat — a serving replica must outlive any
        # lighthouse restart or parent death.
        while not self._stop.is_set():
            try:
                self._beat_once()
            except Exception as e:  # noqa: BLE001 - keep serving
                logger.warning(
                    "serving replica %s beat failed: %s", self._replica_id, e
                )
            self._stop.wait(self._poll)

    def _beat_once(self) -> None:
        reply = self._client.serving_heartbeat(
            self._replica_id,
            self.address(),
            role="server",
            version=self.version(),
            capacity=self._capacity,
        )
        if reply["plan_epoch"] != self.plan_epoch():
            self._adopt_plan()
        target = int(reply["latest_version"])
        if target > self.version():
            self._pull(target)

    def _adopt_plan(self) -> None:
        plan = self._client.serving_plan()
        epoch = int(plan["epoch"])
        # Chaos site: a raise here leaves the OLD tree adopted — the
        # replica keeps serving what it holds (degrade, never wedge) and
        # re-tries adoption on the next heartbeat.
        _faults.check(
            "serving.tree_commit", replica=self._replica_id, step=epoch
        )
        t0_ns = time.time_ns()
        me = None
        peers: "List[str]" = []
        for node in plan["nodes"]:
            if node["replica_id"] == self._replica_id:
                me = node
            elif node["address"]:
                peers.append(node["address"])
        with self._lock:
            self._plan_epoch = epoch
            self._root_source = plan["root_source"]
            self._peers = peers
            if me is not None:
                self._parent = me["parent"] or plan["root_source"]
                self._depth = int(me["depth"])
        _metrics.SERVING_PLAN_EPOCH.labels(role="server").set(epoch)
        _metrics.SERVING_TREE_DEPTH.set(int(plan["depth"]))
        _flightrec.record(
            "serving.tree_commit", start_ns=t0_ns, step=epoch,
            parent=self._parent, depth=self._depth,
        )
        tracer = _tracing.get_tracer()
        ctx = _tracing.get_current()
        if tracer is not None and ctx is not None and ctx.sampled:
            tracer.export_span(
                name="serving.tree_commit",
                trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id,
                start_ns=t0_ns,
                end_ns=time.time_ns(),
                attributes={"epoch": epoch, "depth": self._depth},
            )

    def _pull(self, target: int) -> None:
        """Pull version ``target`` from the parent; fail over to the
        root source, then any peer, when the parent is dead or lags."""
        _faults.check("serving.fetch", replica=self._replica_id, step=target)
        with self._lock:
            sources = [s for s in (self._parent, self._root_source) if s]
            peers = list(self._peers)
        own = self.address()
        # dedupe, drop self, keep order: parent -> root source -> two
        # peers (bounded walk: a stale target is cheaper to re-resolve
        # on the next beat than to chase across the whole fleet)
        seen = {own}
        ordered: "List[str]" = []
        for s in sources + peers:
            if s not in seen:
                seen.add(s)
                ordered.append(s)
        ordered = ordered[:4]
        if not ordered:
            return
        t0 = time.perf_counter()
        with _flightrec.track(
            "serving.fetch", step=target, role="relay",
        ) as op:
            last: "Optional[Exception]" = None
            deadline = time.monotonic() + self._fetch_timeout
            for i, src in enumerate(ordered):
                # Per-source budget: split the remaining deadline, but
                # cap every non-final source at the failover bound so a
                # dead parent costs seconds, not the whole deadline.
                remaining = max(deadline - time.monotonic(), 0.1)
                budget = max(remaining / max(len(ordered) - i, 1), 0.5)
                if i < len(ordered) - 1:
                    budget = min(budget, self._failover_s)
                try:
                    doc = self._transport.recv_checkpoint(
                        0, src, step=target, timeout=budget
                    )
                    # WAN wire model (serving/wire.py): the relay pull
                    # pays one RTT + payload/rate when the parent/peer
                    # sits across the topology boundary
                    _wire.get_shaper().charge(
                        src, _wire.payload_nbytes(doc)
                    )
                    break
                except Exception as e:  # noqa: BLE001 - failover path
                    last = e
                    if i < len(ordered) - 1:
                        # count only pulls that actually MOVE to another
                        # source; a terminal failure is not a failover
                        _metrics.SERVING_FAILOVERS.labels(role="relay").inc()
                    logger.warning(
                        "serving relay %s: pull v%d from %s failed (%s); "
                        "failing over",
                        self._replica_id, target, src, e,
                    )
            else:
                op.update(status="error")
                raise ConnectionError(
                    f"serving relay {self._replica_id}: no source served "
                    f"v{target} within {self._fetch_timeout}s"
                ) from last
            self._transport.send_checkpoint(
                [], target, doc, timeout=self._fetch_timeout
            )
        with self._lock:
            if target > self._version:
                self._version = target
        dt = time.perf_counter() - t0
        _metrics.SERVING_FETCH_SECONDS.labels(role="relay").observe(dt)
        _metrics.SERVING_VERSION.labels(role="server").set(self.version())

    # -- lifecycle ---------------------------------------------------------

    def retire_below(self, version: int) -> None:
        """Drop staged versions older than ``version`` (the bounded
        staging window does this on its own; explicit for tests)."""
        for v in self._transport.staged_steps():
            if v < version:
                self._transport.retire_checkpoint(v)

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._client.close()
        self._transport.shutdown()
