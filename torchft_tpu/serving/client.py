"""ServingClient: the inference-side weight fetch path.

Discovers the distribution tree through the lighthouse (cached plan,
refreshed on epoch change or failure), fetches versioned payloads from
serving replicas — leaves first, so client load lands on the tree's
widest tier — and fails over to siblings/the root source when a server
dies mid-fetch.  Holding the previous version enables delta fetches:
manifest + changed fragments only (publisher-computed digests decide).

The fetch rides the shared fragment-fetch plane (``serving/fetcher.py``,
ISSUE 14): persistent HTTP connections against the checkpoint
transport's ``/checkpoint/<version>/<resource>`` surface, the unified
retry layer polling retryable 503s (version staged but not yet on this
node) inside each source's budget slice, and — on the delta path — a
bounded-parallel pipeline that overlaps digest verify + decode of
fragment *i* with the wire of fragment *i+1*.
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from torchft_tpu.checkpointing import provenance as _prov
from torchft_tpu.checkpointing import serialization as ser
from torchft_tpu.serving import fetcher as _fetcher
from torchft_tpu.serving import payload as _payload
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.bufpool import POOL
from torchft_tpu.utils.env import env_float
from torchft_tpu.utils.retry import RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["ServingClient", "fetch_resource"]


class _NoServableNodes(RuntimeError):
    """The current plan names zero servable nodes — transient right
    after a coordination-plane failover (lighthouse serving state is
    soft; a fresh leader serves an EMPTY plan until the serving fleet's
    next heartbeats re-register it)."""


# Empty-plan poll: re-ask the lighthouse until nodes re-register or the
# caller's deadline expires.  Connection errors ride too (the plan RPC
# itself may be walking a mid-election endpoint list).
_PLAN_POLICY = RetryPolicy(
    name="serving.plan",
    base_delay=0.05,
    multiplier=1.5,
    max_delay=0.5,
    retry_if=lambda e: isinstance(
        e, (_NoServableNodes, ConnectionError, OSError, TimeoutError)
    ),
)


def fetch_resource(
    base: str, version: int, resource: str, timeout: float
) -> Any:
    """Fetch + deserialize one resource of a staged version from a
    serving node's transport (``full``, ``frag_<name>``, ...) — decoded
    straight off the socket (a multi-GB ``full`` document lands in its
    final buffers, never a raw intermediate copy)."""
    skeleton, leaves, n = _fetcher.fetch_serialized(
        base, version, resource, timeout, role="client"
    )
    return ser.reassemble(skeleton, leaves, n)


class ServingClient:
    """Pull live weight versions from the serving tier.

    Args:
        lighthouse_addr: serving-tier discovery endpoint.
        plan_ttl: seconds a fetched plan is trusted before re-asking the
            lighthouse (default ``TORCHFT_SERVING_PLAN_TTL_S``); any
            fetch failure refreshes immediately.
        client_id: spreads initial source choice across clients (leaves
            are rotated by its hash) so a client fleet does not dogpile
            one leaf.
        pin_version: serve EXACTLY this weight version: every
            ``fetch()`` without an explicit ``version`` targets it, and
            its eviction from the staging window is an error (the 503
            poll exhausts the deadline), never a silent substitution.
        min_version: rollback floor — a fetch that would RESOLVE OR
            RETURN a version below this raises instead (e.g. a restarted
            publisher re-advertising an older checkpoint must not roll
            an inference fleet back).  The floor also ratchets up to
            every version successfully fetched, so "never serve older
            than what I already serve" needs no bookkeeping by the
            caller.
    """

    def __init__(
        self,
        lighthouse_addr: str,
        plan_ttl: "Optional[float]" = None,
        client_id: "Optional[str]" = None,
        pin_version: "Optional[int]" = None,
        min_version: int = 0,
    ) -> None:
        from torchft_tpu.coordination import LighthouseClient

        self._client = LighthouseClient(lighthouse_addr)
        self._plan_ttl = (
            plan_ttl
            if plan_ttl is not None
            else env_float("TORCHFT_SERVING_PLAN_TTL_S", 2.0, minimum=0.0)
        )
        # Stable rotation seed: hash() varies per process under
        # PYTHONHASHSEED, which would land a RESTARTED client on a
        # different leaf — a sha256 digest keeps the spread deterministic
        # (tests pin it; anonymous clients still spread by identity).
        self._rot = (
            int.from_bytes(
                hashlib.sha256(str(client_id).encode()).digest()[:8], "big"
            )
            if client_id is not None
            else id(self)
        )
        self._frag_fetcher = _fetcher.FragmentFetcher(role="client")
        # non-final sources are capped at the failover bound (a killed
        # server costs seconds, not the fetch deadline)
        self._failover_s = env_float(
            "TORCHFT_SERVING_FAILOVER_S", 2.0, minimum=0.05
        )
        self._plan: "Optional[Dict[str, Any]]" = None
        self._plan_at = 0.0
        # previous decoded version for delta fetches
        self._held: "Optional[Tuple[Dict[str, Any], Dict[int, Any]]]" = None
        self._held_version = 0
        # version pinning / rollback floor (coordination with rolling
        # deploys: a pinned canary, a fleet that must never regress)
        self._pin_version = (
            int(pin_version) if pin_version is not None else None
        )
        if self._pin_version is not None and self._pin_version <= 0:
            raise ValueError("pin_version must be a positive version")
        self._min_version = int(min_version)
        if (
            self._pin_version is not None
            and self._pin_version < self._min_version
        ):
            raise ValueError(
                f"pin_version={self._pin_version} is below "
                f"min_version={self._min_version}"
            )

    # -- discovery ---------------------------------------------------------

    def plan(self, refresh: bool = False) -> "Dict[str, Any]":
        now = time.monotonic()
        if (
            refresh
            or self._plan is None
            or now - self._plan_at > self._plan_ttl
        ):
            self._plan = self._client.serving_plan()
            self._plan_at = now
            _metrics.SERVING_PLAN_EPOCH.labels(role="client").set(
                self._plan["epoch"]
            )
        return self._plan

    def latest_version(self, refresh: bool = True) -> int:
        return int(self.plan(refresh=refresh)["latest_version"])

    def _sources(self, plan: "Dict[str, Any]") -> "List[str]":
        """Fetch order: leaves (deepest first, rotated per client for
        load spread), then interior nodes, then the root source — a
        client can always complete as long as ANY copy is alive."""
        nodes = list(plan["nodes"])
        leaves = [n for n in nodes if n["children"] == 0]
        inner = [n for n in nodes if n["children"] > 0]
        leaves.sort(key=lambda n: (-n["depth"], n["replica_id"]))
        inner.sort(key=lambda n: (-n["depth"], n["replica_id"]))
        if leaves:
            r = self._rot % len(leaves)
            leaves = leaves[r:] + leaves[:r]
        ordered = [n["address"] for n in leaves + inner if n["address"]]
        if plan["root_source"]:
            ordered.append(plan["root_source"])
        return ordered

    # -- fetch -------------------------------------------------------------

    def fetch(
        self,
        version: "Optional[int]" = None,
        timeout: float = 30.0,
        delta: bool = True,
    ) -> "Tuple[Any, int]":
        """Fetch weight ``version`` (default: the fleet's latest);
        returns ``(state_dict, version)``.

        With ``delta`` and a previously fetched version held, only the
        manifest plus changed fragments cross the wire.  Sources are
        tried leaves-first within the deadline; a source failure (killed
        server, staging lag past its budget slice) fails over to the
        next and counts in ``torchft_serving_failovers_total``.

        A client constructed with ``pin_version=`` targets that version
        whenever ``version`` is omitted; one constructed with
        ``min_version=`` (or that has fetched before — the floor
        ratchets) refuses any resolution below the floor with a
        ``RuntimeError`` instead of rolling back."""
        deadline = time.monotonic() + timeout
        plan = self.plan()
        if version is None and self._pin_version is not None:
            version = self._pin_version
        pinned = version is not None
        v = int(version) if pinned else int(plan["latest_version"])
        if v <= 0:
            raise RuntimeError("serving tier has no published version yet")
        if v < self._min_version:
            raise RuntimeError(
                f"serving fetch refused: version {v} is below the "
                f"client's rollback floor (min_version="
                f"{self._min_version}) — the tier would roll this "
                f"client back to an older checkpoint"
            )
        _faults.check("serving.fetch", step=v)
        t0 = time.perf_counter()
        t0_ns = time.time_ns()
        with _flightrec.track("serving.fetch", step=v, role="client") as op:
            state, v, failovers = self._fetch_any(
                v, plan, deadline, delta, pinned
            )
            op.update(failovers=failovers, version=v)
        # ratchet: this client never serves older than what it has served
        self._min_version = max(self._min_version, v)
        dt = time.perf_counter() - t0
        _metrics.SERVING_FETCH_SECONDS.labels(role="client").observe(dt)
        # client-role staleness: publish->in-hand lag, from the fetched
        # manifest's publish stamp (publisher clock vs this host's —
        # subject to cross-host skew; the skew-free ledger is the
        # lighthouse's /serving.json staleness_ms rows).
        held = self._held
        if held is not None:
            v_ms = int(held[0].get("created_ns", 0) // 1_000_000)
            if v_ms > 0:
                _metrics.SERVING_STALENESS.labels(role="client").observe(
                    max(time.time() - v_ms / 1e3, 0.0)
                )
        tracer = _tracing.get_tracer()
        ctx = _tracing.get_current()
        if tracer is not None and ctx is not None and ctx.sampled:
            tracer.export_span(
                name="serving.fetch",
                trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id,
                start_ns=t0_ns,
                end_ns=time.time_ns(),
                attributes={"version": v, "failovers": failovers},
            )
        return state, v

    def _fetch_any(
        self,
        v: int,
        plan: "Dict[str, Any]",
        deadline: float,
        delta: bool,
        pinned: bool,
    ) -> "Tuple[Any, int, int]":
        """Try sources in failover order; returns ``(state, version,
        failovers)``.  An UNPINNED fetch (caller asked for "latest")
        re-resolves the target version on every failover: under a fast
        publish cadence the originally-latest version can be evicted
        from every staging window before a slow start completes, and a
        newer version satisfies the caller strictly better."""
        sources = self._sources(plan)
        if not sources:
            # transient after a lighthouse failover (soft serving state):
            # poll the plan inside the caller's deadline rather than
            # failing the fetch while the fleet re-registers
            def attempt(_budget: "Optional[float]") -> "Tuple[Any, Any]":
                p = self.plan(refresh=True)
                s = self._sources(p)
                if not s:
                    raise _NoServableNodes(
                        "serving plan has no servable nodes"
                    )
                return p, s

            plan, sources = _PLAN_POLICY.run(
                attempt,
                timeout=max(deadline - time.monotonic(), 0.001),
                op="serving.plan",
            )
        failovers = 0
        last: "Optional[Exception]" = None
        i = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            budget = max(remaining / max(len(sources) - i, 1), 0.2)
            # Every non-final slice is capped so a dead source costs
            # seconds.  An UNPINNED fetch caps the final slice too: if
            # the target was evicted fleet-wide (publish cadence outran
            # this fetch), burning the whole deadline polling 503s on
            # one source would be pure loss — re-resolve and go again.
            if i < len(sources) - 1 or not pinned:
                budget = min(budget, self._failover_s, remaining)
            try:
                state = self._fetch_from(sources[i], v, budget, delta)
                if failovers:
                    _metrics.SERVING_FAILOVERS.labels(role="client").inc(
                        failovers
                    )
                return state, v, failovers
            except Exception as e:  # noqa: BLE001 - failover path
                last = e
                failovers += 1
                logger.warning(
                    "serving fetch v%d from %s failed (%s); failing over",
                    v, sources[i], e,
                )
                # mid-fetch plan refresh: the tree may have re-formed
                # around a dead node, and an unpinned target re-resolves
                # to the CURRENT latest version
                restart = False
                try:
                    plan = self.plan(refresh=True)
                    if not pinned and int(plan["latest_version"]) > v:
                        v = int(plan["latest_version"])
                        restart = True  # newer version: walk from the top
                    sources = self._sources(plan) or sources
                except Exception:  # noqa: BLE001 - keep old list
                    pass
                i = 0 if restart else i + 1
                if i >= len(sources):
                    if pinned:
                        break
                    i = 0  # unpinned: keep cycling until the deadline
        # The LAST failed attempt never moved to another source — it is
        # the terminal failure, not a failover (on the success path every
        # earlier failure did move, so the count there is already right).
        failovers = max(failovers - 1, 0)
        if failovers:
            _metrics.SERVING_FAILOVERS.labels(role="client").inc(failovers)
        raise TimeoutError(
            f"serving fetch v{v}: no source completed within deadline "
            f"({failovers} failover(s))"
        ) from last

    def _fetch_from(
        self, base: str, v: int, budget: float, delta: bool
    ) -> Any:
        t_end = time.monotonic() + budget
        if delta and self._held is not None and self._held_version > 0:
            # Delta path, pipelined (ISSUE 14): manifest first, then the
            # digest-changed fragments through the bounded-parallel
            # fetcher — raw bytes verified against the publisher's
            # sha256, decode of fragment i overlapping the wire of
            # fragment i+1, all on persistent connections.  The timeout
            # clamp matters: an exhausted budget must hand the retry
            # layer a zero-ish deadline, never a negative one.
            mbuf = self._frag_fetcher.fetch_raw(
                base, v, f"frag_{_payload.MANIFEST_FRAG}",
                timeout=max(t_end - time.monotonic(), 0.001),
            )
            try:
                manifest = _payload.decode_manifest(mbuf)
            finally:
                POOL.give(mbuf)
            names = _payload.changed_fragments(manifest, self._held[0])
            leaves: "Dict[int, Any]" = dict(self._held[1])
            for res, buf, _span in self._frag_fetcher.fetch_stream(
                base, v, [f"frag_{n}" for n in names], deadline=t_end
            ):
                name = res[len("frag_"):]
                fid = _prov.frag_id("weights", name)
                try:
                    try:
                        _payload.verify_fragment(name, buf, manifest)
                    except ValueError:
                        _prov.note_hop(
                            fid, v, base, "serving",
                            verdict="mismatch", nbytes=buf.nbytes,
                        )
                        raise
                    _prov.note_hop(
                        fid, v, base, "serving",
                        verdict="ok", nbytes=buf.nbytes,
                    )
                    leaves.update(_payload.decode_fragment(buf))
                finally:
                    POOL.give(buf)
            state = _payload.assemble(manifest, leaves)
        else:
            doc = fetch_resource(base, v, "full", timeout=budget)
            state, manifest, leaves = _payload.decode_payload(doc)
        if int(manifest["version"]) != v:
            raise RuntimeError(
                f"serving fetch: wanted v{v}, source {base} served "
                f"v{manifest['version']}"
            )
        # provenance: the client now holds every fragment of v (fetched
        # and delta-reused alike)
        c_ms = int(manifest.get("created_ns", 0) // 1_000_000)
        c_digests = manifest.get("digests") or {}
        for name in manifest.get("fragments") or ():
            _prov.note_hold(
                _prov.frag_id("weights", name), v,
                c_digests.get(name, ""), version_ms=c_ms, role="client",
            )
        self._held = (manifest, leaves)
        self._held_version = v
        return state

    def close(self) -> None:
        self._frag_fetcher.close()
        self._client.close()
