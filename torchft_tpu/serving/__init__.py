"""Weight-serving tier: fault-tolerant fan-out checkpoint distribution.

The heavy-traffic serving plane (ROADMAP item 5, docs/architecture.md
"Weight-serving tier"): a :class:`WeightPublisher` next to training
publishes committed weights as versioned, optionally int8-quantized
payloads; :class:`ServingReplica` nodes form a lighthouse-synthesized
fan-out tree (root pulls the publisher, interior nodes relay, leaves
serve); :class:`ServingClient` inference clients fetch full or delta
(changed-fragment) payloads with automatic failover when a server dies
mid-fetch.  Discovery, health and tree synthesis ride the existing
lighthouse (``serving_heartbeat`` / ``serving_plan`` RPCs,
``/serving.json``); the wire path is the existing HTTP checkpoint
transport's version-keyed multi-slot staging.

The data path is fragment-streamed (ISSUE 14): relays CUT THROUGH —
restaging each digest-verified fragment the moment it arrives, pulling
only digest-changed fragments when they hold the previous version, and
never decoding payload bytes (``serving/fetcher.py`` +
``serving/payload.py``; docs/architecture.md "Streaming relay").
"""

from torchft_tpu.serving.client import ServingClient, fetch_resource
from torchft_tpu.serving.fetcher import FragmentFetcher
from torchft_tpu.serving.payload import (
    MANIFEST_FRAG,
    WIRE_F32,
    WIRE_INT8,
    changed_fragments,
    decode_manifest,
    decode_payload,
    encode_payload,
    verify_fragment,
)
from torchft_tpu.serving.publisher import WeightPublisher
from torchft_tpu.serving.replica import ServingReplica

__all__ = [
    "WeightPublisher",
    "ServingReplica",
    "ServingClient",
    "FragmentFetcher",
    "fetch_resource",
    "encode_payload",
    "decode_payload",
    "decode_manifest",
    "changed_fragments",
    "verify_fragment",
    "MANIFEST_FRAG",
    "WIRE_F32",
    "WIRE_INT8",
]
