"""Serving-tier WAN wire model (ROADMAP "serving-tier WAN realism").

The training-side shaper (parallel/process_group.py) models the WAN with
two decoupled legs — ``TORCHFT_WIRE_RTT_MS``, a per-MESSAGE first-byte
latency, and ``TORCHFT_WIRE_GBPS``, a shared egress token bucket — both
scoped to messages that cross the ``TORCHFT_TOPOLOGY`` boundary.  This
module applies the SAME model to the serving tier's fetch/relay HTTP
pulls, so serving benches and soaks price multi-region distribution
realistically instead of at loopback speed.

Boundary rule: the serving tier has no rank grid, so the topology
boundary is tested by HOST — with a declared (non-flat)
``TORCHFT_TOPOLOGY``, a fetch whose source host is this machine rides
the local fabric unshaped; with a flat/unset topology EVERY fetch
crosses the boundary (the multi-region premise, and the same default
the PG shaper uses for flat topologies).  A fetch pays one RTT plus
``bytes/rate`` of bucket debt, never more: pacing below one message
would only measure sleep granularity.

Shaping is charged as explicit sleeps on the fetching side after the
response arrives — from the caller's point of view latency and
throughput bound exactly as a shaped link would, without touching the
HTTP stack.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterable, Optional, Tuple
from urllib.parse import urlparse

from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.env import env_float, env_str
from torchft_tpu.utils.hostident import local_host_identities

__all__ = ["WireShaper", "get_shaper", "payload_nbytes", "source_host"]


def source_host(source: str) -> str:
    """The host of a serving source address: a transport base URL
    (``http://host:port``) or a bare ``host:port``."""
    if "://" in source:
        return urlparse(source).hostname or ""
    host, _, _port = source.rpartition(":")
    return host or "127.0.0.1"


def payload_nbytes(doc: Any) -> int:
    """Approximate wire size of a fetched payload/checkpoint document:
    the sum of its array/bytes leaves (metadata is noise at any size the
    shaper matters for)."""
    total = 0
    stack = [doc]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
        elif isinstance(node, (bytes, bytearray)):
            total += len(node)
        else:
            nb = getattr(node, "nbytes", None)
            if isinstance(nb, int):
                total += nb
    return total


class WireShaper:
    """One shaped serving link: per-message RTT + shared token bucket.

    The bucket is shared by every fetch this process makes (relay pulls
    and client fetches alike) — the serving tier's WAN uplink is one
    pipe, exactly like the PG's egress bucket across sender threads.
    """

    def __init__(
        self,
        rtt_ms: float,
        gbps: float,
        topology_spec: str,
        local_hosts: "Optional[Iterable[str]]" = None,
    ) -> None:
        self._rtt_s = max(rtt_ms, 0.0) / 1e3
        self._rate = max(gbps, 0.0) * 1e9  # decimal GB/s, like the PG
        self._flat = not topology_spec or topology_spec.lower() == "flat"
        self._local = (
            frozenset(local_hosts) if local_hosts else local_host_identities()
        )
        self._burst = 4 << 20
        self._tokens = float(self._burst)
        self._t = time.monotonic()
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return self._rtt_s > 0.0 or self._rate > 0.0

    def crosses_boundary(self, source: str) -> bool:
        """Flat/unset topology: every fetch is WAN.  Declared topology:
        only fetches from another host are."""
        if self._flat:
            return True
        return source_host(source) not in self._local

    def charge(self, source: str, nbytes: int) -> float:
        """Sleep off one message's WAN cost; returns seconds slept."""
        if not self.active or not self.crosses_boundary(source):
            return 0.0
        wait = self._rtt_s
        if self._rate > 0.0 and nbytes > 0:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    float(self._burst),
                    self._tokens + (now - self._t) * self._rate,
                )
                self._t = now
                self._tokens -= nbytes
                debt = -self._tokens
            if debt > 0:
                wait += debt / self._rate
        if wait > 0:
            time.sleep(wait)
            _metrics.SERVING_WIRE_WAIT.inc(wait)
        return wait


_shaper_lock = threading.Lock()
_shaper: "Optional[WireShaper]" = None
_shaper_key: "Optional[Tuple[float, float, str]]" = None


def get_shaper() -> WireShaper:
    """The process-wide serving wire shaper, rebuilt when the shaping
    env knobs change (tests flip them between cases; a steady process
    pays one tuple compare per fetch)."""
    global _shaper, _shaper_key
    key = (
        env_float("TORCHFT_WIRE_RTT_MS", 0.0),
        env_float("TORCHFT_WIRE_GBPS", 0.0),
        env_str("TORCHFT_TOPOLOGY", "") or "",
    )
    with _shaper_lock:
        if _shaper is None or key != _shaper_key:
            _shaper = WireShaper(key[0], key[1], key[2])
            _shaper_key = key
        return _shaper
