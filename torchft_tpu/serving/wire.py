"""Serving-tier WAN wire model (ROADMAP "serving-tier WAN realism").

The training-side shaper (parallel/process_group.py) models the WAN with
two decoupled legs — ``TORCHFT_WIRE_RTT_MS``, a per-MESSAGE first-byte
latency, and ``TORCHFT_WIRE_GBPS``, a shared egress token bucket — both
scoped to messages that cross the ``TORCHFT_TOPOLOGY`` boundary.  This
module applies the SAME model to the serving tier's fetch/relay HTTP
pulls, so serving benches and soaks price multi-region distribution
realistically instead of at loopback speed.

Boundary rule: the serving tier has no rank grid, so the topology
boundary is tested by HOST — with a declared (non-flat)
``TORCHFT_TOPOLOGY``, a fetch whose source host is this machine rides
the local fabric unshaped; with a flat/unset topology EVERY fetch
crosses the boundary (the multi-region premise, and the same default
the PG shaper uses for flat topologies).  A fetch pays one RTT plus
``bytes/rate`` of bucket debt, never more: pacing below one message
would only measure sleep granularity.

Shaping is charged as explicit sleeps on the fetching side after the
response arrives — from the caller's point of view latency and
throughput bound exactly as a shaped link would, without touching the
HTTP stack.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, Optional, Tuple
from urllib.parse import urlparse

from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils.env import env_float, env_str
from torchft_tpu.utils.hostident import local_host_identities

__all__ = ["WireShaper", "get_shaper", "source_host"]


def source_host(source: str) -> str:
    """The host of a serving source address: a transport base URL
    (``http://host:port``) or a bare ``host:port``."""
    if "://" in source:
        return urlparse(source).hostname or ""
    host, _, _port = source.rpartition(":")
    return host or "127.0.0.1"


class WireShaper:
    """One shaped serving link: per-message RTT + per-SOURCE token
    buckets.

    Each bucket models one serving node's WAN egress uplink (keyed by
    the source address — the sender-side egress semantics of the PG
    shaper): fetches from the SAME source share its pipe, fetches from
    different sources (distinct relays on distinct machines in a real
    deployment) shape independently — which is what lets the depth-axis
    bench see cut-through relays of a chain forwarding concurrently
    instead of serializing every hop through one process-wide bucket.
    ``burst_bytes`` (``TORCHFT_WIRE_BURST_MB``) is each uplink's bucket
    depth.
    """

    def __init__(
        self,
        rtt_ms: float,
        gbps: float,
        topology_spec: str,
        local_hosts: "Optional[Iterable[str]]" = None,
        burst_bytes: int = 4 << 20,
    ) -> None:
        self._rtt_s = max(rtt_ms, 0.0) / 1e3
        self._rate = max(gbps, 0.0) * 1e9  # decimal GB/s, like the PG
        self._flat = not topology_spec or topology_spec.lower() == "flat"
        self._local = (
            frozenset(local_hosts) if local_hosts else local_host_identities()
        )
        self._burst = max(int(burst_bytes), 1)
        # source address -> [tokens, last refill time]
        self._buckets: "dict[str, list[float]]" = {}
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return self._rtt_s > 0.0 or self._rate > 0.0

    def crosses_boundary(self, source: str) -> bool:
        """Flat/unset topology: every fetch is WAN.  Declared topology:
        only fetches from another host are."""
        if self._flat:
            return True
        return source_host(source) not in self._local

    def first_byte_s(self, source: str) -> float:
        """The modeled first-byte latency a fetch from ``source`` pays
        (0 when unshaped or intra-host) — the component of charge() the
        link-state plane attributes to RTT rather than bandwidth."""
        if not self.active or not self.crosses_boundary(source):
            return 0.0
        return self._rtt_s

    def charge(self, source: str, nbytes: int) -> float:
        """Sleep off one message's WAN cost; returns seconds slept."""
        if not self.active or not self.crosses_boundary(source):
            return 0.0
        wait = self._rtt_s
        if self._rate > 0.0 and nbytes > 0:
            with self._lock:
                bucket = self._buckets.get(source)
                if bucket is None:
                    bucket = self._buckets[source] = [
                        float(self._burst), time.monotonic(),
                    ]
                now = time.monotonic()
                bucket[0] = min(
                    float(self._burst),
                    bucket[0] + (now - bucket[1]) * self._rate,
                )
                bucket[1] = now
                bucket[0] -= nbytes
                debt = -bucket[0]
            if debt > 0:
                wait += debt / self._rate
        if wait > 0:
            time.sleep(wait)
            # per-host-pair attribution: shaped waits and the passively
            # measured goodput (utils/linkstats.py) join on the same
            # peer-host key; the worst-K label tier bounds cardinality
            from torchft_tpu.utils import linkstats as _linkstats

            _metrics.SERVING_WIRE_WAIT.labels(
                peer=_linkstats.LINKS.peer_topk_label(
                    source_host(source) or "unknown"
                )
            ).inc(wait)
        return wait


_shaper_lock = threading.Lock()
_shaper: "Optional[WireShaper]" = None
_shaper_key: "Optional[Tuple[float, float, str, float]]" = None


def get_shaper() -> WireShaper:
    """The process-wide serving wire shaper, rebuilt when the shaping
    env knobs change (tests flip them between cases; a steady process
    pays one tuple compare per fetch)."""
    global _shaper, _shaper_key
    key = (
        env_float("TORCHFT_WIRE_RTT_MS", 0.0),
        env_float("TORCHFT_WIRE_GBPS", 0.0),
        env_str("TORCHFT_TOPOLOGY", "") or "",
        env_float("TORCHFT_WIRE_BURST_MB", 4.0, minimum=0.001),
    )
    with _shaper_lock:
        if _shaper is None or key != _shaper_key:
            _shaper = WireShaper(
                key[0], key[1], key[2],
                burst_bytes=int(key[3] * (1 << 20)),
            )
            _shaper_key = key
        return _shaper
