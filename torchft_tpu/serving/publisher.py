"""WeightPublisher: the training-side mouth of the serving tier.

Snapshots committed weights (fed by ``Manager.attach_weight_publisher``
per committed step, or called directly per DiLoCo fragment/outer sync)
and publishes them as versioned, optionally int8-quantized payloads
staged in the HTTP checkpoint transport — the same zero-steady-state-
allocation wire path heal and reshard use.  When given a lighthouse
address it registers as the ``publisher`` serving role, so the
lighthouse-synthesized distribution tree roots at this process and every
serving replica learns new versions from its heartbeat replies.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Any, Dict, Optional

from torchft_tpu.checkpointing import provenance as _prov
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.serving import payload as _payload
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.env import env_float, env_int, env_str

logger = logging.getLogger(__name__)

__all__ = ["WeightPublisher"]


class WeightPublisher:
    """Publish versioned weight payloads for the serving tier.

    Args:
        lighthouse_addr: when set, a daemon thread heartbeats the
            ``publisher`` serving role (registration + freshest version
            + discovery address); without it the publisher is a
            standalone staging server reachable by explicit address.
        replica_id: serving-member id (defaults to ``publisher``).
        wire: payload wire format — ``f32`` or ``int8`` (default from
            ``TORCHFT_SERVING_QUANT``, f32 when unset).
        fragments: fragments per payload (the delta-fetch unit; align
            with the DiLoCo fragment count).  Default
            ``TORCHFT_SERVING_FRAGMENTS``.
        max_versions: staged versions retained; a publish burst never
            retires a version inside this window while clients still
            fetch it.  Default ``TORCHFT_SERVING_VERSIONS``.
        store: optional durable :class:`~torchft_tpu.checkpointing.
            store.FragmentStore` — each published document's fragments
            (already-encoded wire bytes + digest manifest) also spill to
            disk via ``put_doc``, no re-encode; a spill failure degrades
            (logged + counted), never failing the publish.
    """

    def __init__(
        self,
        lighthouse_addr: "Optional[str]" = None,
        replica_id: str = "publisher",
        wire: "Optional[str]" = None,
        fragments: "Optional[int]" = None,
        max_versions: "Optional[int]" = None,
        heartbeat_interval: "Optional[float]" = None,
        store: "Optional[Any]" = None,
    ) -> None:
        self._store = store
        self._wire = wire if wire is not None else (
            env_str("TORCHFT_SERVING_QUANT") or _payload.WIRE_F32
        )
        self._fragments = (
            fragments
            if fragments is not None
            else env_int("TORCHFT_SERVING_FRAGMENTS", 1, minimum=1)
        )
        self._transport = HTTPTransport(
            max_staged=(
                max_versions
                if max_versions is not None
                else env_int("TORCHFT_SERVING_VERSIONS", 4, minimum=1)
            ),
        )
        self._replica_id = replica_id
        # _version = newest successfully STAGED version (the advertised
        # latest); _reserved = newest version number minted — reserved
        # under the lock so concurrent publishes can never share a
        # version, advertised only after its bytes are actually staged
        # (a failed publish burns its number instead of advertising a
        # version clients could never fetch).
        self._version = 0
        self._reserved = 0
        # Staleness ledger: publish wall-stamp (ms, THIS process's clock
        # — the reference clock every staleness comparison uses) of
        # _version, taken from the manifest's created_ns so the stamp
        # advertised here is bit-identical to the one relays/clients
        # read out of the fetched manifest.
        self._version_ms = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # publish() sets this so the next heartbeat (which advertises the
        # new version fleet-wide) fires immediately instead of waiting
        # out the interval — version propagation latency is one beat.
        self._nudge = threading.Event()
        self._hb_thread: "Optional[threading.Thread]" = None
        self._client: Any = None
        if lighthouse_addr:
            from torchft_tpu.coordination import LighthouseClient

            self._client = LighthouseClient(lighthouse_addr)
            interval = (
                heartbeat_interval
                if heartbeat_interval is not None
                else env_float("TORCHFT_SERVING_HB_S", 0.5, minimum=0.01)
            )
            self._hb_thread = threading.Thread(
                target=self._hb_loop,
                args=(interval,),
                name="tft_serving_pub_hb",
                daemon=True,
            )
            self._hb_thread.start()

    # -- discovery ---------------------------------------------------------

    def address(self) -> str:
        """HTTP base address serving ``/checkpoint/<version>/...``."""
        return self._transport.metadata()

    def latest_version(self) -> int:
        with self._lock:
            return self._version

    def latest_version_ms(self) -> int:
        """Publish wall-stamp (ms since epoch, this process's clock) of
        :meth:`latest_version` — the staleness ledger's reference point
        (0 before the first publish)."""
        with self._lock:
            return self._version_ms

    def _hb_loop(self, interval: float) -> None:
        # Pacing loop (not a retry loop): one registration heartbeat per
        # interval; RPC failures are logged and the next beat retries
        # naturally.  Event.wait doubles as the shutdown latch.
        while not self._stop.is_set():
            # provenance piggyback: consumed-on-send, handed back to the
            # registry when the RPC fails so no vector change is lost
            digest = _prov.PROV.maybe_digest(socket.gethostname())
            try:
                reply = self._client.serving_heartbeat(
                    self._replica_id,
                    self.address(),
                    role="publisher",
                    version=self.latest_version(),
                    version_ms=self.latest_version_ms(),
                    fragments=digest,
                )
                _metrics.SERVING_PLAN_EPOCH.labels(role="publisher").set(
                    reply["plan_epoch"]
                )
            except Exception as e:  # noqa: BLE001 - keep beating
                _prov.PROV.restore_digest(digest)
                logger.warning("serving heartbeat failed: %s", e)
            self._nudge.wait(interval)
            self._nudge.clear()

    # -- publication -------------------------------------------------------

    def publish(
        self,
        state_dict: Any,
        version: "Optional[int]" = None,
        timeout: float = 60.0,
    ) -> int:
        """Publish ``state_dict`` as the next (or given) weight version;
        returns the version number staged.  Versions must be monotone —
        the version key IS the fetch coordinate."""
        with self._lock:
            v = self._reserved + 1 if version is None else int(version)
            if v <= self._reserved:
                raise ValueError(
                    f"serving version must be monotone: {v} <= "
                    f"{self._reserved}"
                )
            # Reserve INSIDE the lock: two concurrent publish() calls
            # must never mint the same version (same version = same
            # bytes everywhere is the tier's core invariant).
            self._reserved = v
        _faults.check("serving.publish", replica=self._replica_id, step=v)
        t0 = time.perf_counter()
        t0_ns = time.time_ns()
        doc = _payload.encode_payload(
            state_dict, v, wire=self._wire, fragments=self._fragments
        )
        self._transport.send_checkpoint([], v, doc, timeout=timeout)
        # Durable spill hook: the staged document already holds every
        # fragment's wire bytes + the digest manifest, so the spill is
        # pure disk writes (deduped by digest) — publish() runs on the
        # manager's single publish worker, already off the training hot
        # path.  Failures degrade and are counted by the store.
        if self._store is not None:
            try:
                self._store.put_doc(doc)
            except Exception as e:  # noqa: BLE001 - spill never fails publish
                _metrics.STORE_SPILL_FAILURES.inc()
                logger.warning("durable spill of v%s failed: %s", v, e)
        # Staleness ledger: the manifest's created_ns IS the publish
        # stamp — advertised here and carried in the payload, so every
        # tier reads the same number.
        v_ms = int(
            doc[f"frag:{_payload.MANIFEST_FRAG}"].get("created_ns", 0)
            // 1_000_000
        )
        # provenance: the publisher is the origin holder — its manifest
        # stamp is the reference clock every fleet staleness row uses
        manifest = doc[f"frag:{_payload.MANIFEST_FRAG}"]
        p_digests = manifest.get("digests") or {}
        for name in manifest.get("fragments") or ():
            _prov.note_hold(
                _prov.frag_id("weights", name), v,
                p_digests.get(name, ""), version_ms=v_ms,
                role="publisher", publisher=True,
            )
        with self._lock:
            if v > self._version:
                self._version = v
                self._version_ms = v_ms
        # Advertise synchronously: when publish() returns, the version is
        # discoverable fleet-wide (a lighthouse hiccup degrades to the
        # background beat rather than failing the publish).
        if self._client is not None:
            try:
                self._client.serving_heartbeat(
                    self._replica_id, self.address(),
                    role="publisher", version=v, version_ms=v_ms,
                )
            except Exception as e:  # noqa: BLE001 - next beat re-advertises
                logger.warning("serving publish advertise failed: %s", e)
                self._nudge.set()
        # publisher-role staleness = publish->advertise lag on the
        # publisher's own clock (encode + staging + the advertise RPC)
        if v_ms > 0:
            _metrics.SERVING_STALENESS.labels(role="publisher").observe(
                max(time.time() - v_ms / 1e3, 0.0)
            )
        dt = time.perf_counter() - t0
        _metrics.SERVING_PUBLISHES.labels(wire=self._wire).inc()
        _metrics.SERVING_PUBLISH_SECONDS.labels(wire=self._wire).observe(dt)
        _metrics.SERVING_VERSION.labels(role="publisher").set(v)
        _flightrec.record(
            "serving.publish", start_ns=t0_ns, step=v, wire=self._wire,
            fragments=self._fragments,
        )
        tracer = _tracing.get_tracer()
        ctx = _tracing.get_current()
        if tracer is not None and ctx is not None and ctx.sampled:
            tracer.export_span(
                name="serving.publish",
                trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id,
                start_ns=t0_ns,
                end_ns=time.time_ns(),
                attributes={"version": v, "wire": self._wire},
            )
        return v

    def retire(self, version: int) -> None:
        """Explicitly drop one staged version (normally the bounded
        staging window retires oldest-first on its own)."""
        self._transport.retire_checkpoint(version)

    def staged_versions(self) -> "list[int]":
        return self._transport.staged_steps()

    def shutdown(self) -> None:
        self._stop.set()
        self._nudge.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        if self._client is not None:
            self._client.close()
        self._transport.shutdown()
