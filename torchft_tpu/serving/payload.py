"""Versioned weight-payload codec for the serving tier.

A published weight version is one staged checkpoint-transport document
(``HTTPTransport`` multi-slot staging keyed by VERSION instead of step):

.. code-block:: text

    {
      "frag:manifest": {version, wire, fragments, digests, skeleton,
                        num_leaves, created_ns},
      "frag:0": {"<slot>": <encoded leaf>, ...},
      ...
      "frag:<F-1>": {...},
    }

Every fragment is independently fetchable via the transport's
``frag_<name>`` resource, so a client that already holds version ``V``
can pull version ``V+1`` as *manifest + changed fragments only* — the
per-fragment ``digests`` (publisher-computed over the encoded leaf
bytes) say which fragments moved.  A DiLoCo fragment maps naturally onto
one payload fragment (the delta unit the training side already syncs).

Leaves are optionally int8-quantized through the same per-row absmax
codec the quantized collectives use (``ops/quantization.py``, reusing
its GIL-free native kernels): a float32 leaf becomes
``{"q8": int8 payload, "scale": f32 row scales, "shape": [...]}``.
Encoding is deterministic, so two serving replicas relaying the same
published version hold — and serve — bitwise-identical bytes: the
property the chaos tests pin (failover mid-fetch completes with
identical weights).
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "WIRE_F32",
    "WIRE_INT8",
    "MANIFEST_FRAG",
    "encode_payload",
    "decode_fragment",
    "decode_payload",
    "changed_fragments",
]

WIRE_F32 = "f32"
WIRE_INT8 = "int8"

#: the manifest travels as a fragment itself so the delta path is
#: uniform: fetch ``frag_manifest``, diff digests, fetch what moved.
MANIFEST_FRAG = "manifest"

_Q8_KEY = "q8"


def _encode_leaf(leaf: Any, wire: str) -> Any:
    if wire != WIRE_INT8:
        return leaf
    if not isinstance(leaf, np.ndarray) and hasattr(leaf, "__array__"):
        leaf = np.asarray(leaf)
    if (
        not isinstance(leaf, np.ndarray)
        or leaf.dtype != np.float32
        or leaf.size == 0
    ):
        return leaf
    from torchft_tpu.ops import quantization as q

    # The codec's own row view (``_as_rows``: leading dim = rows, rest
    # flattened) — passing the leaf straight through keeps serving
    # payload bytes in lockstep with the collective wire bytes by
    # construction, not by a mirrored re-implementation.
    scales, payload = q.quantize(np.ascontiguousarray(leaf), q.WIRE_INT8)
    return {
        _Q8_KEY: payload,
        "scale": scales,
        "shape": np.asarray(leaf.shape, dtype=np.int64),
    }


def _decode_leaf(leaf: Any) -> Any:
    if isinstance(leaf, dict) and _Q8_KEY in leaf:
        from torchft_tpu.ops import quantization as q

        shape = tuple(int(d) for d in np.asarray(leaf["shape"]).tolist())
        return q.dequantize(
            np.asarray(leaf["scale"]),
            np.asarray(leaf[_Q8_KEY]),
            shape,
            np.dtype(np.float32),
        )
    return leaf


def _leaf_bytes(leaf: Any) -> bytes:
    """Stable byte view of an encoded leaf for digesting."""
    if isinstance(leaf, dict) and _Q8_KEY in leaf:
        return (
            np.ascontiguousarray(leaf[_Q8_KEY]).tobytes()
            + np.ascontiguousarray(leaf["scale"]).tobytes()
        )
    if isinstance(leaf, np.ndarray) or hasattr(leaf, "__array__"):
        return np.ascontiguousarray(np.asarray(leaf)).tobytes()
    return repr(leaf).encode()


def encode_payload(
    state_dict: Any,
    version: int,
    wire: str = WIRE_F32,
    fragments: int = 1,
) -> "Dict[str, Any]":
    """Build the staged document for one published weight version.

    ``fragments``: leaf slots are split round-robin into this many
    independently fetchable fragments (the delta unit); pass the DiLoCo
    fragment count to align delta fetches with training's sync unit.
    """
    import jax

    if wire not in (WIRE_F32, WIRE_INT8):
        raise ValueError(f"serving wire must be f32|int8, got {wire!r}")
    fragments = max(int(fragments), 1)
    leaves, treedef = jax.tree_util.tree_flatten(state_dict)
    skeleton = jax.tree_util.tree_unflatten(treedef, list(range(len(leaves))))
    frag_names = [str(i) for i in range(min(fragments, max(len(leaves), 1)))]
    doc: "Dict[str, Any]" = {}
    digests: "Dict[str, str]" = {}
    for fi, name in enumerate(frag_names):
        frag: "Dict[str, Any]" = {}
        h = hashlib.sha256()
        for slot in range(fi, len(leaves), len(frag_names)):
            enc = _encode_leaf(leaves[slot], wire)
            frag[str(slot)] = enc
            h.update(str(slot).encode())
            h.update(_leaf_bytes(enc))
        doc[f"frag:{name}"] = frag
        digests[name] = h.hexdigest()
    doc[f"frag:{MANIFEST_FRAG}"] = {
        "version": int(version),
        "wire": wire,
        "fragments": frag_names,
        "digests": digests,
        "skeleton": skeleton,
        "num_leaves": len(leaves),
        "created_ns": time.time_ns(),
    }
    return doc


def decode_fragment(frag: "Dict[str, Any]") -> "Dict[int, Any]":
    """Decode one fetched fragment into ``{leaf slot: decoded leaf}``."""
    return {int(slot): _decode_leaf(leaf) for slot, leaf in frag.items()}


def changed_fragments(
    manifest: "Dict[str, Any]", prev_manifest: "Optional[Dict[str, Any]]"
) -> "List[str]":
    """Fragment names whose digest differs from ``prev_manifest`` (all of
    them when there is no previous version or the shape changed)."""
    names = list(manifest["fragments"])
    if prev_manifest is None or prev_manifest.get("num_leaves") != manifest.get(
        "num_leaves"
    ):
        return names
    prev = prev_manifest.get("digests") or {}
    return [n for n in names if manifest["digests"].get(n) != prev.get(n)]


def decode_payload(
    doc: "Dict[str, Any]",
    prev: "Optional[Tuple[Dict[str, Any], Dict[int, Any]]]" = None,
) -> "Tuple[Any, Dict[str, Any], Dict[int, Any]]":
    """Decode a full fetched document (or a manifest + changed-fragment
    subset merged over ``prev = (prev_manifest, prev_leaves)``).

    Returns ``(state_dict, manifest, leaves)`` — keep ``(manifest,
    leaves)`` around to decode the next delta fetch.
    """
    import jax

    manifest = doc[f"frag:{MANIFEST_FRAG}"]
    leaves: "Dict[int, Any]" = dict(prev[1]) if prev is not None else {}
    for name in manifest["fragments"]:
        frag = doc.get(f"frag:{name}")
        if frag is not None:
            leaves.update(decode_fragment(frag))
    n = int(manifest["num_leaves"])
    missing = [i for i in range(n) if i not in leaves]
    if missing:
        raise ValueError(
            f"serving payload v{manifest.get('version')}: missing leaf "
            f"slots {missing[:5]}{'...' if len(missing) > 5 else ''} "
            f"(delta fetch without a complete previous version?)"
        )
    state = jax.tree_util.tree_map(
        lambda slot: leaves[slot], manifest["skeleton"]
    )
    return state, manifest, leaves
