"""Versioned weight-payload codec for the serving tier — thin alias.

The fragment codec was promoted to the shared fragment plane
(``torchft_tpu/checkpointing/fragments.py``, ISSUE 15) so the heal path
could ride the same digest-manifested fragment documents; this module
keeps the serving tier's import surface stable.  See the fragments
module for the format contract (serialized-wire fragments, sha256
digests, zero-decode passthrough, optional int8 leaves).
"""

from __future__ import annotations

from torchft_tpu.checkpointing.fragments import (  # noqa: F401
    HEADER_FRAG,
    MANIFEST_FRAG,
    WIRE_F32,
    WIRE_INT8,
    _ViewReader,
    assemble,
    changed_fragments,
    decode_fragment,
    decode_manifest,
    decode_payload,
    encode_payload,
    fragment_wire,
    verify_fragment,
)

__all__ = [
    "WIRE_F32",
    "WIRE_INT8",
    "MANIFEST_FRAG",
    "encode_payload",
    "decode_fragment",
    "decode_manifest",
    "decode_payload",
    "assemble",
    "changed_fragments",
    "fragment_wire",
    "verify_fragment",
]
