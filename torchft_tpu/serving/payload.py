"""Versioned weight-payload codec for the serving tier.

A published weight version is one staged checkpoint-transport document
(``HTTPTransport`` multi-slot staging keyed by VERSION instead of step):

.. code-block:: text

    {
      "frag:manifest": {version, wire, fragments, digests, skeleton,
                        num_leaves, created_ns},
      "frag:0": <serialized fragment wire bytes>,
      ...
      "frag:<F-1>": <bytes>,
    }

Every fragment is independently fetchable via the transport's
``frag_<name>`` resource, so a client that already holds version ``V``
can pull version ``V+1`` as *manifest + changed fragments only* — the
per-fragment ``digests`` say which fragments moved.  A DiLoCo fragment
maps naturally onto one payload fragment (the delta unit the training
side already syncs).

Fragments are stored (and staged, and relayed) as the **serialized wire
stream itself** (``checkpointing/serialization.py`` format), and the
publisher's digest is the sha256 of exactly those bytes.  That is the
contract the streaming relay path (ISSUE 14) is built on: a relay can
verify a fragment on receipt and re-serve it **verbatim** — zero decode
passes, zero Python-object copies — and every node in the tree holds
bitwise-identical bytes by construction, not by re-encoding
deterministically.  A fragment travelling the tree may therefore appear
as ``bytes`` (publisher-encoded), a bufpool-backed ``uint8`` ndarray
(relay passthrough), or a decoded ``{slot: leaf}`` dict (tests/legacy);
:func:`fragment_wire` normalizes the raw forms.

Leaves are optionally int8-quantized through the same per-row absmax
codec the quantized collectives use (``ops/quantization.py``, reusing
its GIL-free native kernels): a float32 leaf becomes
``{"q8": int8 payload, "scale": f32 row scales, "shape": [...]}``.
"""

from __future__ import annotations

import hashlib
import io
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu.checkpointing import serialization as ser

__all__ = [
    "WIRE_F32",
    "WIRE_INT8",
    "MANIFEST_FRAG",
    "encode_payload",
    "decode_fragment",
    "decode_manifest",
    "decode_payload",
    "assemble",
    "changed_fragments",
    "fragment_wire",
    "verify_fragment",
]

WIRE_F32 = "f32"
WIRE_INT8 = "int8"

#: the manifest travels as a fragment itself so the delta path is
#: uniform: fetch ``frag_manifest``, diff digests, fetch what moved.
MANIFEST_FRAG = "manifest"

_Q8_KEY = "q8"


def _encode_leaf(leaf: Any, wire: str) -> Any:
    if wire != WIRE_INT8:
        return leaf
    if not isinstance(leaf, np.ndarray) and hasattr(leaf, "__array__"):
        leaf = np.asarray(leaf)
    if (
        not isinstance(leaf, np.ndarray)
        or leaf.dtype != np.float32
        or leaf.size == 0
    ):
        return leaf
    from torchft_tpu.ops import quantization as q

    # The codec's own row view (``_as_rows``: leading dim = rows, rest
    # flattened) — passing the leaf straight through keeps serving
    # payload bytes in lockstep with the collective wire bytes by
    # construction, not by a mirrored re-implementation.
    scales, payload = q.quantize(np.ascontiguousarray(leaf), q.WIRE_INT8)
    return {
        _Q8_KEY: payload,
        "scale": scales,
        "shape": np.asarray(leaf.shape, dtype=np.int64),
    }


def _decode_leaf(leaf: Any) -> Any:
    if isinstance(leaf, dict) and _Q8_KEY in leaf:
        from torchft_tpu.ops import quantization as q

        shape = tuple(int(d) for d in np.asarray(leaf["shape"]).tolist())
        return q.dequantize(
            np.asarray(leaf["scale"]),
            np.asarray(leaf[_Q8_KEY]),
            shape,
            np.dtype(np.float32),
        )
    return leaf


def fragment_wire(frag: Any) -> "Optional[memoryview]":
    """Raw wire view of a fragment in passthrough form (``bytes`` from
    the publisher's encode, a bufpool-backed ``uint8`` ndarray on a
    relay); ``None`` for decoded/pytree fragments."""
    return ser.raw_view(frag)


class _ViewReader(io.RawIOBase):
    """Zero-copy BinaryIO over a memoryview: ``deserialize_from`` reads
    straight out of the received buffer into the final leaf arrays —
    ``io.BytesIO(raw)`` would copy the whole fragment first."""

    def __init__(self, view: memoryview) -> None:
        self._view = view
        self._off = 0

    def readable(self) -> bool:
        return True

    def readinto(self, b: Any) -> int:
        n = min(len(b), len(self._view) - self._off)
        b[:n] = self._view[self._off:self._off + n]
        self._off += n
        return n


def verify_fragment(name: str, frag: Any, manifest: "Dict[str, Any]") -> None:
    """Check a raw fragment against the publisher-computed sha256 in the
    manifest; raises ``ValueError`` on mismatch.  Decoded fragments (no
    raw view) and fragments the manifest carries no digest for pass —
    integrity is a property of the wire form."""
    raw = fragment_wire(frag)
    if raw is None:
        return
    want = (manifest.get("digests") or {}).get(name)
    if want is None:
        return
    got = hashlib.sha256(raw).hexdigest()
    if got != want:
        raise ValueError(
            f"serving fragment {name!r} v{manifest.get('version')}: digest "
            f"mismatch ({got[:12]} != {want[:12]}) — corrupted or torn "
            f"fragment must never be staged or served"
        )


def encode_payload(
    state_dict: Any,
    version: int,
    wire: str = WIRE_F32,
    fragments: int = 1,
) -> "Dict[str, Any]":
    """Build the staged document for one published weight version.

    ``fragments``: leaf slots are split round-robin into this many
    independently fetchable fragments (the delta unit); pass the DiLoCo
    fragment count to align delta fetches with training's sync unit.
    Fragment values are the serialized wire bytes; ``digests`` is the
    sha256 of those bytes, so relays verify and re-serve them verbatim.
    """
    import jax

    if wire not in (WIRE_F32, WIRE_INT8):
        raise ValueError(f"serving wire must be f32|int8, got {wire!r}")
    fragments = max(int(fragments), 1)
    leaves, treedef = jax.tree_util.tree_flatten(state_dict)
    skeleton = jax.tree_util.tree_unflatten(treedef, list(range(len(leaves))))
    frag_names = [str(i) for i in range(min(fragments, max(len(leaves), 1)))]
    doc: "Dict[str, Any]" = {}
    digests: "Dict[str, str]" = {}
    for fi, name in enumerate(frag_names):
        frag: "Dict[str, Any]" = {}
        for slot in range(fi, len(leaves), len(frag_names)):
            frag[str(slot)] = _encode_leaf(leaves[slot], wire)
        raw = ser.serialize(frag)
        doc[f"frag:{name}"] = raw
        digests[name] = hashlib.sha256(raw).hexdigest()
    doc[f"frag:{MANIFEST_FRAG}"] = {
        "version": int(version),
        "wire": wire,
        "fragments": frag_names,
        "digests": digests,
        "skeleton": skeleton,
        "num_leaves": len(leaves),
        "created_ns": time.time_ns(),
    }
    return doc


def decode_fragment(frag: Any) -> "Dict[int, Any]":
    """Decode one fragment (raw wire bytes or an already-deserialized
    sub-dict) into ``{leaf slot: decoded leaf}``."""
    raw = fragment_wire(frag)
    if raw is not None:
        skeleton, leaves, n = ser.deserialize_from(_ViewReader(raw))
        frag = ser.reassemble(skeleton, leaves, n)
    return {int(slot): _decode_leaf(leaf) for slot, leaf in frag.items()}


def decode_manifest(raw: Any) -> "Dict[str, Any]":
    """Decode a raw ``frag_manifest`` fetch into the manifest dict."""
    view = fragment_wire(raw)
    skeleton, leaves, n = ser.deserialize_from(
        _ViewReader(view) if view is not None else io.BytesIO(raw)
    )
    manifest = ser.reassemble(skeleton, leaves, n)
    if not isinstance(manifest, dict) or "fragments" not in manifest:
        raise ValueError("serving fetch: frag_manifest is not a manifest")
    return manifest


def changed_fragments(
    manifest: "Dict[str, Any]", prev_manifest: "Optional[Dict[str, Any]]"
) -> "List[str]":
    """Fragment names whose digest differs from ``prev_manifest`` (all of
    them when there is no previous version or the shape changed)."""
    names = list(manifest["fragments"])
    if prev_manifest is None or prev_manifest.get("num_leaves") != manifest.get(
        "num_leaves"
    ):
        return names
    prev = prev_manifest.get("digests") or {}
    return [n for n in names if manifest["digests"].get(n) != prev.get(n)]


def assemble(
    manifest: "Dict[str, Any]", leaves: "Dict[int, Any]"
) -> Any:
    """Rebuild the state dict from a complete ``{slot: decoded leaf}``
    map and the manifest skeleton (the tail of :func:`decode_payload`,
    split out so pipelined fetchers can merge leaves incrementally)."""
    import jax

    n = int(manifest["num_leaves"])
    missing = [i for i in range(n) if i not in leaves]
    if missing:
        raise ValueError(
            f"serving payload v{manifest.get('version')}: missing leaf "
            f"slots {missing[:5]}{'...' if len(missing) > 5 else ''} "
            f"(delta fetch without a complete previous version?)"
        )
    return jax.tree_util.tree_map(
        lambda slot: leaves[slot], manifest["skeleton"]
    )


def decode_payload(
    doc: "Dict[str, Any]",
    prev: "Optional[Tuple[Dict[str, Any], Dict[int, Any]]]" = None,
) -> "Tuple[Any, Dict[str, Any], Dict[int, Any]]":
    """Decode a full fetched document (or a manifest + changed-fragment
    subset merged over ``prev = (prev_manifest, prev_leaves)``).

    Returns ``(state_dict, manifest, leaves)`` — keep ``(manifest,
    leaves)`` around to decode the next delta fetch.
    """
    manifest = doc[f"frag:{MANIFEST_FRAG}"]
    leaves: "Dict[int, Any]" = dict(prev[1]) if prev is not None else {}
    for name in manifest["fragments"]:
        frag = doc.get(f"frag:{name}")
        if frag is not None:
            verify_fragment(name, frag, manifest)
            leaves.update(decode_fragment(frag))
    state = assemble(manifest, leaves)
    return state, manifest, leaves
