"""Shared fragment-fetch plane — thin alias.

The pipelined fetch plane (persistent per-``(thread, netloc)``
connections, bufpool ``readinto`` receive, 503-poll retry, WAN
wire-model charge, per-fragment flight/span/fault telemetry) was
promoted to ``torchft_tpu/checkpointing/fragments.py`` (ISSUE 15) so
live healing stripes over the same plane the serving tier relays on;
this module keeps the serving tier's import surface stable.
"""

from __future__ import annotations

from torchft_tpu.checkpointing.fragments import (  # noqa: F401
    FragmentFetcher,
    close_connections,
    fetch_raw,
    fetch_serialized,
)

__all__ = [
    "FragmentFetcher",
    "fetch_raw",
    "fetch_serialized",
    "close_connections",
]
