"""Shared fragment-fetch plane for the serving tier (ISSUE 14).

One raw-HTTP fetch path used by BOTH sides of the streaming data path:
the relay pull (``ServingReplica``: cut-through restaging of opaque
verified bytes) and the client delta fetch (``ServingClient``: decode of
fragment *i* overlapped with the wire of fragment *i+1*).

Two things distinguish it from the ``urllib.urlopen``-per-fragment path
it replaces:

- **Persistent connections.**  HTTP/1.1 keep-alive connections are
  cached per ``(thread, base address)``, so a delta fetch of K changed
  fragments pays one TCP connect — and, under the WAN wire model, the
  per-message RTT charges overlap across the bounded-parallel in-flight
  window instead of serializing.  (Error responses close the connection
  per ``http.server`` semantics; the steady-state 200 stream reuses it.)
- **Bufpool-backed receive.**  Fragment bodies land straight in
  process-pool ``uint8`` buffers via ``readinto`` — no intermediate
  bytes assembly, zero steady-state allocation on the relay hot path.
  Ownership of the returned buffer transfers to the caller: stage it
  (the HTTP transport's streamed staging returns it to the pool on
  retirement) or ``POOL.give`` it back after decoding.

Every fetch is one ``serving.frag`` flight record (+ span when the step
is sampled) and consults the ``serving.frag`` chaos site with ``step`` =
the fragment's index in its stream (``pg.allreduce.chunk`` idiom:
deterministic mid-stream targeting), falling back to the version for
single fetches.
"""

from __future__ import annotations

import http.client
import threading
import time
import urllib.error
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from torchft_tpu.serving import wire as _wire
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.bufpool import POOL
from torchft_tpu.utils.env import env_int
from torchft_tpu.utils.retry import RetryPolicy

__all__ = [
    "FragmentFetcher",
    "fetch_raw",
    "fetch_serialized",
    "close_connections",
]

# Fragment fetch retry: 503 = the version/fragment exists fleet-wide but
# this node has not staged it yet (publisher encoding, parent relay
# still streaming it — the cut-through poll) — poll within the source's
# budget.  Connection errors (server killed mid-fetch, stale keep-alive
# connection) retry here too; budget expiry surfaces so the caller fails
# over to the next source.  The backoff ceiling is deliberately LOW:
# cut-through fragments land every few ms–tens of ms, so a 0.5 s ceiling
# would add more cascade latency per hop than the fragment wire itself
# (the polls ride a kept-alive connection, so each one is cheap).
_FRAG_POLICY = RetryPolicy(
    name="serving.frag",
    base_delay=0.01,
    multiplier=1.6,
    max_delay=0.1,
    retry_if=lambda e: (
        e.code == 503
        if isinstance(e, urllib.error.HTTPError)
        else isinstance(e, (urllib.error.URLError, ConnectionError, OSError))
    ),
)

_conns = threading.local()


def _conn_cache() -> "Dict[str, http.client.HTTPConnection]":
    cache = getattr(_conns, "cache", None)
    if cache is None:
        cache = _conns.cache = {}
    return cache


def _conn_for(base: str, timeout: float) -> http.client.HTTPConnection:
    cache = _conn_cache()
    conn = cache.get(base)
    if conn is None:
        p = urlparse(base)
        conn = http.client.HTTPConnection(
            p.hostname or "127.0.0.1", p.port, timeout=timeout
        )
        cache[base] = conn
    conn.timeout = timeout
    if conn.sock is not None:
        conn.sock.settimeout(timeout)
    return conn


def _drop_conn(base: str) -> None:
    conn = _conn_cache().pop(base, None)
    if conn is not None:
        try:
            conn.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


def close_connections() -> None:
    """Close THIS thread's cached keep-alive connections (tests; worker
    threads drop theirs when their executor shuts down)."""
    for base in list(_conn_cache()):
        _drop_conn(base)


def _request_once(
    base: str, path: str, timeout: float
) -> http.client.HTTPResponse:
    """One GET over the cached keep-alive connection; returns the live
    200 response (the caller consumes the body).  Raises
    ``urllib.error.HTTPError`` on non-200 (503 = retryable
    not-yet-staged, drained so the connection stays reusable) and
    ``ConnectionError`` / ``OSError`` on transport failure."""
    conn = _conn_for(base, timeout)
    headers = {}
    traceparent = _tracing.current_traceparent()
    if traceparent:
        headers["traceparent"] = traceparent
    try:
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        if resp.status != 200:
            body = resp.read()  # drain so the connection could be reused
            if resp.will_close:
                _drop_conn(base)
            raise urllib.error.HTTPError(
                f"{base}{path}",
                resp.status,
                body[:200].decode("utf-8", "replace") or resp.reason,
                resp.headers,
                None,
            )
        return resp
    except (OSError, http.client.HTTPException) as e:
        if isinstance(e, urllib.error.HTTPError):
            raise
        _drop_conn(base)
        if isinstance(e, OSError):
            raise
        raise ConnectionError(f"http fetch {base}{path}: {e}") from e


def _get_raw_once(base: str, path: str, timeout: float) -> np.ndarray:
    """One GET returning a POOLED uint8 buffer the caller owns."""
    resp = _request_once(base, path, timeout)
    try:
        n = int(resp.headers.get("Content-Length") or 0)
        buf = POOL.take(n, np.uint8)
        try:
            view = memoryview(buf)
            off = 0
            while off < n:
                got = resp.readinto(view[off:])
                if not got:
                    raise ConnectionError(
                        f"http fetch {base}{path}: body ended {n - off} "
                        f"bytes short"
                    )
                off += got
        except BaseException:
            POOL.give(buf)
            raise
        if resp.will_close:
            _drop_conn(base)
        return buf
    except (OSError, http.client.HTTPException) as e:
        _drop_conn(base)
        if isinstance(e, OSError):
            raise
        raise ConnectionError(f"http fetch {base}{path}: {e}") from e


def fetch_raw(
    base: str,
    version: int,
    resource: str,
    timeout: float,
    role: str = "client",
    frag_index: "Optional[int]" = None,
) -> np.ndarray:
    """Fetch one staged resource as raw wire bytes (POOLED uint8 buffer —
    the caller owns giving it back or staging it), with the 503-poll
    retry, the WAN wire-model charge, and per-fragment telemetry."""
    path = f"/checkpoint/{version}/{resource}"
    t0 = time.perf_counter()
    t0_ns = time.time_ns()

    def attempt(budget: "Optional[float]") -> np.ndarray:
        # Chaos INSIDE the attempt: an injected drop takes exactly the
        # broken-connection path a real one would — absorbed by this
        # policy's in-budget retries (docs/robustness.md serving.frag),
        # while raise surfaces to the caller's source-failover walk.
        _faults.check(
            "serving.frag",
            step=frag_index if frag_index is not None else version,
        )
        t = max(budget if budget is not None else 0.001, 0.001)
        return _get_raw_once(base, path, t)

    buf = _FRAG_POLICY.run(
        attempt, timeout=max(timeout, 0.001), op="serving.frag"
    )
    # WAN wire model (serving/wire.py): one RTT + bytes/rate of source-
    # uplink bucket debt per fetch message crossing the topology boundary
    _wire.get_shaper().charge(base, buf.nbytes)
    _metrics.SERVING_FETCH_BYTES.labels(role=role).inc(buf.nbytes)
    _flightrec.record(
        "serving.frag", start_ns=t0_ns, step=version, resource=resource,
        bytes=buf.nbytes, source=base, role=role,
    )
    tracer = _tracing.get_tracer()
    ctx = _tracing.get_current()
    if tracer is not None and ctx is not None and ctx.sampled:
        tracer.export_span(
            name="serving.frag",
            trace_id=ctx.trace_id,
            parent_span_id=ctx.span_id,
            start_ns=t0_ns,
            end_ns=time.time_ns(),
            attributes={
                "version": version, "resource": resource,
                "bytes": buf.nbytes, "role": role,
            },
        )
    return buf


def fetch_serialized(
    base: str,
    version: int,
    resource: str,
    timeout: float,
    role: str = "client",
) -> "Tuple[Any, Dict[int, Any], int]":
    """Fetch one resource and deserialize it STRAIGHT OFF the socket —
    the whole-payload (``full``) path: a multi-GB document lands
    directly in its final leaf buffers (serialization.py's streaming
    contract) instead of being buffered raw and copied again.  Returns
    ``(skeleton, leaves, num_leaves)``; same retry/wire/telemetry
    envelope as :func:`fetch_raw`."""
    from torchft_tpu.checkpointing import serialization as ser

    path = f"/checkpoint/{version}/{resource}"
    t0_ns = time.time_ns()

    def attempt(budget: "Optional[float]") -> "Tuple[Any, Dict[int, Any], int, int]":
        _faults.check("serving.frag", step=version)
        t = max(budget if budget is not None else 0.001, 0.001)
        resp = _request_once(base, path, t)
        nbytes = int(resp.headers.get("Content-Length") or 0)
        try:
            out = ser.deserialize_from(resp)
            resp.read()  # drain any trailer so the connection is reusable
        except BaseException as e:
            # mid-body failure: unknown remainder, the conn can't be kept
            _drop_conn(base)
            if isinstance(e, EOFError):
                # truncated stream = broken connection: retryable
                raise ConnectionError(
                    f"http fetch {base}{path}: truncated stream: {e}"
                ) from e
            raise
        if resp.will_close:
            _drop_conn(base)
        return out + (nbytes,)

    skeleton, leaves, n, nbytes = _FRAG_POLICY.run(
        attempt, timeout=max(timeout, 0.001), op="serving.frag"
    )
    _wire.get_shaper().charge(base, nbytes)
    _metrics.SERVING_FETCH_BYTES.labels(role=role).inc(nbytes)
    _flightrec.record(
        "serving.frag", start_ns=t0_ns, step=version, resource=resource,
        bytes=nbytes, source=base, role=role,
    )
    return skeleton, leaves, n


class FragmentFetcher:
    """Bounded-parallel pipelined fragment fetcher.

    ``parallel`` (default ``TORCHFT_SERVING_PARALLEL``) raw fetches ride
    persistent per-thread connections concurrently; results come back in
    SUBMISSION order so the consumer's verify/decode/stage of fragment
    *i* overlaps the wire of fragments *i+1..i+K*.
    """

    def __init__(
        self, parallel: "Optional[int]" = None, role: str = "client"
    ) -> None:
        self._parallel = (
            parallel
            if parallel is not None
            else env_int("TORCHFT_SERVING_PARALLEL", 4, minimum=1)
        )
        self._role = role
        self._pool: "Optional[ThreadPoolExecutor]" = None
        self._lock = threading.Lock()

    def _executor(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._parallel,
                    thread_name_prefix="tft_serving_fetch",
                )
            return self._pool

    def fetch_raw(
        self, base: str, version: int, resource: str, timeout: float
    ) -> np.ndarray:
        return fetch_raw(base, version, resource, timeout, role=self._role)

    def fetch_stream(
        self,
        base: str,
        version: int,
        resources: "List[str]",
        deadline: float,
    ) -> "Iterator[Tuple[str, np.ndarray, Tuple[float, float]]]":
        """Pipelined raw fetches of ``resources`` from one source; yields
        ``(resource, pooled_buffer, (wire_start, wire_end))`` in
        submission order — the perf-counter interval each fetch occupied
        the wire, so the consumer can compute true (union) wire busy
        time across the concurrent in-flight window.  On failure,
        buffers still in flight are drained back to the pool and the
        error re-raised (the caller fails over to another source;
        already-yielded items stay valid and staged)."""
        if not resources:
            return
        ex = self._executor()
        pending: "deque[Tuple[str, Future]]" = deque()
        it = iter(enumerate(resources))

        def _timed(
            res: str, idx: int
        ) -> "Tuple[np.ndarray, Tuple[float, float]]":
            t0 = time.perf_counter()
            buf = fetch_raw(
                base, version, res,
                timeout=max(deadline - time.monotonic(), 0.001),
                role=self._role, frag_index=idx,
            )
            return buf, (t0, time.perf_counter())

        def _submit_next() -> bool:
            try:
                idx, res = next(it)
            except StopIteration:
                return False
            pending.append((res, ex.submit(_timed, res, idx)))
            return True

        def _drain_pending() -> None:
            while pending:
                _res, fut = pending.popleft()
                try:
                    buf, _ = fut.result()
                except BaseException:  # noqa: BLE001 - already failing
                    continue
                POOL.give(buf)

        for _ in range(self._parallel):
            if not _submit_next():
                break
        try:
            while pending:
                res, fut = pending.popleft()
                try:
                    buf, span = fut.result()
                except BaseException:
                    _drain_pending()
                    raise
                _submit_next()
                yield res, buf, span
        except GeneratorExit:
            # consumer abandoned the stream mid-flight (failover after a
            # verify failure): nothing may leak out of the pool
            _drain_pending()
            raise

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
