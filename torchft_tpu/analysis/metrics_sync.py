"""Pass ``metrics-sync``: the exported-metrics surface and the
structured-event kinds stay coherent with their registries and docs.

The telemetry layer's contract (PR 1) is "one source of truth": every
instrument is defined in ``utils/metrics.py``'s bottom section and
mirrored in docs/observability.md's table; every event ``kind`` exists
in BOTH ``_LOGGERS`` (utils/logging.py — ``log_event`` rejects unknown
kinds) and ``_SEVERITY`` (utils/otel.py — an unmapped kind silently
exports as INFO, burying errors).  Those invariants only held because
reviewers remembered them; this pass remembers instead:

- ``non-torchft-metric``: a ``counter()``/``gauge()``/``histogram()``
  (or class-constructor) registration whose name doesn't start with
  ``torchft_`` — the namespace contract with dashboards/alerts;
- ``duplicate-metric``: the same name registered from more than one
  call site (get-or-create makes this *run*, but two definitions drift);
- ``undocumented-metric``: a registered name missing from
  docs/observability.md (the native lighthouse metrics are documented
  there too, but originate in C++ and are out of this pass's scope);
- ``kind-maps-diverged``: ``_LOGGERS`` and ``_SEVERITY`` key sets
  differ;
- ``unknown-event-kind``: a ``log_event("<kind>", ...)`` literal not in
  ``_LOGGERS`` — it would raise ``ValueError`` at runtime, on the
  failure path where it hurts most.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    QualnameVisitor,
    SelftestError,
    const_str,
    dotted,
)

PASS_ID = "metrics-sync"

_FACTORIES = ("counter", "gauge", "histogram", "Counter", "Gauge", "Histogram")
_OBSERVABILITY_DOC = "docs/observability.md"
_LOGGING_FILE = "utils/logging.py"
_OTEL_FILE = "utils/otel.py"

# Registrations inside these test/selftest helpers are exempt from the
# namespace + docs rules (they create fixture registries on purpose).
_EXEMPT_NAME_PREFIXES = ("test_", "_selftest")


class _MetricCollector(QualnameVisitor):
    def __init__(self, project: Project, path: str) -> None:
        super().__init__()
        self.project = project
        self.path = path
        self.registrations: "List[Tuple[str, int, str]]" = []  # (name, line, qual)

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        func = dotted(node.func).rsplit(".", 1)[-1]
        if func in _FACTORIES and node.args:
            name = const_str(node.args[0])
            if name is not None and not any(
                part.startswith(_EXEMPT_NAME_PREFIXES)
                for part in self.qualname.split(".")
            ):
                self.registrations.append((name, node.lineno, self.qualname))
        self.generic_visit(node)


def _dict_keys(tree: ast.Module, var_name: str) -> "Optional[Set[str]]":
    """String keys of a module-level ``VAR = {...}`` dict, or None."""
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == var_name
            and isinstance(node.value, ast.Dict)
        ):
            keys: "Set[str]" = set()
            for k in node.value.keys:
                val = const_str(k)
                if val is not None:
                    keys.add(val)
            return keys
    return None


def run(project: Project) -> "Iterable[Finding]":
    out: "List[Finding]" = []
    doc = project.doc_text_for(_OBSERVABILITY_DOC)

    # --- metric registrations ------------------------------------------
    by_name: "Dict[str, List[Tuple[str, int, str]]]" = {}
    for path in project.py_files:
        tree = project.tree(path)
        if tree is None:
            continue
        col = _MetricCollector(project, path)
        col.visit(tree)
        for name, line, qual in col.registrations:
            by_name.setdefault(name, []).append((path, line, qual))

    for name, sites in sorted(by_name.items()):
        path, line, qual = sites[0]
        rel = project.rel(path)
        if not name.startswith("torchft_"):
            out.append(
                Finding(
                    pass_id=PASS_ID,
                    code="non-torchft-metric",
                    file=rel,
                    line=line,
                    symbol=name,
                    message=(
                        f"metric {name!r} breaks the torchft_* namespace "
                        f"contract with dashboards and alert rules"
                    ),
                )
            )
        if len(sites) > 1:
            others = ", ".join(
                f"{project.rel(p)}:{ln}" for p, ln, _ in sites[1:]
            )
            out.append(
                Finding(
                    pass_id=PASS_ID,
                    code="duplicate-metric",
                    file=rel,
                    line=line,
                    symbol=name,
                    message=(
                        f"metric {name!r} registered from {len(sites)} call "
                        f"sites (also {others}) — define once in "
                        f"utils/metrics.py and import"
                    ),
                )
            )
        if doc and name.startswith("torchft_") and name not in doc:
            out.append(
                Finding(
                    pass_id=PASS_ID,
                    code="undocumented-metric",
                    file=rel,
                    line=line,
                    symbol=name,
                    message=(
                        f"metric {name!r} is missing from the "
                        f"{_OBSERVABILITY_DOC} table"
                    ),
                )
            )

    # --- event-kind maps ------------------------------------------------
    loggers_keys: "Optional[Set[str]]" = None
    logging_path = project.find_file(_LOGGING_FILE)
    otel_path = project.find_file(_OTEL_FILE)
    if logging_path is not None and otel_path is not None:
        ltree, otree = project.tree(logging_path), project.tree(otel_path)
        if ltree is not None and otree is not None:
            loggers_keys = _dict_keys(ltree, "_LOGGERS")
            severity_keys = _dict_keys(otree, "_SEVERITY")
            if loggers_keys is not None and severity_keys is not None:
                for missing in sorted(loggers_keys - severity_keys):
                    out.append(
                        Finding(
                            pass_id=PASS_ID,
                            code="kind-maps-diverged",
                            file=project.rel(otel_path),
                            line=1,
                            symbol=missing,
                            message=(
                                f"event kind {missing!r} is in _LOGGERS but "
                                f"not _SEVERITY — it would export at INFO, "
                                f"burying it"
                            ),
                        )
                    )
                for missing in sorted(severity_keys - loggers_keys):
                    out.append(
                        Finding(
                            pass_id=PASS_ID,
                            code="kind-maps-diverged",
                            file=project.rel(logging_path),
                            line=1,
                            symbol=missing,
                            message=(
                                f"event kind {missing!r} is in _SEVERITY but "
                                f"not _LOGGERS — log_event would reject it"
                            ),
                        )
                    )

    # --- log_event call sites -------------------------------------------
    if loggers_keys:
        for path in project.py_files:
            tree = project.tree(path)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and dotted(node.func).rsplit(".", 1)[-1] == "log_event"
                    and node.args
                ):
                    kind = const_str(node.args[0])
                    if kind is not None and kind not in loggers_keys:
                        out.append(
                            Finding(
                                pass_id=PASS_ID,
                                code="unknown-event-kind",
                                file=project.rel(path),
                                line=node.lineno,
                                symbol=kind,
                                message=(
                                    f"log_event kind {kind!r} is not in "
                                    f"_LOGGERS — this call raises ValueError "
                                    f"at runtime"
                                ),
                            )
                        )
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

_DOC = "| `torchft_good_total` | counter | documented |\n"

_LOGGING_SRC = '_LOGGERS = {"quorum": 1, "error": 2}\n'
_OTEL_SRC = '_SEVERITY = {"quorum": (9, "INFO")}\n'  # "error" missing -> diverged


def _run_on_project(files: "Dict[str, str]", doc: str = _DOC) -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory(prefix="tftlint_selftest_") as td:
        os.makedirs(os.path.join(td, "docs"))
        with open(
            os.path.join(td, "docs", "observability.md"), "w", encoding="utf-8"
        ) as fh:
            fh.write(doc)
        paths = []
        for rel, src in files.items():
            path = os.path.join(td, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(src)
            paths.append(path)
        return list(run(Project(td, paths)))


def selftest() -> None:
    bad = _run_on_project(
        {
            "pkg/m.py": (
                "from torchft_tpu.utils.metrics import counter\n"
                'A = counter("myapp_oops_total", "bad namespace")\n'
                'B = counter("torchft_dup_total", "dup a")\n'
            ),
            "pkg/n.py": (
                "from torchft_tpu.utils.metrics import counter\n"
                'C = counter("torchft_dup_total", "dup b")\n'
                'D = counter("torchft_undocumented_total", "undocumented")\n'
                "from torchft_tpu.utils.logging import log_event\n"
                'log_event("nonexistent_kind", "boom")\n'
            ),
            "pkg/utils/logging.py": _LOGGING_SRC,
            "pkg/utils/otel.py": _OTEL_SRC,
        }
    )
    codes = {f.code for f in bad}
    expect = {
        "non-torchft-metric",
        "duplicate-metric",
        "undocumented-metric",
        "kind-maps-diverged",
        "unknown-event-kind",
    }
    missing = expect - codes
    if missing:
        raise SelftestError(f"{PASS_ID}: bad project missed codes {missing}")

    got = _run_on_project(
        {
            "pkg/m.py": (
                "from torchft_tpu.utils.metrics import counter\n"
                'A = counter("torchft_good_total", "documented")\n'
                "from torchft_tpu.utils.logging import log_event\n"
                'log_event("quorum", "fine")\n'
            ),
            "pkg/utils/logging.py": _LOGGING_SRC,
            "pkg/utils/otel.py": '_SEVERITY = {"quorum": (9, "INFO"), "error": (17, "ERROR")}\n',
        }
    )
    if got:
        raise SelftestError(
            f"{PASS_ID}: good project falsely flagged: "
            f"{[f.render() for f in got]}"
        )


PASS = LintPass(
    id=PASS_ID,
    doc="metric names are torchft_*, unique, and documented; event kinds "
    "exist in both _LOGGERS and _SEVERITY",
    run=run,
    selftest=selftest,
)
