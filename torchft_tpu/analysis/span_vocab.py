"""Pass ``span-vocab``: trace spans stay joinable and post-mortem-visible.

The distributed-tracing layer (utils/tracing.py) is only useful if the
spans the fleet emits share ONE name vocabulary — the diagnose ledger
(``torchft-diagnose --trace``) maps span names to cost categories, and a
free-form name silently falls out of every report.  Two rules:

**Vocabulary.**  Every ``export_span`` call site must name its span from
``manager.PROTOCOL_PHASES`` (parsed from the tree, the same canonical
tuple the flight recorder and the quorum-duration histogram label from),
the ``quorum_round`` root, or the documented prefix families ``quant.*``
(quantized-collective pipeline), ``heal.*`` (checkpoint heal endpoints),
``rpc.*`` (native server spans), and ``serving.*`` (weight-serving tier
publish/fetch/tree-commit) — docs/observability.md "Distributed
tracing".  One level of indirection is resolved: when the name argument
is a parameter of the enclosing function (e.g. ``Manager._record_phase``),
the SAME-MODULE callers' literal arguments are checked instead.

**Flight reach.**  Every traced phase must also reach the flight
recorder: a function that emits a span must reference the recorder
within two same-module call hops (the exact rule fault-coverage applies
to the PG worker and the checkpoint transports) — a trace backend must
never know something the crash-durable post-mortem dump doesn't.

``utils/tracing.py`` itself (the emit implementation) is exempt, as are
test files.  Waiver: ``# tft-lint: allow(span-vocab)`` on the line.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    QualnameVisitor,
    SelftestError,
    const_str,
    dotted,
)
from torchft_tpu.analysis.coverage import _module_flight_reach

PASS_ID = "span-vocab"

_MANAGER_FILE = "manager.py"

#: documented span-name prefix families (docs/observability.md)
SPAN_FAMILIES = ("quant.", "heal.", "rpc.", "serving.", "link.",
                 "fragment.")

#: allowed exact names beyond PROTOCOL_PHASES
EXTRA_SPAN_NAMES = ("quorum_round",)

#: files whose span plumbing is the implementation, not a call site
_EXEMPT_SUFFIXES = ("utils/tracing.py",)


def _protocol_phases(project: Project) -> "Optional[Set[str]]":
    """Parse ``PROTOCOL_PHASES`` from the tree's manager.py (None when
    absent — the vocabulary rule then only enforces the families)."""
    path = project.find_file(_MANAGER_FILE)
    if path is None:
        return None
    tree = project.tree(path)
    if tree is None:
        return None
    for node in tree.body:
        value: "Optional[ast.AST]" = None
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == "PROTOCOL_PHASES"
        ):
            value = node.value
        elif (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "PROTOCOL_PHASES"
        ):
            value = node.value
        if isinstance(value, (ast.Tuple, ast.List)):
            names = {const_str(e) for e in value.elts}
            return {n for n in names if n is not None}
    return None


def _allowed(name: str, phases: "Optional[Set[str]]") -> bool:
    if phases is not None and name in phases:
        return True
    if name in EXTRA_SPAN_NAMES:
        return True
    return any(
        name.startswith(fam) and len(name) > len(fam) for fam in SPAN_FAMILIES
    )


def _has_waiver(project: Project, path: str, lineno: int) -> bool:
    lines = project.source(path).splitlines()
    if 1 <= lineno <= len(lines):
        return f"tft-lint: allow({PASS_ID})" in lines[lineno - 1]
    return False


def _span_name_arg(node: ast.Call) -> "Optional[ast.AST]":
    for kw in node.keywords:
        if kw.arg == "name":
            return kw.value
    if node.args:
        return node.args[0]
    return None


class _EmitCollector(QualnameVisitor):
    """Collects ``*.export_span(...)`` sites and, per enclosing function,
    the name-parameter indirections plus all same-module calls."""

    def __init__(self) -> None:
        super().__init__()
        # (lineno, qualname, name_node, enclosing_fn, enclosing_params)
        self.emits: "List[Tuple[int, str, Optional[ast.AST], str, Set[str]]]" = []
        # function name -> [(call node, lineno)]
        self.calls: "Dict[str, List[ast.Call]]" = {}
        self._fn_stack: "List[Tuple[str, Set[str]]]" = []

    def _visit_func(self, node: ast.AST) -> None:  # type: ignore[override]
        params = {
            a.arg
            for a in list(node.args.args) + list(node.args.kwonlyargs)  # type: ignore[attr-defined]
        }
        self._fn_stack.append((node.name, params))  # type: ignore[attr-defined]
        self._stack.append(node.name)  # type: ignore[attr-defined]
        self.generic_visit(node)
        self._stack.pop()
        self._fn_stack.pop()

    visit_FunctionDef = _visit_func  # noqa: N815
    visit_AsyncFunctionDef = _visit_func  # noqa: N815

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        leaf = dotted(node.func).rsplit(".", 1)[-1]
        if leaf == "export_span":
            fn, params = self._fn_stack[-1] if self._fn_stack else ("", set())
            self.emits.append(
                (node.lineno, self.qualname, _span_name_arg(node), fn, params)
            )
        else:
            self.calls.setdefault(leaf, []).append(node)
        self.generic_visit(node)


def run(project: Project) -> "Iterable[Finding]":
    out: "List[Finding]" = []
    phases = _protocol_phases(project)

    for path in project.py_files:
        rel = project.rel(path).replace("\\", "/")
        if any(rel.endswith(s) for s in _EXEMPT_SUFFIXES):
            continue
        if "/tests/" in rel or rel.startswith("tests/"):
            continue
        tree = project.tree(path)
        if tree is None:
            continue
        col = _EmitCollector()
        col.visit(tree)
        if not col.emits:
            continue
        reach = _module_flight_reach(tree)

        def flag(lineno: int, code: str, symbol: str, message: str) -> None:
            if _has_waiver(project, path, lineno):
                return
            out.append(
                Finding(
                    pass_id=PASS_ID,
                    code=code,
                    file=project.rel(path),
                    line=lineno,
                    symbol=symbol,
                    message=message,
                )
            )

        emitting_fns: "Set[str]" = set()
        for lineno, qual, name_node, fn, params in col.emits:
            if fn:
                emitting_fns.add(fn)
            name = const_str(name_node)
            if name is not None:
                if not _allowed(name, phases):
                    flag(
                        lineno,
                        "unknown-span-name",
                        name,
                        f"span name {name!r} is not in manager."
                        f"PROTOCOL_PHASES, {EXTRA_SPAN_NAMES}, or the "
                        f"documented {'/'.join(SPAN_FAMILIES)}* families — "
                        f"the diagnose ledger cannot categorize it",
                    )
                continue
            # one level of indirection: name comes from the enclosing
            # function's parameter -> validate same-module callers
            if (
                isinstance(name_node, ast.Name)
                and name_node.id in params
                and fn
            ):
                # callers pass the phase name as the first argument by
                # convention; keyword form is also resolved
                for call in col.calls.get(fn, []):
                    cand: "Optional[ast.AST]" = None
                    for kw in call.keywords:
                        if kw.arg == name_node.id:
                            cand = kw.value
                    if cand is None and call.args:
                        cand = call.args[0]
                    lit = const_str(cand)
                    if lit is None:
                        flag(
                            call.lineno,
                            "non-literal-span-name",
                            fn,
                            f"call to span-emitting {fn}() passes a "
                            f"non-literal span name — the vocabulary "
                            f"cannot be checked statically",
                        )
                    elif not _allowed(lit, phases):
                        flag(
                            call.lineno,
                            "unknown-span-name",
                            lit,
                            f"span name {lit!r} (via {fn}()) is not in "
                            f"manager.PROTOCOL_PHASES, {EXTRA_SPAN_NAMES}, "
                            f"or the documented "
                            f"{'/'.join(SPAN_FAMILIES)}* families",
                        )
                continue
            flag(
                lineno,
                "non-literal-span-name",
                qual,
                "export_span name is neither a literal nor a parameter of "
                "the enclosing function — the vocabulary cannot be checked "
                "statically",
            )

        # flight reach: every span-emitting function must reach the
        # flight recorder within two same-module hops
        for fn in sorted(emitting_fns):
            if fn not in reach:
                lineno = next(
                    (ln for ln, _, _, f, _ in col.emits if f == fn), 1
                )
                flag(
                    lineno,
                    "span-without-flight",
                    fn,
                    f"{fn} emits trace spans but never reaches the flight "
                    f"recorder (no record/start/track reference within two "
                    f"same-module call hops) — a traced phase must stay "
                    f"visible in crash-durable post-mortem dumps too",
                )
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------


def _run_on_project(files: "Dict[str, str]") -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory(prefix="tftlint_selftest_") as td:
        os.makedirs(os.path.join(td, "docs"))
        with open(os.path.join(td, "docs", "x.md"), "w", encoding="utf-8") as fh:
            fh.write("")
        paths = []
        for rel, src in files.items():
            path = os.path.join(td, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(src)
            paths.append(path)
        return list(run(Project(td, paths)))


_MANAGER_SRC = 'PROTOCOL_PHASES = ("quorum_rpc", "ring", "commit")\n'

_GOOD_SRC = """
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import tracing

def _record_phase(name, dt):
    _flightrec.record(name, kind="phase")
    tracer = tracing.get_tracer()
    if tracer is not None:
        tracer.export_span(name=name, trace_id="t", start_ns=0, end_ns=1)

def step(tracer):
    _record_phase("ring", 0.1)
    _flightrec.record("quant.pipeline")
    tracer.export_span("quant.pipeline", "t", 0, 1)
    tracer.export_span("heal.send", "t", 0, 1)
    tracer.export_span("link.digest", "t", 0, 1)
    tracer.export_span("fragment.hop", "t", 0, 1)
    tracer.export_span("quorum_round", "t", 0, 1)
"""

_BAD_VOCAB_SRC = """
from torchft_tpu.utils import flightrecorder as _flightrec

def emit(tracer):
    _flightrec.record("x")
    tracer.export_span("made_up_phase", "t", 0, 1)
"""

_BAD_INDIRECT_SRC = """
from torchft_tpu.utils import flightrecorder as _flightrec

def _phase(name, tracer):
    _flightrec.record(name)
    tracer.export_span(name=name, trace_id="t", start_ns=0, end_ns=1)

def step(tracer):
    _phase("bogus_phase", tracer)
"""

_BAD_FLIGHT_SRC = """
def emit(tracer):
    tracer.export_span("ring", "t", 0, 1)  # no flight recorder anywhere
"""


def selftest() -> None:
    base = {"pkg/manager.py": _MANAGER_SRC}
    good = _run_on_project({**base, "pkg/good.py": _GOOD_SRC})
    if good:
        raise SelftestError(
            f"{PASS_ID}: clean project falsely flagged: "
            f"{[f.render() for f in good]}"
        )
    cases = {
        "unknown-span-name": {"pkg/bad.py": _BAD_VOCAB_SRC},
        "span-without-flight": {"pkg/bad.py": _BAD_FLIGHT_SRC},
    }
    for code, files in cases.items():
        got = {f.code for f in _run_on_project({**base, **files})}
        if code not in got:
            raise SelftestError(
                f"{PASS_ID}: seeded {code} not caught (got {sorted(got)})"
            )
    got = {f.code for f in _run_on_project({**base, "pkg/bad.py": _BAD_INDIRECT_SRC})}
    if "unknown-span-name" not in got:
        raise SelftestError(
            f"{PASS_ID}: indirect (parameter) span name not resolved to "
            f"its literal caller (got {sorted(got)})"
        )


PASS = LintPass(
    id=PASS_ID,
    doc="trace-span names come from PROTOCOL_PHASES / quant.* / heal.* / "
    "rpc.* / serving.* / link.* / fragment.*; every span-emitting "
    "function also feeds the flight recorder",
    run=run,
    selftest=selftest,
)
