"""tft-plan verifier: named invariants over any :class:`~.plan_ir.PlanIR`
(ISSUE 19) — the dynamic half of plan validation, proved the tft-verify
way.

Three legs, mirroring :mod:`torchft_tpu.analysis.model_checker`:

1. :func:`verify_plan` — the invariant catalog, checked in a fixed
   severity order so a seeded bug's FIRST reported violation is its
   named invariant:

   ==================  ====================================================
   ``acyclic``         the distribution tree (tree edges) has no cycle
   ``single-parent``   every node has at most one inbound TREE edge
   ``root-reaches-all``  every node is reachable from the plan roots
   ``fanout-bound``    tree out-degree <= per-node capacity (else the
                       plan fanout; 0 = unbounded)
   ``full-coverage``   every consumer's ownership ranges tile
                       ``[0, units)`` with no gap
   ``single-owner``    ...and with no overlap (no unit arrives twice)
   ``byte-conservation``  a relay's outbound payload equals SOME inbound
                       payload unless the node is a requant boundary
   ``requant-boundary``  wire format changes only at declared boundaries
                       (DynamiQ's requant-at-boundaries, generalized)
   ``elastic-stability``  ``hosts:K`` group assignment of surviving
                       ranks is identical across world sizes
   ==================  ====================================================

2. :func:`explore_plans` — exhaustive enumeration over small worlds ×
   topologies × churn: every reduction topology to world 8, every
   serving membership to 6 servers × fanout × capacity overrides ×
   publisher counts (plus drop-one churn resynthesis), every stripe
   (sources × fragments × leaves) plus per-source failover requeue.
   All must verify clean.

3. :data:`PLAN_MUTATIONS` / :func:`check_plan_mutation` — seeded plan
   bugs (orphaned subtree, cycle, double owner, dropped fragment, ...)
   each caught by its named invariant; ``tft-verify --scenario plan``
   and tests/test_plan_verify.py gate on the full catalog.

The runtime complement is :func:`check_live` — behind
``TORCHFT_PLAN_VERIFY`` every live plan is validated at its
monotone-epoch commit point (reduction plan build, serving
tree_commit, stripe resolution), counting verdicts in
``torchft_plan_verify_total{plane,verdict}`` and emitting a
``plan.verify`` flight record so ``torchft-diagnose`` can name a bad
plan (signal ``bad_plan``).  The hook OBSERVES — a rejected plan is
loud telemetry, never a wedge.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, replace
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from torchft_tpu.analysis import plan_ir as pir
from torchft_tpu.ops import topology as topo_mod

__all__ = [
    "INVARIANTS",
    "PlanViolation",
    "PlanMutation",
    "PLAN_MUTATIONS",
    "verify_plan",
    "elastic_stability",
    "explore_plans",
    "check_plan_mutation",
    "enabled",
    "check_live",
    "base_serving_ir",
    "base_reduction_ir",
    "base_stripe_ir",
]

logger = logging.getLogger(__name__)

#: Catalog order IS severity order: :func:`verify_plan` sorts its output
#: by this index, so a mutated plan's first violation names the seeded
#: bug's invariant deterministically.
INVARIANTS: Tuple[str, ...] = (
    "acyclic",
    "single-parent",
    "root-reaches-all",
    "fanout-bound",
    "full-coverage",
    "single-owner",
    "byte-conservation",
    "requant-boundary",
    "elastic-stability",
)


@dataclass(frozen=True)
class PlanViolation:
    """One named-invariant failure; ``subject`` is the node/edge/range
    the violation anchors to."""

    invariant: str
    message: str
    subject: str = ""


# ---------------------------------------------------------------------------
# The verifier
# ---------------------------------------------------------------------------


def verify_plan(ir: pir.PlanIR) -> List[PlanViolation]:
    """All invariant violations in ``ir``, ordered by the
    :data:`INVARIANTS` severity index (then discovery order).  Raises
    ``ValueError`` on a malformed IR (dangling edge endpoint, range
    outside ``[0, units)``) — that is an adapter bug, not a plan bug."""

    ids = {n.id for n in ir.nodes}
    for e in ir.edges:
        if e.src not in ids or e.dst not in ids:
            raise ValueError(f"malformed plan: edge {e.src}->{e.dst} "
                             f"references unknown node")
    for o in ir.coverage:
        if o.consumer not in ids or not 0 <= o.lo <= o.hi <= ir.units:
            raise ValueError(f"malformed plan: ownership {o} out of "
                             f"[0, {ir.units}) for {o.consumer}")

    out: List[PlanViolation] = []
    out.extend(_check_acyclic(ir))
    out.extend(_check_single_parent(ir))
    out.extend(_check_reachability(ir))
    out.extend(_check_fanout(ir))
    out.extend(_check_coverage(ir))
    out.extend(_check_bytes(ir))
    out.extend(_check_requant(ir))
    order = {name: i for i, name in enumerate(INVARIANTS)}
    out.sort(key=lambda v: order[v.invariant])
    return out


def _check_acyclic(ir: pir.PlanIR) -> List[PlanViolation]:
    # Tree edges only: the pairwise inter-leader exchange and the
    # many-to-one reduce leg are bidirectional/converging by design —
    # it is the DISTRIBUTION tree that must never chase its own tail.
    adj: Dict[str, List[str]] = {n.id: [] for n in ir.nodes}
    for e in ir.edges:
        if e.tree:
            adj[e.src].append(e.dst)
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n.id: WHITE for n in ir.nodes}
    for start in adj:
        if color[start] != WHITE:
            continue
        stack: List[Tuple[str, int]] = [(start, 0)]
        color[start] = GREY
        while stack:
            node, i = stack[-1]
            if i < len(adj[node]):
                stack[-1] = (node, i + 1)
                nxt = adj[node][i]
                if color[nxt] == GREY:
                    return [PlanViolation(
                        "acyclic",
                        f"transfer cycle through {nxt} (via {node})",
                        subject=nxt,
                    )]
                if color[nxt] == WHITE:
                    color[nxt] = GREY
                    stack.append((nxt, 0))
            else:
                color[node] = BLACK
                stack.pop()
    return []


def _check_single_parent(ir: pir.PlanIR) -> List[PlanViolation]:
    parents: Dict[str, List[str]] = {}
    for e in ir.edges:
        if e.tree:
            parents.setdefault(e.dst, []).append(e.src)
    return [
        PlanViolation(
            "single-parent",
            f"{dst} has {len(ps)} tree parents: {sorted(ps)}",
            subject=dst,
        )
        for dst, ps in sorted(parents.items())
        if len(ps) > 1
    ]


def _check_reachability(ir: pir.PlanIR) -> List[PlanViolation]:
    if not ir.roots:
        return []
    adj: Dict[str, List[str]] = {n.id: [] for n in ir.nodes}
    for e in ir.edges:
        adj[e.src].append(e.dst)
    seen = set(ir.roots)
    frontier = list(ir.roots)
    while frontier:
        node = frontier.pop()
        for nxt in adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    lost = sorted(n.id for n in ir.nodes if n.id not in seen)
    if lost:
        return [PlanViolation(
            "root-reaches-all",
            f"{len(lost)} node(s) unreachable from roots "
            f"{sorted(ir.roots)}: {lost}",
            subject=lost[0],
        )]
    return []


def _check_fanout(ir: pir.PlanIR) -> List[PlanViolation]:
    out: List[PlanViolation] = []
    degree: Dict[str, int] = {}
    for e in ir.edges:
        if e.tree:
            degree[e.src] = degree.get(e.src, 0) + 1
    for n in ir.nodes:
        bound = n.capacity if n.capacity > 0 else ir.fanout
        deg = degree.get(n.id, 0)
        if bound > 0 and deg > bound:
            out.append(PlanViolation(
                "fanout-bound",
                f"{n.id} has {deg} tree children, bound {bound}"
                + (" (capacity)" if n.capacity > 0 else " (fanout)"),
                subject=n.id,
            ))
    return out


def _check_coverage(ir: pir.PlanIR) -> List[PlanViolation]:
    out: List[PlanViolation] = []
    rows: Dict[str, List[pir.Ownership]] = {c: [] for c in ir.consumers}
    for o in ir.coverage:
        rows.setdefault(o.consumer, []).append(o)
    for consumer in ir.consumers:
        spans = sorted(
            ((o.lo, o.hi) for o in rows[consumer] if o.hi > o.lo)
        )
        pos = 0
        for lo, hi in spans:
            if lo > pos:
                out.append(PlanViolation(
                    "full-coverage",
                    f"{consumer} misses {ir.unit} range [{pos}, {lo})",
                    subject=consumer,
                ))
            elif lo < pos:
                out.append(PlanViolation(
                    "single-owner",
                    f"{consumer} receives {ir.unit} range "
                    f"[{lo}, {min(pos, hi)}) more than once",
                    subject=consumer,
                ))
            pos = max(pos, hi)
        if pos < ir.units:
            out.append(PlanViolation(
                "full-coverage",
                f"{consumer} misses {ir.unit} range [{pos}, {ir.units})",
                subject=consumer,
            ))
    return out


def _check_bytes(ir: pir.PlanIR) -> List[PlanViolation]:
    out: List[PlanViolation] = []
    inbound: Dict[str, List[int]] = {}
    for e in ir.edges:
        if e.nbytes >= 0:
            inbound.setdefault(e.dst, []).append(e.nbytes)
    boundaries = set(ir.boundaries)
    for e in ir.edges:
        if e.nbytes < 0 or e.src in boundaries:
            continue
        seen = inbound.get(e.src)
        if seen and e.nbytes not in seen:
            out.append(PlanViolation(
                "byte-conservation",
                f"{e.src}->{e.dst} ({e.hop}) sends {e.nbytes} B but "
                f"{e.src} received {sorted(set(seen))} B and is not a "
                f"boundary",
                subject=f"{e.src}->{e.dst}",
            ))
    return out


def _check_requant(ir: pir.PlanIR) -> List[PlanViolation]:
    out: List[PlanViolation] = []
    inbound: Dict[str, List[str]] = {}
    for e in ir.edges:
        if e.wire:
            inbound.setdefault(e.dst, []).append(e.wire)
    boundaries = set(ir.boundaries)
    for e in ir.edges:
        if not e.wire or e.src in boundaries:
            continue
        seen = inbound.get(e.src)
        if seen and e.wire not in seen:
            out.append(PlanViolation(
                "requant-boundary",
                f"{e.src}->{e.dst} ({e.hop}) requantizes "
                f"{sorted(set(seen))} -> {e.wire!r} but {e.src} is not "
                f"a declared boundary",
                subject=f"{e.src}->{e.dst}",
            ))
    return out


# ---------------------------------------------------------------------------
# Elastic-rerank stability (cross-plan: hosts:K under resize)
# ---------------------------------------------------------------------------


def _assignment_stability(
    assignments: Mapping[int, Mapping[int, int]],
) -> List[PlanViolation]:
    """Core check behind :func:`elastic_stability`: for every pair of
    world sizes, the common rank prefix must map to the same group in
    both — a shrink/grow must never silently reshuffle survivors."""

    out: List[PlanViolation] = []
    worlds = sorted(assignments)
    for i, wa in enumerate(worlds):
        for wb in worlds[i + 1:]:
            a, b = assignments[wa], assignments[wb]
            for rank in range(min(wa, wb)):
                if a.get(rank) != b.get(rank):
                    out.append(PlanViolation(
                        "elastic-stability",
                        f"rank {rank} moves from group {a.get(rank)} "
                        f"(world {wa}) to group {b.get(rank)} "
                        f"(world {wb}) under resize",
                        subject=f"r{rank}",
                    ))
    return out


def elastic_stability(spec: str, worlds: Iterable[int]) -> List[PlanViolation]:
    """``hosts:K`` re-rank stability across ``worlds``: the group of a
    surviving rank must not depend on the world size (contiguous
    ``r // K`` guarantees it; explicit lists are rejected at parse time
    instead — this invariant is why)."""

    assignments: Dict[int, Dict[int, int]] = {}
    for world in worlds:
        topo = topo_mod.parse_topology(spec, world)
        if topo is None:
            assignments[world] = {r: 0 for r in range(world)}
        else:
            assignments[world] = {
                r: topo.group_index(r) for r in range(world)
            }
    return _assignment_stability(assignments)


# ---------------------------------------------------------------------------
# Base plans (shared by the mutation catalog and tests)
# ---------------------------------------------------------------------------

_PAYLOAD = 1 << 20


def base_serving_ir() -> pir.PlanIR:
    """7 servers (s0 capacity-3 override), 1 publisher, fanout 2:
    s0 -> {s1,s2,s3}, s1 -> {s4,s5}, s2 -> {s6}."""
    members = [
        {"replica_id": f"s{i}", "address": f"http://s{i}:1",
         "role": "server", "capacity": 3 if i == 0 else 0,
         "version": 4}
        for i in range(7)
    ]
    members.append({"replica_id": "p0", "address": "http://p0:1",
                    "role": "publisher", "version": 5})
    doc = pir.reference_serving_plan(members, fanout=2, epoch=3)
    return pir.serving_ir(doc, payload_nbytes=_PAYLOAD)


def base_reduction_ir() -> pir.PlanIR:
    """hosts:2 over world 6: leaders r0/r2/r4, 3 row-slices."""
    topo = topo_mod.parse_topology("hosts:2", 6)
    assert topo is not None
    return pir.reduction_ir(topo, wire="int8", slice_nbytes=64)


def base_stripe_ir(num_fragments: int = 6, num_leaves: int = 17) -> pir.PlanIR:
    """4 sources (primary + 3 max-step peers) striping the round-robin
    fragment layout."""
    sources = [f"http://src{i}:1" for i in range(4)]
    return pir.stripe_ir(sources, num_fragments, num_leaves, step=7)


# ---------------------------------------------------------------------------
# Seeded plan mutations
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PlanMutation:
    """One seeded plan bug: ``catches`` is the invariant whose FIRST
    violation must name it."""

    name: str
    catches: str
    plane: str
    doc: str


PLAN_MUTATIONS: Tuple[PlanMutation, ...] = (
    PlanMutation(
        "orphan_subtree", "root-reaches-all", "serving",
        "drop an interior relay's inbound edge: its whole subtree "
        "silently stops receiving publishes",
    ),
    PlanMutation(
        "cycle_edge", "acyclic", "serving",
        "reparent a relay under its own descendant: the payload chases "
        "its own tail and never commits",
    ),
    PlanMutation(
        "two_parents", "single-parent", "serving",
        "a relay acquires a second tree parent: double pulls, "
        "non-deterministic version adoption",
    ),
    PlanMutation(
        "fanout_overflow", "fanout-bound", "serving",
        "a child lands on an already-full parent: the relay exceeds its "
        "capacity/fanout budget",
    ),
    PlanMutation(
        "requant_mid_hop", "requant-boundary", "serving",
        "a mid-tree relay changes wire format: serving hops must relay "
        "digest-verified bytes unchanged",
    ),
    PlanMutation(
        "bytes_vanish", "byte-conservation", "serving",
        "a relay forwards fewer bytes than it received without being a "
        "declared boundary",
    ),
    PlanMutation(
        "double_owner", "single-owner", "reduction",
        "a leader is assigned the same row-slice from two peer leaders: "
        "one slice accumulates twice",
    ),
    PlanMutation(
        "dropped_fragment", "full-coverage", "stripe",
        "one fragment's leaf slots vanish from the stripe assignment: "
        "the healer never receives them",
    ),
    PlanMutation(
        "stripe_gap", "full-coverage", "stripe",
        "a stripe range shrinks by one leaf: an off-by-one leaves a "
        "hole in the healed state",
    ),
    PlanMutation(
        "stripe_overlap", "single-owner", "stripe",
        "a stripe range grows into its neighbour: two sources own the "
        "same leaf slot",
    ),
    PlanMutation(
        "rerank_drift", "elastic-stability", "reduction",
        "hosts:K group assignment depends on world size: an elastic "
        "resize silently reshuffles surviving ranks across groups",
    ),
)


def _drop_edge(ir: pir.PlanIR, src: str, dst: str) -> pir.PlanIR:
    kept = tuple(
        e for e in ir.edges if not (e.src == src and e.dst == dst)
    )
    if len(kept) == len(ir.edges):
        raise AssertionError(f"mutation expected edge {src}->{dst}")
    return replace(ir, edges=kept)


def _rewire(ir: pir.PlanIR, src: str, dst: str, **changes: Any) -> pir.PlanIR:
    edges = []
    hit = False
    for e in ir.edges:
        if e.src == src and e.dst == dst:
            e = replace(e, **changes)
            hit = True
        edges.append(e)
    if not hit:
        raise AssertionError(f"mutation expected edge {src}->{dst}")
    return replace(ir, edges=tuple(edges))


def check_plan_mutation(name: str) -> List[PlanViolation]:
    """Apply one seeded plan bug to its base plan and return the
    verifier's (ordered) violations — the gate asserts the first names
    ``catches``."""

    if name == "orphan_subtree":
        return verify_plan(_drop_edge(base_serving_ir(), "s0", "s1"))
    if name == "cycle_edge":
        ir = _drop_edge(base_serving_ir(), "s0", "s1")
        return verify_plan(replace(ir, edges=ir.edges + (
            pir.PlanEdge("s4", "s1", "serving.relay", "frag", tree=True,
                         nbytes=_PAYLOAD),
        )))
    if name == "two_parents":
        ir = base_serving_ir()
        return verify_plan(replace(ir, edges=ir.edges + (
            pir.PlanEdge("s3", "s4", "serving.relay", "frag", tree=True,
                         nbytes=_PAYLOAD),
        )))
    if name == "fanout_overflow":
        ir = _drop_edge(base_serving_ir(), "s2", "s6")
        return verify_plan(replace(ir, edges=ir.edges + (
            pir.PlanEdge("s1", "s6", "serving.relay", "frag", tree=True,
                         nbytes=_PAYLOAD),
        )))
    if name == "requant_mid_hop":
        return verify_plan(
            _rewire(base_serving_ir(), "s1", "s4", wire="fp8")
        )
    if name == "bytes_vanish":
        return verify_plan(
            _rewire(base_serving_ir(), "s2", "s6", nbytes=_PAYLOAD // 2)
        )
    if name == "double_owner":
        ir = base_reduction_ir()
        return verify_plan(replace(ir, coverage=ir.coverage + (
            pir.Ownership("r0", 1, 2, via="r4"),
        )))
    if name == "dropped_fragment":
        ir = base_stripe_ir()
        victim = ir.coverage[0].via  # the primary's nominal fragment 0
        return verify_plan(replace(ir, coverage=tuple(
            o for o in ir.coverage if o.via != victim
        )))
    if name == "stripe_gap":
        ir = base_stripe_ir(num_fragments=1)  # one contiguous run
        o = ir.coverage[0]
        return verify_plan(replace(ir, coverage=(
            replace(o, hi=o.hi - 1),
        ) + ir.coverage[1:]))
    if name == "stripe_overlap":
        ir = base_stripe_ir()
        last = ir.coverage[-1]
        return verify_plan(replace(ir, coverage=ir.coverage[:-1] + (
            replace(last, hi=last.hi + 1),
        )))
    if name == "rerank_drift":
        # a (buggy) assignment that depends on world size: ranks shift
        # by one group on grow — exactly what hosts:K must never do
        return _assignment_stability({
            4: {r: r // 2 for r in range(4)},
            6: {r: (r + 1) // 2 for r in range(6)},
        })
    raise KeyError(f"unknown plan mutation {name!r}")


# ---------------------------------------------------------------------------
# Exhaustive enumeration: small worlds x topologies x churn
# ---------------------------------------------------------------------------


def _serving_members(
    n_servers: int, n_pubs: int, caps: Mapping[int, int]
) -> List[Dict[str, Any]]:
    members: List[Dict[str, Any]] = [
        {"replica_id": f"s{i}", "address": f"http://s{i}:1",
         "role": "server", "capacity": caps.get(i, 0), "version": 3}
        for i in range(n_servers)
    ]
    for j in range(n_pubs):
        members.append({"replica_id": f"p{j}", "address": f"http://p{j}:1",
                        "role": "publisher", "version": 5 + j})
    return members


def explore_plans() -> Dict[str, Any]:
    """Enumerate every small-world plan on all three planes (plus churn
    and failover variants) and verify each.  Returns ``{"plans": N,
    "violations": [...]}`` — the gate requires an empty list."""

    plans = 0
    violations: List[PlanViolation] = []

    def _verify(ir: pir.PlanIR) -> None:
        nonlocal plans
        plans += 1
        violations.extend(verify_plan(ir))

    # -- reduction: hosts:K and explicit groups over worlds 1..8
    for world in range(1, 9):
        for k in range(1, 5):
            topo = topo_mod.parse_topology(f"hosts:{k}", world)
            if topo is not None:
                _verify(pir.reduction_ir(topo, slice_nbytes=64))
    for spec, world in (
        ("0,1;2,3", 4), ("0,2;1,3", 4), ("0;1;2", 3),
        ("1,2,0;3,4", 5), ("0,1,2,3;4,5;6,7", 8),
    ):
        topo = topo_mod.parse_topology(spec, world)
        if topo is not None:
            _verify(pir.reduction_ir(topo, slice_nbytes=64))
    # elastic resize stability of the adaptive grammar
    for k in range(1, 5):
        plans += 1
        violations.extend(elastic_stability(f"hosts:{k}", range(1, 9)))

    # -- serving: membership x fanout x capacity override x publishers,
    # plus drop-one churn resynthesis (sorted order is stable under
    # churn, so the re-plan must verify too)
    for n in range(0, 7):
        cap_patterns: List[Dict[int, int]] = [{}]
        if n >= 1:
            cap_patterns.append({0: 1})
        if n >= 2:
            cap_patterns.append({1: 5})
        for fanout in (1, 2, 3):
            for caps in cap_patterns:
                for n_pubs in (0, 1, 2):
                    members = _serving_members(n, n_pubs, caps)
                    doc = pir.reference_serving_plan(members, fanout)
                    _verify(pir.serving_ir(doc, payload_nbytes=_PAYLOAD))
    for n in (3, 5):
        members = _serving_members(n, 1, {})
        for dropped in range(n):
            churned = [
                m for m in members if m["replica_id"] != f"s{dropped}"
            ]
            doc = pir.reference_serving_plan(churned, 2)
            _verify(pir.serving_ir(doc, payload_nbytes=_PAYLOAD))

    # -- stripe: sources x fragments x leaves, plus per-source failover
    for nsrc in range(1, 6):
        sources = [f"http://src{i}:1" for i in range(nsrc)]
        for nfrag in (1, 2, 3, 5, 8):
            for leaves in (1, 2, 3, 5, 8, 13):
                ir = pir.stripe_ir(sources, nfrag, leaves)
                _verify(ir)
                for dead in sources[1:]:
                    _verify(pir.stripe_reassign(ir, dead))

    return {"plans": plans, "violations": violations}


# ---------------------------------------------------------------------------
# Runtime hook: TORCHFT_PLAN_VERIFY
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """Live-plan validation armed?  Call sites gate IR construction on
    this so the default path pays one env read, nothing else."""
    from torchft_tpu.utils.env import env_bool

    return env_bool("TORCHFT_PLAN_VERIFY", False)


def check_live(ir: pir.PlanIR) -> Optional[PlanViolation]:
    """Validate one live plan at its commit point.  Observe-only: a
    rejection increments ``torchft_plan_verify_total{plane,
    verdict="reject"}``, lands a ``plan.verify`` flight record (the
    ``bad_plan`` diagnose signal), and logs at ERROR — it never raises
    into the committing path (degrade loudly, never wedge).  Returns
    the first violation for callers that want to surface it."""

    from torchft_tpu.utils import flightrecorder as _flightrec
    from torchft_tpu.utils import metrics as _metrics

    try:
        violations = verify_plan(ir)
    except Exception as e:  # noqa: BLE001 - adapter bug must not wedge
        logger.exception("plan verifier errored on %s plan: %s", ir.plane, e)
        _metrics.PLAN_VERIFY_TOTAL.labels(
            plane=ir.plane, verdict="error"
        ).inc()
        return None
    first = violations[0] if violations else None
    verdict = "reject" if first else "accept"
    _metrics.PLAN_VERIFY_TOTAL.labels(plane=ir.plane, verdict=verdict).inc()
    _flightrec.RECORDER.record(
        "plan.verify",
        status="error" if first else "ok",
        step=ir.epoch,
        plane=ir.plane,
        verdict=verdict,
        invariant=first.invariant if first else "",
        detail=first.message if first else "",
    )
    if first:
        logger.error(
            "rejected live %s plan (epoch %s): %s violated — %s",
            ir.plane, ir.epoch, first.invariant, first.message,
        )
    return first
