"""Pass ``env-hygiene``: every environment knob goes through
``utils/env.py``, is ``TORCHFT_*``-named, and is documented.

The failure mode this kills: PR N adds ``os.environ.get("TORCHFT_FOO")``
deep in a transport, nothing documents it, and six months later a
production run depends on a knob no operator can discover and whose
garbage-value behavior (crash? silent default?) nobody decided.  The
shared helpers (``env_str``/``env_int``/``env_float``/``env_bool``)
decide the garbage policy once; this pass makes them the only door:

- ``direct-env-read``: ``os.environ[...]`` / ``os.environ.get`` /
  ``os.getenv`` reads anywhere outside ``utils/env.py``.  Writes
  (``os.environ["X"] = ...`` for child-env propagation) are allowed.
- ``non-torchft-knob``: a helper read of a name that is neither
  ``TORCHFT_*`` nor a known external (``OTEL_*`` standard vars, the
  scheduler/JAX identity vars RANK/WORLD_SIZE/...).
- ``undocumented-knob``: a ``TORCHFT_*`` helper read whose name appears
  nowhere in the docs corpus (README.md + docs/*.md) — the knob tables
  in docs/observability.md, docs/robustness.md, and
  docs/static_analysis.md are the expected homes.

Helper first-arguments are resolved through module-level string
constants (``env_str(SOME_CONST)``); dynamic names are skipped — the
pass polices the declarative form, which is also the greppable one.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    QualnameVisitor,
    SelftestError,
    const_str,
    dotted,
    module_str_constants,
)

PASS_ID = "env-hygiene"

_HELPERS = ("env_str", "env_int", "env_float", "env_bool")

# Non-TORCHFT names the helpers may legitimately read: OTEL standard
# exporter config, scheduler-injected identity, and JAX/XLA platform vars.
_EXTERNAL_PREFIXES: "Tuple[str, ...]" = ("OTEL_",)
_EXTERNAL_NAMES: "Tuple[str, ...]" = (
    "RANK",
    "WORLD_SIZE",
    "JOB_ID",
    "LOGLEVEL",
    "REPLICA_GROUP_ID",
    "NUM_REPLICA_GROUPS",
    "XLA_FLAGS",
    "JAX_PLATFORMS",
)

# The helper module itself is the one sanctioned direct reader.
_EXEMPT_FILE_SUFFIX = "utils/env.py"


def _is_env_read(node: ast.AST) -> "str | None":
    """Describe a direct env read at this node, or None.

    Matches ``os.environ[...]`` loads, ``os.environ.get(...)``,
    ``os.environ.setdefault(...)`` (read-or-write counts: the read leg
    decides behavior), and ``os.getenv(...)``.
    """
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted(node.value).endswith("os.environ"):
            return "os.environ[...]"
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name.endswith("os.environ.get") or name.endswith("os.environ.setdefault"):
            return name[name.index("os.") :]
        if name.endswith("os.getenv"):
            return "os.getenv"
    return None


class _Visitor(QualnameVisitor):
    def __init__(self, project: Project, path: str, consts: "dict") -> None:
        super().__init__()
        self.project = project
        self.path = path
        self.consts = consts
        self.findings: "List[Finding]" = []
        self.torchft_knobs: "List[Tuple[str, int, str]]" = []  # (name, line, qual)

    def _resolve(self, arg: "ast.AST | None") -> "str | None":
        val = const_str(arg)
        if val is not None:
            return val
        if isinstance(arg, ast.Name):
            return self.consts.get(arg.id)
        return None

    def visit_Subscript(self, node: ast.Subscript) -> None:  # noqa: N802
        kind = _is_env_read(node)
        if kind:
            self._flag_direct(node, kind)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        kind = _is_env_read(node)
        if kind:
            self._flag_direct(node, kind)
        func = dotted(node.func)
        if func.rsplit(".", 1)[-1] in _HELPERS and node.args:
            name = self._resolve(node.args[0])
            if name is not None:
                self._check_knob(name, node.lineno)
        self.generic_visit(node)

    def _flag_direct(self, node: ast.AST, kind: str) -> None:
        self.findings.append(
            Finding(
                pass_id=PASS_ID,
                code="direct-env-read",
                file=self.project.rel(self.path),
                line=node.lineno,
                symbol=self.qualname,
                message=(
                    f"{kind} read outside utils/env.py — use "
                    f"env_str/env_int/env_float/env_bool so garbage values "
                    f"warn-and-default and the knob is lintable"
                ),
            )
        )

    def _check_knob(self, name: str, line: int) -> None:
        if name.startswith("TORCHFT_"):
            self.torchft_knobs.append((name, line, self.qualname))
            return
        if name.startswith(_EXTERNAL_PREFIXES) or name in _EXTERNAL_NAMES:
            return
        self.findings.append(
            Finding(
                pass_id=PASS_ID,
                code="non-torchft-knob",
                file=self.project.rel(self.path),
                line=line,
                symbol=name,
                message=(
                    f"env knob {name!r} is neither TORCHFT_*-prefixed nor a "
                    f"known external var — namespace it or add it to the "
                    f"pass's external allowlist with a reason"
                ),
            )
        )


def run(project: Project) -> "Iterable[Finding]":
    out: "List[Finding]" = []
    docs = project.docs_text()
    for path in project.py_files:
        if path.replace("\\", "/").endswith(_EXEMPT_FILE_SUFFIX):
            continue
        tree = project.tree(path)
        if tree is None:
            continue
        visitor = _Visitor(project, path, module_str_constants(tree))
        visitor.visit(tree)
        out.extend(visitor.findings)
        for name, line, qual in visitor.torchft_knobs:
            if name not in docs:
                out.append(
                    Finding(
                        pass_id=PASS_ID,
                        code="undocumented-knob",
                        file=project.rel(path),
                        line=line,
                        symbol=name,
                        message=(
                            f"env knob {name!r} is read here but appears in "
                            f"no docs table (README.md / docs/*.md) — add it "
                            f"to the env-knob table"
                        ),
                    )
                )
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

_BAD = {
    "direct-read": 'import os\nx = os.environ.get("TORCHFT_FOO", "1")\n',
    "direct-subscript": 'import os\nx = os.environ["TORCHFT_FOO"]\n',
    "getenv": 'import os\nx = os.getenv("TORCHFT_FOO")\n',
    "non-torchft": (
        "from torchft_tpu.utils.env import env_str\n"
        'x = env_str("MY_RANDOM_KNOB")\n'
    ),
    "undocumented": (
        "from torchft_tpu.utils.env import env_int\n"
        'x = env_int("TORCHFT_UNDOCUMENTED_THING", 1)\n'
    ),
}

_GOOD = {
    "write-allowed": 'import os\nos.environ["TORCHFT_FOO"] = "1"\n',
    "helper-documented": (
        "from torchft_tpu.utils.env import env_int\n"
        'x = env_int("TORCHFT_DOCUMENTED_THING", 1)\n'
    ),
    "external-allowlisted": (
        "from torchft_tpu.utils.env import env_str\n"
        'x = env_str("OTEL_EXPORTER_OTLP_ENDPOINT")\n'
    ),
    "const-resolution": (
        "from torchft_tpu.utils.env import env_str\n"
        'KNOB = "TORCHFT_DOCUMENTED_THING"\n'
        "x = env_str(KNOB)\n"
    ),
}


def _run_on_source(src: str) -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        os.makedirs(os.path.join(td, "docs"))
        with open(os.path.join(td, "docs", "knobs.md"), "w", encoding="utf-8") as fh:
            fh.write("| `TORCHFT_DOCUMENTED_THING` | a documented knob |\n")
        path = os.path.join(td, "snippet.py")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)
        return list(run(Project(td, [path])))


def selftest() -> None:
    for name, src in _BAD.items():
        if not _run_on_source(src):
            raise SelftestError(f"{PASS_ID}: bad snippet {name!r} not flagged")
    for name, src in _GOOD.items():
        got = _run_on_source(src)
        if got:
            raise SelftestError(
                f"{PASS_ID}: good snippet {name!r} falsely flagged: "
                f"{[f.render() for f in got]}"
            )


PASS = LintPass(
    id=PASS_ID,
    doc="env reads go through utils/env.py helpers, are TORCHFT_*-named "
    "(or allowlisted externals), and appear in the docs knob tables",
    run=run,
    selftest=selftest,
)
