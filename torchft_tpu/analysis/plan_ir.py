"""tft-plan: one IR for every "who talks to whom" decision (ISSUE 19).

Three live subsystems independently derive peer-communication
structure — reduction plans (:mod:`torchft_tpu.ops.topology`, the
4-hop hierarchy), serving trees (the native lighthouse's BFS in
``native/lighthouse.cc``), and heal stripe assignment (first-K roster
order feeding :func:`torchft_tpu.checkpointing.fragments.striped_fetch`).
None of their outputs were machine-checked, even though a malformed
plan silently drops fragments, orphans subtrees, or double-owns a
slice.  This module is the common *Plan IR* those subsystems adapt
into, and the contract ROADMAP item 4's synthesizer will emit directly:

- :class:`PlanNode` — a participant (host, role, per-node capacity);
- :class:`PlanEdge` — one directed transfer (hop kind, wire format,
  tree membership, payload bytes);
- :class:`Ownership` — one half-open ``[lo, hi)`` unit range a consumer
  receives *via* a named producer ("" = produced locally);
- :class:`PlanIR` — the whole plan: plane name, monotone epoch, the
  unit the coverage ranges count (slices / leaves / payloads), nodes,
  edges, coverage, roots, consumers, requant boundaries, fanout bound.

The three adapters (:func:`reduction_ir`, :func:`serving_ir`,
:func:`stripe_ir`) express each subsystem's live plan as IR;
:mod:`torchft_tpu.analysis.plan_verify` asserts the named invariants
over any IR regardless of which plane produced it.
:func:`reference_serving_plan` is the pure-Python mirror of the native
BFS slot-queue (``rpc_serving_plan``) so C++ and Python can never
drift on tree shape — the cross-language parity test pins them to each
other.  :func:`stripe_roster` / :func:`stripe_source_cohort` are the
one copy of the first-K roster math ``manager.py`` previously inlined
twice.

Everything here is stdlib-only and import-light: the lint/verify tier
and the live runtime hooks both load it, and a plan is validated in
microseconds (worlds are small; the IR is tuples of frozen
dataclasses).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Iterable, List, Mapping, Sequence, Tuple

from torchft_tpu.ops import topology as topo_mod

__all__ = [
    "PlanNode",
    "PlanEdge",
    "Ownership",
    "PlanIR",
    "reduction_ir",
    "serving_ir",
    "stripe_ir",
    "stripe_reassign",
    "reference_serving_plan",
    "stripe_roster",
    "stripe_source_cohort",
    "LINK_SNAPSHOT_FIELDS",
    "LINK_ROW_KEYS",
]


# ---------------------------------------------------------------------------
# The IR proper
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PlanNode:
    """One plan participant.

    ``capacity`` is the per-node tree fan-out override (0 = use the
    plan-wide :attr:`PlanIR.fanout`; both 0 = unbounded)."""

    id: str
    host: str = ""
    role: str = ""
    capacity: int = 0


@dataclass(frozen=True)
class PlanEdge:
    """One directed transfer ``src -> dst``.

    ``hop`` is the schedule stage (``intra.reduce``, ``serving.relay``,
    ``heal.stripe``, ...); ``wire`` the on-the-wire format crossing this
    edge; ``tree`` marks edges that form the plan's distribution tree
    (single-parent / fanout invariants apply to tree edges only —
    pairwise exchange legs are not tree edges); ``nbytes`` the payload
    size when known (-1 = unknown, byte-conservation skips it)."""

    src: str
    dst: str
    hop: str
    wire: str = ""
    tree: bool = False
    nbytes: int = -1


@dataclass(frozen=True)
class Ownership:
    """Consumer ``consumer`` receives units ``[lo, hi)`` via node
    ``via`` ("" = produced locally, no wire involved)."""

    consumer: str
    lo: int
    hi: int
    via: str = ""


@dataclass(frozen=True)
class PlanIR:
    """A complete, verifiable communication plan.

    ``unit`` names what the coverage ranges count (``slice`` for
    reduction row-slices, ``leaf`` for heal stripe leaf slots,
    ``payload`` for the serving tree's single artifact); ``units`` is
    the total range ``[0, units)`` every consumer must end up owning
    exactly once.  ``roots`` are the nodes data originates from for the
    reachability invariant; ``consumers`` the nodes the coverage map
    must satisfy; ``boundaries`` the nodes allowed to change wire
    format (DynamiQ's requant-at-boundaries); ``fanout`` the plan-wide
    tree fan-out bound (0 = unbounded)."""

    plane: str
    epoch: int
    unit: str
    units: int
    nodes: Tuple[PlanNode, ...]
    edges: Tuple[PlanEdge, ...]
    coverage: Tuple[Ownership, ...]
    roots: Tuple[str, ...] = ()
    consumers: Tuple[str, ...] = ()
    boundaries: Tuple[str, ...] = ()
    fanout: int = 0

    def node(self, node_id: str) -> PlanNode:
        for n in self.nodes:
            if n.id == node_id:
                return n
        raise KeyError(node_id)


# ---------------------------------------------------------------------------
# Adapter 1: reduction plans (ops/topology.synthesize_plan)
# ---------------------------------------------------------------------------


def reduction_ir(
    topo: "topo_mod.Topology",
    *,
    epoch: int = 0,
    wire: str = "int8",
    slice_nbytes: int = -1,
) -> PlanIR:
    """The fleet-wide view of :func:`topology.synthesize_plan`.

    Per-rank plans are rank-local hop schedules; the IR is the union of
    every rank's sends as directed edges, with the coverage map stating
    how each rank ends up holding ALL ``n_groups`` reduced row-slices:
    leaders reduce their own slice locally, gather the others from peer
    leaders, and members receive the whole bundle over the broadcast
    edge.  Only ``intra.bcast`` is a distribution-TREE edge — the
    ``intra.reduce`` leg is a many-to-one gather and the inter-leader
    exchange is pairwise-bidirectional by design, so the tree
    invariants (acyclic / single-parent / fanout) deliberately do not
    apply to them.  Leaders are the requant boundaries (hop-boundary
    requant is theirs by construction; the wire format is fleet-uniform
    today — per-hop wires arrive with the ROADMAP item 5 synthesizer)."""

    n = topo.world
    groups = topo.n_groups
    leaders = topo.leaders()

    def rid(rank: int) -> str:
        return f"r{rank}"

    nodes = tuple(
        PlanNode(
            id=rid(r),
            host=f"g{topo.group_index(r)}",
            role="leader" if r in leaders else "member",
        )
        for r in range(n)
    )

    edges: List[PlanEdge] = []
    total = slice_nbytes * groups if slice_nbytes >= 0 else -1
    for gidx in range(groups):
        lead = topo.leader(gidx)
        for m in topo.members(gidx):
            edges.append(
                PlanEdge(rid(m), rid(lead), "intra.reduce", wire,
                         tree=False, nbytes=total)
            )
        plan = topo_mod.synthesize_plan(topo, lead)
        for hop in plan.hops:
            if hop.name in ("inter.exchange", "inter.gather"):
                for peer in hop.sends:
                    edges.append(
                        PlanEdge(rid(lead), rid(peer), hop.name, wire,
                                 tree=False, nbytes=slice_nbytes)
                    )
        for m in topo.members(gidx):
            edges.append(
                PlanEdge(rid(lead), rid(m), "intra.bcast", wire,
                         tree=True, nbytes=total)
            )

    coverage: List[Ownership] = []
    for gidx in range(groups):
        lead = topo.leader(gidx)
        for h in range(groups):
            coverage.append(
                Ownership(rid(lead), h, h + 1,
                          via="" if h == gidx else rid(topo.leader(h)))
            )
        for m in topo.members(gidx):
            coverage.append(Ownership(rid(m), 0, groups, via=rid(lead)))

    return PlanIR(
        plane="reduction",
        epoch=epoch,
        unit="slice",
        units=groups,
        nodes=nodes,
        edges=tuple(edges),
        coverage=tuple(coverage),
        # rank 0 is always its group's leader (leader = min rank);
        # member -> leader -> all leaders -> their members covers the
        # whole digraph from this single origin.
        roots=(rid(0),),
        consumers=tuple(rid(r) for r in range(n)),
        boundaries=tuple(rid(lv) for lv in leaders),
        fanout=0,
    )


# ---------------------------------------------------------------------------
# Adapter 2: serving trees (native lighthouse rpc_serving_plan)
# ---------------------------------------------------------------------------


def serving_ir(
    doc: Mapping[str, Any],
    *,
    payload_nbytes: int = -1,
    wire: str = "frag",
) -> PlanIR:
    """Express a ``serving_plan`` document (native BFS output, or the
    :func:`reference_serving_plan` mirror) as IR.

    Servers form the relay tree (parent address -> child); publishers
    are the roots, with a ``serving.source`` edge from the max-version
    publisher to the parentless server.  The serving plane never
    requantizes (every hop relays the same digest-verified fragment
    bytes), so ``boundaries`` is empty and ``wire`` is uniform."""

    raw_nodes = list(doc.get("nodes") or [])
    raw_pubs = list(doc.get("publishers") or [])
    fanout = int(doc.get("fanout") or 0)
    root_source = str(doc.get("root_source") or "")

    nodes: List[PlanNode] = []
    by_addr: Dict[str, str] = {}
    for rn in raw_nodes:
        nid = str(rn["replica_id"])
        addr = str(rn.get("address") or "")
        nodes.append(
            PlanNode(id=nid, host=addr, role="server",
                     capacity=int(rn.get("capacity") or 0))
        )
        by_addr[addr] = nid
    pub_ids: Dict[str, str] = {}
    for rp in raw_pubs:
        pid = f"pub:{rp['replica_id']}"
        addr = str(rp.get("address") or "")
        nodes.append(PlanNode(id=pid, host=addr, role="publisher"))
        pub_ids[addr] = pid

    edges: List[PlanEdge] = []
    coverage: List[Ownership] = []
    consumers: List[str] = []
    for rn in raw_nodes:
        nid = str(rn["replica_id"])
        consumers.append(nid)
        parent_addr = str(rn.get("parent") or "")
        if parent_addr:
            edges.append(
                PlanEdge(by_addr[parent_addr], nid, "serving.relay", wire,
                         tree=True, nbytes=payload_nbytes)
            )
            coverage.append(Ownership(nid, 0, 1, via=by_addr[parent_addr]))
        elif root_source and root_source in pub_ids:
            edges.append(
                PlanEdge(pub_ids[root_source], nid, "serving.source", wire,
                         tree=True, nbytes=payload_nbytes)
            )
            coverage.append(Ownership(nid, 0, 1, via=pub_ids[root_source]))
        else:
            # no publisher yet: the root server holds whatever it has
            coverage.append(Ownership(nid, 0, 1, via=""))

    if pub_ids:
        roots: Tuple[str, ...] = tuple(pub_ids[a] for a in sorted(pub_ids))
    else:
        roots = tuple(
            str(rn["replica_id"])
            for rn in raw_nodes
            if not str(rn.get("parent") or "")
        )

    return PlanIR(
        plane="serving",
        epoch=int(doc.get("epoch") or 0),
        unit="payload",
        units=1,
        nodes=tuple(nodes),
        edges=tuple(edges),
        coverage=tuple(coverage),
        roots=roots,
        consumers=tuple(consumers),
        boundaries=(),
        fanout=fanout,
    )


def reference_serving_plan(
    members: Iterable[Mapping[str, Any]],
    fanout: int,
    *,
    epoch: int = 0,
) -> Dict[str, Any]:
    """Pure-Python mirror of the native lighthouse's BFS slot-queue
    (``rpc_serving_plan`` in ``native/lighthouse.cc``).

    ``members`` carry ``replica_id`` / ``address`` / ``role`` and
    optional ``capacity`` / ``version`` / ``version_ms``.  Iteration is
    replica_id order (the native side walks a ``std::map``), node i's
    parent is the earliest node with a free child slot (per-node
    capacity, else ``fanout``), and the root source is the max-version
    publisher with first-in-order winning ties (strict ``>``).  The
    cross-language parity test pins this function to the native output
    — change one side and tier-1 breaks."""

    ordered = sorted(members, key=lambda m: str(m["replica_id"]))
    servers = [m for m in ordered if str(m.get("role") or "") != "publisher"]
    publishers = [m for m in ordered if str(m.get("role") or "") == "publisher"]

    root_source = ""
    root_version = -1
    pubs_out: List[Dict[str, Any]] = []
    for p in publishers:
        version = int(p.get("version") or 0)
        pubs_out.append(
            {
                "replica_id": str(p["replica_id"]),
                "address": str(p.get("address") or ""),
                "version": version,
                "version_ms": int(p.get("version_ms") or 0),
            }
        )
        if version > root_version:
            root_version = version
            root_source = str(p.get("address") or "")

    n = len(servers)
    depth = [0] * n
    children = [0] * n
    parent = [""] * n
    # BFS slot queue: (server index, remaining child slots)
    slots: List[List[int]] = []
    head = 0
    for i in range(n):
        cap = int(servers[i].get("capacity") or 0)
        cap = cap if cap > 0 else fanout
        if i > 0:
            while head < len(slots) and slots[head][1] <= 0:
                head += 1
            if head < len(slots):
                pi = slots[head][0]
                slots[head][1] -= 1
                parent[i] = str(servers[pi].get("address") or "")
                depth[i] = depth[pi] + 1
                children[pi] += 1
        slots.append([i, cap])

    nodes_out: List[Dict[str, Any]] = []
    for i in range(n):
        nodes_out.append(
            {
                "replica_id": str(servers[i]["replica_id"]),
                "address": str(servers[i].get("address") or ""),
                "parent": parent[i],
                "depth": depth[i],
                "children": children[i],
                "capacity": int(servers[i].get("capacity") or 0),
                "version": int(servers[i].get("version") or 0),
            }
        )
    return {
        "epoch": epoch,
        "fanout": fanout,
        "root_source": root_source,
        "publishers": pubs_out,
        "nodes": nodes_out,
        "depth": max(depth) if depth else 0,
    }


# ---------------------------------------------------------------------------
# Adapter 3: heal stripe assignment (checkpointing striped fetch)
# ---------------------------------------------------------------------------


def stripe_roster(
    participants: Sequence[Any],
    max_step: int,
    primary_index: int,
    max_sources: int,
) -> List[str]:
    """The healer's stripe-candidate pick: addresses of the first
    ``max_sources - 1`` max-step roster entries beyond the primary, in
    replica-rank order.  The ONE copy of the math ``manager.py``'s
    ``_resolve_stripe_sources`` and the IR adapter both consume — the
    healer and the verifier can not disagree on who stripes."""

    out: List[str] = []
    for i, p in enumerate(participants):
        if not isinstance(p, dict):
            continue
        if i == primary_index:
            continue
        if p.get("step", -1) != max_step:
            continue
        addr = str(p.get("address") or "")
        if addr:
            out.append(addr)
        if len(out) >= max_sources - 1:
            break
    return out

def stripe_source_cohort(
    participants: Sequence[Any],
    max_step: int,
    max_sources: int,
) -> List[str]:
    """Replica ids of the first ``max_sources`` max-step participants in
    roster order — the superset any healer's :func:`stripe_roster` pick
    can reach, computed identically on every peer (the source side's
    "should I stage fragments?" test)."""

    out: List[str] = []
    for p in participants:
        if not isinstance(p, dict) or p.get("step") != max_step:
            continue
        out.append(str(p.get("replica_id") or ""))
        if len(out) >= max_sources:
            break
    return out


def _fragment_slot_runs(
    frag_index: int, num_leaves: int, num_fragments: int
) -> List[Tuple[int, int]]:
    """Fragment ``frag_index``'s round-robin leaf slots
    (``serialization.split_chunks`` layout: slot s belongs to fragment
    ``s % num_fragments``) as half-open runs."""

    slots = list(range(frag_index, num_leaves, num_fragments))
    runs: List[Tuple[int, int]] = []
    for s in slots:
        if runs and runs[-1][1] == s:
            runs[-1] = (runs[-1][0], s + 1)
        else:
            runs.append((s, s + 1))
    return runs


def stripe_ir(
    sources: Sequence[str],
    num_fragments: int,
    num_leaves: int,
    *,
    step: int = 0,
    healer: str = "healer",
) -> PlanIR:
    """The striped heal receive as IR.

    ``sources[0]`` is the PRIMARY (its manifest defines truth); every
    source holds bitwise-replicated state, so the live fetch runs a
    dynamic work queue.  The IR records the *nominal* static assignment
    the queue starts from — fragment f via ``sources[f % len(sources)]``
    — which is exactly the coverage contract the dynamic schedule must
    preserve under failover (:func:`stripe_reassign` models a source
    death).  Coverage unit is the global leaf slot; fragment f owns the
    round-robin slot set ``range(f, num_leaves, num_fragments)``."""

    if not sources:
        raise ValueError("stripe plan: no sources")
    srcs = [str(s) for s in sources]
    nodes = [
        PlanNode(id=s, host=s, role="primary" if i == 0 else "source")
        for i, s in enumerate(srcs)
    ]
    nodes.append(PlanNode(id=healer, role="healer"))
    edges = tuple(
        PlanEdge(s, healer, "heal.primary" if i == 0 else "heal.stripe",
                 "frag", tree=(i == 0))
        for i, s in enumerate(srcs)
    )
    coverage: List[Ownership] = []
    for f in range(num_fragments):
        via = srcs[f % len(srcs)]
        for lo, hi in _fragment_slot_runs(f, num_leaves, num_fragments):
            coverage.append(Ownership(healer, lo, hi, via=via))
    return PlanIR(
        plane="stripe",
        epoch=step,
        unit="leaf",
        units=num_leaves,
        nodes=tuple(nodes),
        edges=edges,
        coverage=tuple(coverage),
        roots=tuple(srcs),
        consumers=(healer,),
        boundaries=(),
        fanout=0,
    )


def stripe_reassign(ir: PlanIR, dead: str) -> PlanIR:
    """Model per-fragment failover: source ``dead``'s coverage moves to
    the primary (``roots[0]``), its edge drops.  The result must still
    verify — that is the failover property test."""

    primary = ir.roots[0]
    if dead == primary:
        raise ValueError("the primary cannot fail over to itself")
    return replace(
        ir,
        nodes=tuple(n for n in ir.nodes if n.id != dead),
        edges=tuple(e for e in ir.edges if dead not in (e.src, e.dst)),
        coverage=tuple(
            replace(o, via=primary) if o.via == dead else o
            for o in ir.coverage
        ),
        roots=tuple(r for r in ir.roots if r != dead),
    )


# ---------------------------------------------------------------------------
# Frozen synthesizer input contract: LinkMatrix.snapshot()
# ---------------------------------------------------------------------------

#: Field names of ``utils.linkstats.LinkStat`` — the in-process snapshot
#: row the future plan synthesizer (ROADMAP item 4) consumes.  A rename
#: breaks tests/test_linkstats.py's contract gate, not the synthesizer.
LINK_SNAPSHOT_FIELDS: Tuple[str, ...] = (
    "peer",
    "plane",
    "local",
    "goodput_bps",
    "rtt_p50_ms",
    "rtt_p99_ms",
    "samples",
    "bytes_total",
    "age_s",
)

#: Key names of ``LinkStat.to_dict()`` — the `/links.json` wire row the
#: lighthouse aggregates fleet-wide (note the deliberate short names:
#: ``rtt_ms`` carries the p50, ``bytes`` the byte total).
LINK_ROW_KEYS: Tuple[str, ...] = (
    "peer",
    "plane",
    "local",
    "goodput_bps",
    "rtt_ms",
    "rtt_p99_ms",
    "samples",
    "bytes",
    "age_s",
)
